"""The three control-plane protocol models ``hvd-model`` explores.

Each builder returns a :class:`~.model.Model` whose actions execute
the SAME spec functions the runtime does (journal_spec / lease_spec /
migration_spec) — the spec-is-implementation contract means checking
these models checks shipped transition code, with the harness adding
only the environment: crash/restart, message loss, duplication, and
reorder as fault actions enabled at every step.

Seeded bugs (the mutation proof, tests/test_protocol_model.py +
scripts/ci_lint.sh): each builder takes ``bug=`` re-introducing one
historical failure shape — the checker must produce a minimized
counterexample for every mutant while the shipped (bug=None) models
explore their full bounded state space with zero counterexamples.

- ``ha``:        ``skip_fence`` — a resurrected stale primary writes
  without the term fence (split-brain).
- ``lease``:     ``actuate_before_ledger`` — actuation issued before
  the durable ledger write (the fence-skip ordering bug; a crash in
  the window strands an actuation the recovery protocol rolls back).
- ``migration``: ``double_import`` — staging keeps a completed
  transfer's entry (the missing dedup delete), so a duplicated chunk
  reassembles and imports the sequence twice; ``skip_admit`` — import
  placement skips the watermark admission predicate.
"""

import copy

from . import journal_spec, lease_spec, migration_spec
from .model import Action, Model, _anchor


def _insert_sorted(lst, value):
    """Idempotent membership insert keeping the list canonical."""
    if value not in lst:
        lst.append(value)
        lst.sort()


# ==========================================================================
# HA terms: journal, standby sync, promotion, the term fence
# ==========================================================================

def ha_model(bug=None, max_writes=2):
    """Primary p1 journals durable mutations; a warm standby syncs the
    journal and promotes at term+1 once p1 crashes; a resurrected p1
    must be fenced. ``bug="skip_fence"`` lets the stale primary write
    anyway."""
    apply_anchor = _anchor(journal_spec.apply_entry)

    def init():
        return {
            "journal": [],
            "store_term": 1,
            "primaries": {"p1": {"alive": True, "term": 1,
                                 "writes": 0}},
            "standby": {"seq": 0, "promoted": False,
                        "replica": journal_spec.new_state()},
            "crashes_left": 1,
            "restarts_left": 1,
        }

    def actions(state):
        acts = []
        for name in sorted(state["primaries"]):
            p = state["primaries"][name]
            if p["alive"] and p["writes"] < max_writes:
                fenced = journal_spec.term_fences(
                    p["term"], state["store_term"])
                if not fenced or bug == "skip_fence":
                    def write(s, name=name):
                        prim = s["primaries"][name]
                        entry = {"seq": len(s["journal"]) + 1,
                                 "term": prim["term"], "op": "kv_put",
                                 "scope": "fleet", "key": "k",
                                 "value": f"{name}.{prim['writes']}",
                                 "writer": name}
                        s["journal"].append(entry)
                        prim["writes"] += 1
                        s["store_term"] = max(s["store_term"],
                                              entry["term"])
                        return s
                    acts.append(Action(f"{name}:write", name, write,
                                       anchor=apply_anchor))
            if p["alive"] and state["crashes_left"] > 0:
                def crash(s, name=name):
                    s["primaries"][name]["alive"] = False
                    s["crashes_left"] -= 1
                    return s
                acts.append(Action(f"{name}:crash", name, crash,
                                   fault=True))
            if not p["alive"] and state["restarts_left"] > 0:
                def restart(s, name=name):
                    s["primaries"][name]["alive"] = True
                    s["restarts_left"] -= 1
                    return s
                acts.append(Action(f"{name}:restart", name, restart,
                                   fault=True))
        sb = state["standby"]
        if not sb["promoted"] and sb["seq"] < len(state["journal"]):
            def sync(s):
                rep = s["standby"]
                for entry in s["journal"][rep["seq"]:]:
                    journal_spec.apply_entry(rep["replica"], entry)
                rep["seq"] = len(s["journal"])
                return s
            acts.append(Action("standby:sync", "standby", sync,
                               anchor=apply_anchor))
        if (not sb["promoted"] and sb["seq"] == len(state["journal"])
                and not state["primaries"]["p1"]["alive"]):
            def promote(s):
                rep = s["standby"]
                rep["promoted"] = True
                term = s["store_term"] + 1
                entry = {"seq": len(s["journal"]) + 1, "term": term,
                         "op": "term", "writer": "p2"}
                s["journal"].append(entry)
                journal_spec.apply_entry(rep["replica"], entry)
                rep["seq"] = len(s["journal"])
                s["store_term"] = term
                s["primaries"]["p2"] = {"alive": True, "term": term,
                                        "writes": 0}
                return s
            acts.append(Action("standby:promote", "standby", promote,
                               anchor=apply_anchor))
        return acts

    def terms_monotone(s):
        terms = [e["term"] for e in s["journal"]]
        for a, b in zip(terms, terms[1:]):
            if b < a:
                return (f"journal term regressed {a} -> {b}: a stale "
                        "primary mutated cohort state after a newer "
                        "term was observed (split-brain)")
        writers = {}
        for e in s["journal"]:
            first = writers.setdefault(e["term"], e["writer"])
            if first != e["writer"]:
                return (f"two primaries ({first}, {e['writer']}) "
                        f"wrote under term {e['term']}")
        return None

    def replica_convergence(s):
        sb = s["standby"]
        shadow = journal_spec.new_state()
        for entry in s["journal"][:sb["seq"]]:
            journal_spec.apply_entry(shadow, entry)
        if (journal_spec.state_digest(shadow)
                != journal_spec.state_digest(sb["replica"])):
            return ("standby replica digest diverged from the journal "
                    "replay at its seq — apply_entry is not the single "
                    "transition it claims to be")
        return None

    def goal(s):
        return any(p["alive"] and p["term"] == s["store_term"]
                   for p in s["primaries"].values())

    return Model(
        "ha", init, actions,
        invariants=[("single_writer_per_term", terms_monotone),
                    ("replica_convergence", replica_convergence)],
        liveness=[("active_primary_at_latest_term", goal)])


# ==========================================================================
# Fleet leases: ledger-before-actuation, crash, resume
# ==========================================================================

def lease_model(direction=lease_spec.TRAIN_TO_SERVE, bug=None):
    """One arbiter drives one lease down its chain: durable ledger
    write first, idempotent actuation second, crash anywhere, recovery
    via lease_spec.resume_action. ``bug="actuate_before_ledger"``
    swaps the ordering (the fence-skip shape)."""
    resume_anchor = _anchor(lease_spec.resume_action)
    check_anchor = _anchor(lease_spec.check_transition)

    def init():
        return {
            "lease": None,
            "up": True,
            "inflight": None,    # actuation (or write) still pending
            "effects": [],       # actuations issued (idempotent set)
            "passed": [],        # states durably written
            "crashes_left": 1,
        }

    def actions(state):
        acts = []
        lease = state["lease"]
        if state["up"] and lease is None:
            def open_lease(s):
                s["lease"] = {"id": "L1", "direction": direction,
                              "state": "proposed"}
                _insert_sorted(s["passed"], "proposed")
                return s
            acts.append(Action("arbiter:open", "arbiter", open_lease,
                               anchor=check_anchor))
        if (state["up"] and lease is not None
                and lease["state"] not in lease_spec.TERMINAL_STATES
                and state["inflight"] is None):
            nxt = lease_spec.next_state(direction, lease["state"])
            if nxt is not None:
                if bug == "actuate_before_ledger":
                    def actuate_first(s, nxt=nxt):
                        if nxt not in lease_spec.TERMINAL_STATES:
                            _insert_sorted(s["effects"], nxt)
                        s["inflight"] = {"phase": "write",
                                         "state": nxt}
                        return s
                    acts.append(Action(
                        f"arbiter:actuate[{nxt}]", "arbiter",
                        actuate_first, anchor=check_anchor))
                else:
                    def write(s, nxt=nxt):
                        lease_spec.check_transition(s["lease"], nxt)
                        s["lease"]["state"] = nxt
                        _insert_sorted(s["passed"], nxt)
                        if nxt not in lease_spec.TERMINAL_STATES:
                            s["inflight"] = {"phase": "actuate",
                                             "state": nxt}
                        return s
                    acts.append(Action(
                        f"arbiter:ledger_write[{nxt}]", "arbiter",
                        write, anchor=check_anchor))
        pending = state["inflight"]
        if state["up"] and pending is not None:
            if pending["phase"] == "actuate":
                def actuate(s):
                    _insert_sorted(s["effects"],
                                   s["inflight"]["state"])
                    s["inflight"] = None
                    return s
                acts.append(Action(
                    f"arbiter:actuate[{pending['state']}]", "arbiter",
                    actuate, anchor=check_anchor))
            else:   # the seeded bug's deferred ledger write
                def write_late(s):
                    nxt = s["inflight"]["state"]
                    lease_spec.check_transition(s["lease"], nxt)
                    s["lease"]["state"] = nxt
                    _insert_sorted(s["passed"], nxt)
                    s["inflight"] = None
                    return s
                acts.append(Action(
                    f"arbiter:ledger_write[{pending['state']}]",
                    "arbiter", write_late, anchor=check_anchor))
        if state["up"] and state["crashes_left"] > 0:
            def crash(s):
                s["up"] = False
                s["inflight"] = None    # volatile
                s["crashes_left"] -= 1
                return s
            acts.append(Action("arbiter:crash", "arbiter", crash,
                               fault=True))
        if not state["up"]:
            def recover(s):
                s["up"] = True
                lease = s["lease"]
                if lease is None:
                    return s
                what = lease_spec.resume_action(lease)
                if what == "rollback":
                    lease_spec.check_transition(lease, "rolled_back")
                    lease["state"] = "rolled_back"
                    _insert_sorted(s["passed"], "rolled_back")
                elif what == "roll_forward":
                    # re-issue the current state's idempotent actuation
                    _insert_sorted(s["effects"], lease["state"])
                return s
            acts.append(Action("arbiter:recover", "arbiter", recover,
                               anchor=resume_anchor))
        return acts

    def effects_are_ledgered(s):
        stray = [e for e in s["effects"] if e not in s["passed"]]
        if stray:
            return (f"actuation(s) {stray} issued before their ledger "
                    "write — a crash in this window strands actuated "
                    "state the recovery protocol cannot see")
        return None

    def rollback_unactuated(s):
        lease = s["lease"]
        if (lease is not None and lease["state"] == "rolled_back"
                and s["effects"]):
            return (f"lease rolled back with actuations {s['effects']} "
                    "already issued — rolled forward AND back")
        return None

    def valid_chain(s):
        lease = s["lease"]
        if lease is None:
            return None
        allowed = lease_spec.CHAINS[direction] + ("rolled_back",)
        if lease["state"] not in allowed:
            return f"lease in undefined state {lease['state']!r}"
        return None

    def goal(s):
        lease = s["lease"]
        return (lease is not None
                and lease["state"] in lease_spec.TERMINAL_STATES)

    return Model(
        "lease", init, actions,
        invariants=[("effects_are_ledgered", effects_are_ledgered),
                    ("rollback_unactuated", rollback_unactuated),
                    ("valid_chain", valid_chain)],
        liveness=[("lease_reaches_terminal", goal)])


# ==========================================================================
# KV migration: chunked transfer, staging, watermark admission
# ==========================================================================

def migration_model(bug=None, free=6, watermark=None, n_pages=2):
    """One sequence migrates source -> target as chunked messages over
    a lossy/duplicating/reordering channel; the target reassembles
    through migration_spec.stage_chunk and places all-or-nothing
    behind migration_spec.admits. Ownership transfers only on a
    delivered commit ack; every failure leg falls back to recompute
    (the graceful-degradation contract)."""
    if watermark is None:
        # skip_admit is only load-bearing when the pool is tight
        # enough that the admission predicate actually refuses.
        watermark = 5 if bug == "skip_admit" else 2
    pages = [{"payload": f"p{i}", "digest": f"d{i}"}
             for i in range(n_pages)]
    chunks = migration_spec.chunk_pages(pages, max_bytes=10)
    total = len(chunks)
    meta = {"id": "seq1", "num_tokens": n_pages}
    stage_anchor = _anchor(migration_spec.stage_chunk)
    admit_anchor = _anchor(migration_spec.admits)
    chunk_anchor = _anchor(migration_spec.chunk_pages)

    def _msg(i):
        msg = {"mid": "m1", "chunk": i, "total": total,
               "pages": copy.deepcopy(chunks[i])}
        if i == total - 1:
            msg["meta"] = dict(meta)
            msg["commit"] = True
        return msg

    def init():
        return {
            "src": {"next": 0, "owner": True, "done": None},
            "net": [],           # in-flight chunk indices (multiset)
            "staging": {},
            "imported": {},      # mid -> import count
            "alloc": {},         # mid -> pages allocated
            "free": int(free),
            "tgt_owner": False,
            "dups_left": 1,
            "drops_left": 1,
            "acklost_left": 1,
            "restarts_left": 1,
        }

    def _deliver(s, i, lost_ack):
        s["net"].remove(i)
        payload = _msg(i)
        record = migration_spec.stage_chunk(
            s["staging"], payload, max_staged=2, ttl_s=900.0, now=0.0)
        if record is not None and bug == "double_import":
            # The seeded mutation: the completed transfer's staging
            # entry is NOT deleted, so a duplicated chunk reassembles
            # the record again.
            s["staging"]["m1"] = {
                "chunks": {j: copy.deepcopy(chunks[j])
                           for j in range(total)},
                "total": total, "meta": dict(meta), "t": 0.0}
        imported_ok = False
        if record is not None:
            need = len(record["pages"])
            if (bug == "skip_admit"
                    or migration_spec.admits(s["free"], need,
                                             watermark)):
                s["free"] -= need
                s["imported"]["m1"] = s["imported"].get("m1", 0) + 1
                s["alloc"]["m1"] = s["alloc"].get("m1", 0) + need
                imported_ok = True
        if (payload.get("commit") and not lost_ack
                and s["src"]["done"] is None):
            if imported_ok:
                s["src"]["done"] = "handoff"
                s["src"]["owner"] = False
                s["tgt_owner"] = True
            else:
                s["src"]["done"] = "recompute"   # loud fallback
        return s

    def actions(state):
        acts = []
        src = state["src"]
        if src["done"] is None and src["next"] < total:
            def send(s):
                s["net"].append(s["src"]["next"])
                s["net"].sort()
                s["src"]["next"] += 1
                return s
            acts.append(Action("source:send", "source", send,
                               anchor=chunk_anchor))
        for i in sorted(set(state["net"])):
            def deliver(s, i=i):
                return _deliver(s, i, lost_ack=False)
            acts.append(Action(f"target:deliver[{i}]", "target",
                               deliver, anchor=stage_anchor))
            if state["dups_left"] > 0:
                def dup(s, i=i):
                    s["net"].append(i)
                    s["net"].sort()
                    s["dups_left"] -= 1
                    return s
                acts.append(Action(f"net:dup[{i}]", "net", dup,
                                   fault=True))
            if state["drops_left"] > 0:
                def drop(s, i=i):
                    s["net"].remove(i)
                    s["drops_left"] -= 1
                    return s
                acts.append(Action(f"net:drop[{i}]", "net", drop,
                                   fault=True))
            if i == total - 1 and state["acklost_left"] > 0:
                def acklost(s, i=i):
                    s["acklost_left"] -= 1
                    return _deliver(s, i, lost_ack=True)
                acts.append(Action(f"target:deliver_acklost[{i}]",
                                   "target", acklost, fault=True,
                                   anchor=stage_anchor))
        if (src["done"] is None and src["next"] == total
                and not state["net"]):
            def fallback(s):
                s["src"]["done"] = "recompute"
                return s
            acts.append(Action("source:fallback", "source", fallback,
                               anchor=admit_anchor))
        if state["restarts_left"] > 0 and not state["tgt_owner"]:
            def restart(s):
                s["staging"] = {}
                s["free"] += sum(s["alloc"].values())
                s["alloc"] = {}
                s["imported"] = {}
                s["restarts_left"] -= 1
                return s
            acts.append(Action("target:restart", "target", restart,
                               fault=True))
        return acts

    def no_double_import(s):
        doubled = {m: c for m, c in s["imported"].items() if c > 1}
        if doubled:
            return (f"transfer(s) {sorted(doubled)} imported "
                    f"{max(doubled.values())}x — a duplicated chunk "
                    "reassembled an already-committed migration")
        return None

    def watermark_respected(s):
        if s["free"] < watermark:
            return (f"page pool at {s['free']} free < watermark "
                    f"{watermark} — an import crossed the admission "
                    "reserve")
        return None

    def single_owner(s):
        owners = int(s["src"]["owner"]) + int(s["tgt_owner"])
        if owners != 1:
            return (f"sequence has {owners} authoritative owner(s) — "
                    "it must live in exactly one place")
        return None

    def goal(s):
        return s["src"]["done"] is not None

    return Model(
        "migration", init, actions,
        invariants=[("no_double_import", no_double_import),
                    ("watermark_respected", watermark_respected),
                    ("single_owner", single_owner)],
        liveness=[("migration_completes_or_falls_back", goal)])


# ==========================================================================
# Registry
# ==========================================================================

#: protocol -> builder. ``lease`` covers both directions (build() runs
#: each as its own exploration).
PROTOCOLS = ("ha", "lease", "migration")

#: protocol -> the seeded-bug names its builder understands.
BUGS = {
    "ha": ("skip_fence",),
    "lease": ("actuate_before_ledger",),
    "migration": ("double_import", "skip_admit"),
}


def build(protocol, bug=None):
    """The models to explore for ``protocol`` — a list, because the
    lease chain is per-direction."""
    if bug is not None and bug not in BUGS.get(protocol, ()):
        raise ValueError(
            f"protocol {protocol!r} has no seeded bug {bug!r} "
            f"(known: {', '.join(BUGS.get(protocol, ())) or 'none'})")
    if protocol == "ha":
        return [ha_model(bug=bug)]
    if protocol == "lease":
        return [lease_model(direction=d, bug=bug)
                for d in lease_spec.DIRECTIONS]
    if protocol == "migration":
        return [migration_model(bug=bug)]
    raise ValueError(f"unknown protocol {protocol!r} "
                     f"(known: {', '.join(PROTOCOLS)})")


__all__ = ["ha_model", "lease_model", "migration_model", "PROTOCOLS",
           "BUGS", "build"]
