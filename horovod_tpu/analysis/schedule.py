"""Interprocedural collective-schedule verifier (``hvd-lint verify``).

The HVD1xx/2xx/3xx layers are single-function, one-hop analyses: a
rank-dependent branch two calls above an ``allreduce``, or a loop whose
trip count differs per rank, sails through them clean. This layer
closes that gap by extracting a **symbolic per-rank collective
schedule** for a whole program and flagging every way that schedule can
diverge across ranks.

Architecture (prose version: docs/lint.md "Analyzer architecture"):

1. **Call graph.** Every analyzed file becomes a module of functions
   (top-level defs, methods, nested defs, plus the module body itself).
   ``import horovod_tpu.x`` / ``from horovod_tpu.x import f`` /
   relative imports inside the package are resolved to files on disk
   and pulled into the corpus on demand, so a collective buried in a
   helper module is analyzed in the caller's context. Call edges carry
   the control context of the call site and the taint of every
   argument.
2. **Taint lattice.** A two-point lattice (clean < rank-tainted)
   propagated through local assignment: ``hvd.rank()`` /
   ``local_rank()`` / ``lax.axis_index`` / process-set membership
   (``ps.rank()``/``ps.included()``) seed it; variables, conditions,
   loop bounds, and function *return values* (interprocedural fixpoint)
   carry it. Replica-invariant values — results of collectives — reset
   it: ``done = allreduce(flag)`` is rank-invariant by construction.
3. **Schedule extraction.** Walking each function once per fixpoint
   round records every collective as a ``ScheduleEvent`` (kind x name x
   process set x control context), every call site, every early exit
   (``return``/``raise``/``continue``/``break``), and every loop with
   its bound classification. :func:`extract_schedule` exposes the raw
   per-function schedules.
4. **HVD4xx rules** over the extracted schedules:

   - **HVD401** — a collective reachable under rank-tainted control
     flow through *any* call depth (generalizes HVD102/HVD201 beyond
     one hop; direct single-hop guards stay HVD201's finding).
   - **HVD402** — a loop containing a collective whose trip count is
     rank-tainted or data-dependent (schedule-*length* divergence:
     ranks submit different collective counts — a guaranteed stall).
   - **HVD403** — an early ``return``/``raise``/``continue``/``break``
     under a rank-tainted condition that skips a collective other
     ranks execute.
   - **HVD404** — collectives on distinct process sets interleaved in
     a context where relative order can differ per rank (deadlock by
     cross-set wait cycle).
   - **HVD405** — a per-tensor-semantics reduction (Adasum) routed
     through a bucketing/concatenating path (``grouped_allreduce`` or
     a concatenated payload): bucketing silently changes the dot
     products Adasum's scale-invariant combination is built from.

Known approximations (deliberate, documented in docs/lint.md):
over-approximation — any taint inside a condition taints the whole
frame (no path-sensitive pruning); under-approximation — attribute
*reads* (``topology.rank``) do not seed taint (only calls do), exits
are matched to skipped collectives lexically within one function, and
dynamic dispatch (``getattr``, callables in containers) is invisible.
Member-only collectives guarded by their own set's membership test
(``if ps.included(): allreduce(..., process_set=ps)``) are recognized
and exempt. Pure stdlib — no jax imports.
"""

import ast
import os
import re

from .diagnostics import Diagnostic, dedupe, relative_to_cwd
from .ast_lint import (
    AliasResolver, _apply_suppressions, _root_name, _terminal_name,
    iter_python_files, parse_cached,
)

_DOC_HINT = "see docs/lint.md"

# Bucketing / concatenating constructors feeding HVD405.
_CONCAT_CALLS = frozenset({
    "concatenate", "concat", "stack", "hstack", "vstack", "cat",
})
_GROUPED_PREFIX = "grouped_"
_PSET_CTORS = frozenset({"ProcessSet", "add_process_set"})
_PSET_MEMBER_METHODS = frozenset({"rank", "local_rank", "included"})
# Corpus safety valve: lazy import resolution must never crawl the
# world. Far above the package's module count.
_MAX_MODULES = 512
_MAX_PASSES = 6


def _params_of(node):
    args = node.args
    names = [a.arg for a in (args.posonlyargs + args.args
                             + args.kwonlyargs)]
    if names and names[0] in ("self", "cls"):
        names = names[1:]
    return names


def _unparse(node):
    try:
        return ast.unparse(node)
    except Exception:  # noqa: BLE001 — diagnostics only
        return "<expr>"


class _Frame:
    """One control-flow context (an ``if`` arm, a loop body)."""

    __slots__ = ("kind", "line", "tainted", "direct", "loop",
                 "test_params", "partner", "balanced", "pset_guard")

    def __init__(self, kind, line, tainted, direct, loop=False,
                 test_params=frozenset(), pset_guard=None):
        self.kind = kind
        self.line = line
        self.tainted = tainted
        self.direct = direct          # test literally calls rank()
        self.loop = loop
        self.test_params = test_params
        self.partner = None           # the else-arm frame of an if
        self.balanced = False         # both arms issue collectives
        self.pset_guard = pset_guard  # membership-tested pset var

    def describe(self):
        tag = self.kind
        if self.tainted:
            tag += " rank-tainted"
        return f"{tag}@{self.line}"


class ScheduleEvent:
    """One collective in the symbolic per-rank schedule."""

    __slots__ = ("kind", "name", "pset", "op", "line", "ctx",
                 "from_concat", "pattern")

    def __init__(self, kind, name, pset, op, line, ctx, from_concat,
                 pattern=None):
        self.kind = kind
        self.name = name              # explicit name= constant, or None
        self.pset = pset              # "global" or the unparsed expr
        self.op = op                  # terminal name of op=, or None
        self.line = line
        self.ctx = ctx                # tuple of _Frame
        self.from_concat = from_concat
        # regex for an f-string name= (``f"step{epoch}"`` -> ``step(.+)``)
        # — what lets `hvd-lint explain` map a runtime name like
        # ``step3`` back to this call site. None for constant/absent.
        self.pattern = pattern

    def to_dict(self, func):
        return {
            "function": func, "kind": self.kind, "name": self.name,
            "process_set": self.pset, "line": self.line,
            "context": [fr.describe() for fr in self.ctx],
        }


class _CallSite:
    __slots__ = ("callee", "line", "ctx", "tainted_params",
                 "adasum_params", "arg_params", "arg_names")

    def __init__(self, callee, line, ctx, tainted_params, adasum_params,
                 arg_params, arg_names):
        self.callee = callee
        self.line = line
        self.ctx = ctx
        self.tainted_params = tainted_params  # callee params bound tainted
        self.adasum_params = adasum_params    # callee params bound Adasum
        self.arg_params = arg_params  # callee param -> caller param names
        self.arg_names = arg_names    # every Name appearing in the args


class _Exit:
    __slots__ = ("kind", "line", "ctx")

    def __init__(self, kind, line, ctx):
        self.kind = kind
        self.line = line
        self.ctx = ctx


class _Loop:
    __slots__ = ("frame", "kind", "line", "test_names", "body_assigns")

    def __init__(self, frame, kind, line, test_names):
        self.frame = frame
        self.kind = kind              # "for" | "while"
        self.line = line
        self.test_names = test_names  # Names in the bound/condition
        self.body_assigns = {}        # name -> "invariant"|"call"|"pure"


class _Func:
    """One function (or module body) plus its fixpoint summary."""

    def __init__(self, qualname, node, module):
        self.qualname = qualname
        self.node = node
        self.module = module
        self.params = _params_of(node) if node is not None else []
        self.local_funcs = {}
        # per-pass walk products
        self.events = []
        self.calls = []
        self.exits = []
        self.loops = []
        self.frames = []
        self.program = []             # structured tree (walk_block doc)
        # fixpoint summary bits
        self.return_tainted = False
        self.guard_params = frozenset()
        self.grouped_op_params = frozenset()
        self.has_coll = False
        self.has_coll_trans = False
        self.reached = None           # call-chain text when rank-gated

    def summary(self):
        return (self.return_tainted, self.guard_params,
                self.grouped_op_params, self.has_coll)

    @property
    def body(self):
        return self.node.body if self.node is not None else []


class _Module:
    def __init__(self, path, src, tree):
        self.path = path
        self.src = src
        self.tree = tree
        self.res = AliasResolver()
        self.funcs = {}               # qualname -> _Func
        self.import_map = {}          # local name -> ("mod"|"from", ...)
        self._scan()

    def _scan(self):
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                self.res.visit_import(node)
                for alias in node.names:
                    target = alias.asname or alias.name.split(".")[0]
                    self.import_map.setdefault(
                        target, ("mod", alias.name if alias.asname
                                 else alias.name.split(".")[0], 0))
            elif isinstance(node, ast.ImportFrom):
                self.res.visit_import_from(node)
                mod = node.module or ""
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    name = alias.asname or alias.name
                    self.import_map.setdefault(
                        name, ("from", mod, node.level, alias.name))
        # the module body itself is the entry "function"
        body_fn = _Func("<module>", None, self)
        body_fn.node = None
        self.funcs["<module>"] = body_fn
        self._collect_funcs(self.tree.body, prefix="", owner=body_fn)

    def _collect_funcs(self, stmts, prefix, owner):
        for node in stmts:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = prefix + node.name
                fn = _Func(qual, node, self)
                self.funcs[qual] = fn
                if owner is not None:
                    owner.local_funcs[node.name] = fn
                self._collect_funcs(node.body, qual + ".", fn)
            elif isinstance(node, ast.ClassDef):
                # methods keep the full enclosing prefix so a class
                # nested in a function cannot clobber a same-named
                # top-level class; no owner — methods are not callable
                # by bare name
                self._collect_funcs(node.body, prefix + node.name + ".",
                                    owner=None)


class _Corpus:
    """Modules under analysis, with lazy horovod_tpu import loading."""

    def __init__(self):
        self.modules = {}             # abspath -> _Module
        self.pkg_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))

    def add_source(self, src, filename):
        tree = ast.parse(src, filename=filename)
        mod = _Module(filename, src, tree)
        self.modules[filename] = mod
        return mod

    def load(self, path):
        path = os.path.abspath(path)
        if path in self.modules:
            return self.modules[path]
        if len(self.modules) >= _MAX_MODULES:
            return None
        try:
            src, tree = parse_cached(path)
        except (OSError, SyntaxError):
            return None
        mod = _Module(path, src, tree)
        self.modules[path] = mod
        return mod

    def resolve_module_path(self, modname, level, from_path):
        """File for ``modname`` (absolute ``horovod_tpu.*`` or relative
        with ``level`` leading dots), or None for everything else."""
        if level:
            base = os.path.dirname(os.path.abspath(from_path))
            for _ in range(level - 1):
                base = os.path.dirname(base)
            parts = modname.split(".") if modname else []
        else:
            root = modname.split(".")[0]
            if root in ("horovod_tpu", "horovod"):
                base = self.pkg_root
            else:
                # a sibling module of the entry script (`from helpers
                # import sync` next to train.py) — how plain scripts
                # import their own helpers
                base = os.path.dirname(os.path.abspath(from_path))
            parts = modname.split(".")
        candidate = os.path.join(base, *parts) if parts else base
        for path in (candidate + ".py",
                     os.path.join(candidate, "__init__.py")):
            if os.path.isfile(path):
                return path
        return None

    def resolve_call(self, call, func, module):
        """The _Func a call resolves to, or None (collectives, library
        calls, dynamic dispatch)."""
        f = call.func
        if isinstance(f, ast.Name):
            name = f.id
            cur = func
            while cur is not None:
                if name in cur.local_funcs:
                    return cur.local_funcs[name]
                cur = None  # one level is enough: nested defs register
            if name in module.funcs:
                return module.funcs[name]
            entry = module.import_map.get(name)
            if entry and entry[0] == "from":
                _, mod, level, orig = entry
                path = self.resolve_module_path(mod, level, module.path)
                if path:
                    other = self.load(path)
                    if other:
                        return other.funcs.get(orig)
            return None
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
            base = f.value.id
            if base in ("self", "cls") and "." in func.qualname:
                cls = func.qualname.rsplit(".", 1)[0]
                return module.funcs.get(f"{cls}.{f.attr}")
            entry = module.import_map.get(base)
            if entry and entry[0] == "from":
                _, mod, level, orig = entry
                # `from horovod_tpu import checkpoint` -> module alias
                parent = self.resolve_module_path(mod, level, module.path)
                if parent:
                    sub = os.path.join(os.path.dirname(parent)
                                       if parent.endswith("__init__.py")
                                       else parent[:-3], "")
                    for path in (
                            os.path.join(os.path.dirname(parent), orig
                                         + ".py")
                            if parent.endswith("__init__.py") else None,
                            os.path.join(sub, orig, "__init__.py")):
                        if path and os.path.isfile(path):
                            other = self.load(path)
                            if other:
                                return other.funcs.get(f.attr)
            elif entry and entry[0] == "mod":
                path = self.resolve_module_path(entry[1], 0, module.path)
                if path:
                    other = self.load(path)
                    if other:
                        return other.funcs.get(f.attr)
        return None


class _FuncWalker:
    """One fixpoint pass over one function's body."""

    def __init__(self, corpus, module, func):
        self.corpus = corpus
        self.module = module
        self.func = func
        self.res = module.res
        self.tainted = set()
        self.pset_vars = set()
        self.call_derived = set()     # assigned from local compute calls
        self.concat_vars = set()
        self.active_loops = []

    # -- taint -------------------------------------------------------------
    def _call_tainted(self, n):
        if self.res.is_rank_call(n):
            return True
        if (isinstance(n.func, ast.Attribute)
                and n.func.attr in _PSET_MEMBER_METHODS
                and _root_name(n.func) in self.pset_vars):
            return True
        callee = self.corpus.resolve_call(n, self.func, self.module)
        return callee is not None and callee.return_tainted

    def expr_tainted(self, expr):
        if expr is None:
            return False
        for n in ast.walk(expr):
            if isinstance(n, ast.Call) and self._call_tainted(n):
                return True
            if isinstance(n, ast.Name) and n.id in self.tainted:
                return True
        return False

    def _expr_direct(self, expr):
        """The test itself calls rank()/membership — the one-hop shape
        HVD201 already owns."""
        if expr is None:
            return False
        for n in ast.walk(expr):
            if isinstance(n, ast.Call):
                if self.res.is_rank_call(n):
                    return True
                if (isinstance(n.func, ast.Attribute)
                        and n.func.attr in _PSET_MEMBER_METHODS
                        and _root_name(n.func) in self.pset_vars):
                    return True
        return False

    def _pset_guard_of(self, expr):
        if expr is None:
            return None
        for n in ast.walk(expr):
            if (isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr in _PSET_MEMBER_METHODS):
                root = _root_name(n.func)
                if root in self.pset_vars:
                    return root
        return None

    def _test_params(self, expr):
        if expr is None:
            return frozenset()
        params = set(self.func.params)
        return frozenset(n.id for n in ast.walk(expr)
                         if isinstance(n, ast.Name) and n.id in params)

    # -- expression scan: events + call sites ------------------------------
    def scan_expr(self, expr, ctx, prog=None):
        if expr is None:
            return
        for n in ast.walk(expr):
            if not isinstance(n, ast.Call):
                continue
            kind = self.res.collective_kind(n)
            if kind is not None:
                self._record_event(n, kind, ctx, prog)
                continue
            callee = self.corpus.resolve_call(n, self.func, self.module)
            if callee is None or callee is self.func:
                continue
            self._record_call(n, callee, ctx, prog)

    @staticmethod
    def _name_pattern(node):
        """Regex for an f-string ``name=`` (constant parts escaped,
        interpolations matched loosely), or None."""
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant):
                parts.append(re.escape(str(v.value)))
            else:
                parts.append("(.+)")
        return "".join(parts) or None

    def _record_event(self, n, kind, ctx, prog=None):
        name = op = pattern = None
        pset = "global"
        for kw in n.keywords:
            if kw.arg == "name" and isinstance(kw.value, ast.Constant):
                name = str(kw.value.value)
            elif kw.arg == "name" and isinstance(kw.value,
                                                 ast.JoinedStr):
                pattern = self._name_pattern(kw.value)
            elif kw.arg == "op":
                op = _terminal_name(kw.value)
            elif kw.arg == "process_set":
                text = _unparse(kw.value)
                pset = ("global" if text.endswith("global_process_set")
                        else text)
        from_concat = False
        if n.args:
            first = n.args[0]
            if (isinstance(first, ast.Call)
                    and _terminal_name(first.func) in _CONCAT_CALLS):
                from_concat = True
            elif (isinstance(first, ast.Name)
                    and first.id in self.concat_vars):
                from_concat = True
        event = ScheduleEvent(kind, name, pset, op, n.lineno,
                              tuple(ctx), from_concat, pattern)
        self.func.events.append(event)
        if prog is not None:
            prog.append(("ev", event))
        # an op= that is a bare parameter feeding a grouped/bucketed
        # collective: record for the interprocedural HVD405 check
        if kind.startswith(_GROUPED_PREFIX):
            for kw in n.keywords:
                if (kw.arg == "op" and isinstance(kw.value, ast.Name)
                        and kw.value.id in self.func.params):
                    self.func.grouped_op_params = (
                        self.func.grouped_op_params | {kw.value.id})

    def _record_call(self, n, callee, ctx, prog=None):
        tainted_params, adasum_params = set(), set()
        arg_params, arg_names = {}, set()
        own = set(self.func.params)

        def bind(param, value):
            if param is None:
                return
            if self.expr_tainted(value):
                tainted_params.add(param)
            if _terminal_name(value) == "Adasum":
                adasum_params.add(param)
            referenced = {m.id for m in ast.walk(value)
                          if isinstance(m, ast.Name)}
            arg_names.update(referenced)
            hits = referenced & own
            if hits:
                arg_params.setdefault(param, set()).update(hits)

        for i, value in enumerate(n.args):
            bind(callee.params[i] if i < len(callee.params) else None,
                 value)
        for kw in n.keywords:
            if kw.arg and kw.arg in callee.params:
                bind(kw.arg, kw.value)
        site = _CallSite(
            callee, n.lineno, tuple(ctx), frozenset(tainted_params),
            frozenset(adasum_params), arg_params, frozenset(arg_names))
        self.func.calls.append(site)
        if prog is not None:
            prog.append(("call", site))

    # -- assignment bookkeeping --------------------------------------------
    @staticmethod
    def _target_names(target):
        elts = (target.elts if isinstance(target, (ast.Tuple, ast.List))
                else [target])
        return [t.id for t in elts if isinstance(t, ast.Name)]

    def _value_class(self, value):
        """invariant (collective result) > call (local compute) > pure."""
        has_call = False
        for n in ast.walk(value):
            if isinstance(n, ast.Call):
                if self.res.is_collective(n):
                    return "invariant"
                has_call = True
            elif (isinstance(n, ast.Name)
                    and n.id in self.call_derived):
                has_call = True
        return "call" if has_call else "pure"

    def _note_assign(self, targets, value):
        # element-wise tuple unpacking: `rank, size = hvd.rank(),
        # hvd.size()` must taint `rank` only, not smear over `size`
        if (len(targets) == 1
                and isinstance(targets[0], (ast.Tuple, ast.List))
                and isinstance(value, (ast.Tuple, ast.List))
                and len(targets[0].elts) == len(value.elts)):
            for t, v in zip(targets[0].elts, value.elts):
                self._note_assign([t], v)
            return
        names = []
        for t in targets:
            names.extend(self._target_names(t))
        if not names:
            return
        cls = self._value_class(value)
        # collective results are replica-invariant BY CONSTRUCTION
        # (every rank gets the identical reduction/concatenation), so
        # an assignment through a collective launders rank taint:
        # `n = min(allgather(local_n))` is the canonical lockstep idiom
        tainted = cls != "invariant" and self.expr_tainted(value)
        is_pset = (isinstance(value, ast.Call)
                   and _terminal_name(value.func) in _PSET_CTORS)
        is_concat = (isinstance(value, ast.Call)
                     and _terminal_name(value.func) in _CONCAT_CALLS)
        for name in names:
            for store, on in ((self.tainted, tainted),
                              (self.pset_vars, is_pset),
                              (self.concat_vars, is_concat),
                              (self.call_derived, cls == "call")):
                (store.add if on else store.discard)(name)
            for loop in self.active_loops:
                if name in loop.test_names:
                    loop.body_assigns[name] = cls

    # -- statement walk ----------------------------------------------------
    def walk(self):
        fn = self.func
        fn.events, fn.calls, fn.exits = [], [], []
        fn.loops, fn.frames = [], []
        fn.program = []
        fn.return_tainted = False
        fn.grouped_op_params = frozenset()
        body = fn.body if fn.node is not None else fn.module.tree.body
        self.walk_block(body, [], fn.program)
        fn.has_coll = bool(fn.events)

    def _make_frame(self, kind, test, line, loop=False):
        frame = _Frame(
            kind, line, tainted=self.expr_tainted(test),
            direct=self._expr_direct(test), loop=loop,
            test_params=self._test_params(test),
            pset_guard=self._pset_guard_of(test))
        self.func.frames.append(frame)
        return frame

    def walk_block(self, stmts, ctx, prog):
        """Walk statements recording events/calls/exits/loops (the rule
        inputs) AND building the structured **program tree** in ``prog``
        — the executable form the schedule simulator
        (analysis/simulate.py) replays per symbolic rank. Node shapes:
        ``("ev", ScheduleEvent)``, ``("call", _CallSite)``,
        ``("br", _Frame, then_prog, else_prog)``,
        ``("loop", _Loop, body_prog)``, ``("exit", _Exit)``, and
        ``("opt", prog)`` for exception handlers (never executed by the
        simulator — exception paths are a documented approximation)."""
        for node in stmts:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # separate _Func entries
            elif isinstance(node, ast.If):
                self.scan_expr(node.test, ctx, prog)
                frame = self._make_frame("if", node.test, node.lineno)
                then_prog, else_prog = [], []
                self.walk_block(node.body, ctx + [frame], then_prog)
                other = _Frame("else", node.lineno, frame.tainted,
                               frame.direct,
                               test_params=frame.test_params,
                               pset_guard=frame.pset_guard)
                frame.partner = other
                other.partner = frame
                self.func.frames.append(other)
                self.walk_block(node.orelse, ctx + [other], else_prog)
                prog.append(("br", frame, then_prog, else_prog))
            elif isinstance(node, ast.While):
                self.scan_expr(node.test, ctx, prog)
                frame = self._make_frame("while", node.test, node.lineno,
                                         loop=True)
                loop = _Loop(frame, "while", node.lineno,
                             {m.id for m in ast.walk(node.test)
                              if isinstance(m, ast.Name)})
                self.func.loops.append(loop)
                self.active_loops.append(loop)
                body_prog = []
                self.walk_block(node.body, ctx + [frame], body_prog)
                self.active_loops.pop()
                prog.append(("loop", loop, body_prog))
                self.walk_block(node.orelse, ctx, prog)
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                self.scan_expr(node.iter, ctx, prog)
                frame = self._make_frame("for", node.iter, node.lineno,
                                         loop=True)
                if frame.tainted:
                    target = node.target
                    if (isinstance(node.iter, ast.Call)
                            and _terminal_name(node.iter.func)
                            == "enumerate"
                            and isinstance(target, ast.Tuple)
                            and len(target.elts) == 2):
                        # enumerate counters are replica-invariant
                        # (every rank counts 0,1,2,...) even over
                        # rank-sharded data — taint only the values
                        target = target.elts[1]
                    for name in self._target_names(target):
                        self.tainted.add(name)
                loop = _Loop(frame, "for", node.lineno, set())
                self.func.loops.append(loop)
                self.active_loops.append(loop)
                body_prog = []
                self.walk_block(node.body, ctx + [frame], body_prog)
                self.active_loops.pop()
                prog.append(("loop", loop, body_prog))
                self.walk_block(node.orelse, ctx, prog)
            elif isinstance(node, ast.Try):
                self.walk_block(node.body, ctx, prog)
                for handler in node.handlers:
                    handler_prog = []
                    self.walk_block(handler.body, ctx, handler_prog)
                    prog.append(("opt", handler_prog))
                self.walk_block(node.orelse, ctx, prog)
                self.walk_block(node.finalbody, ctx, prog)
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    self.scan_expr(item.context_expr, ctx, prog)
                self.walk_block(node.body, ctx, prog)
            elif isinstance(node, ast.Assign):
                self.scan_expr(node.value, ctx, prog)
                self._note_assign(node.targets, node.value)
            elif isinstance(node, ast.AugAssign):
                self.scan_expr(node.value, ctx, prog)
                # += keeps the existing classification ("pure" update)
                for loop in self.active_loops:
                    for name in self._target_names(node.target):
                        if (name in loop.test_names
                                and name not in loop.body_assigns):
                            loop.body_assigns[name] = "pure"
            elif isinstance(node, ast.AnnAssign):
                self.scan_expr(node.value, ctx, prog)
                if node.value is not None:
                    self._note_assign([node.target], node.value)
            elif isinstance(node, ast.Return):
                self.scan_expr(node.value, ctx, prog)
                if self.expr_tainted(node.value):
                    self.func.return_tainted = True
                exit_ = _Exit("return", node.lineno, tuple(ctx))
                self.func.exits.append(exit_)
                prog.append(("exit", exit_))
            elif isinstance(node, ast.Raise):
                self.scan_expr(node.exc, ctx, prog)
                exit_ = _Exit("raise", node.lineno, tuple(ctx))
                self.func.exits.append(exit_)
                prog.append(("exit", exit_))
            elif isinstance(node, ast.Continue):
                exit_ = _Exit("continue", node.lineno, tuple(ctx))
                self.func.exits.append(exit_)
                prog.append(("exit", exit_))
            elif isinstance(node, ast.Break):
                exit_ = _Exit("break", node.lineno, tuple(ctx))
                self.func.exits.append(exit_)
                prog.append(("exit", exit_))
            elif isinstance(node, ast.Expr):
                self.scan_expr(node.value, ctx, prog)
            else:
                # assert/delete/global/... — scan any embedded
                # expressions; no new control context
                for child in ast.iter_child_nodes(node):
                    if isinstance(child, ast.expr):
                        self.scan_expr(child, ctx, prog)


def _mentions(pset_text, var):
    return var is not None and var in re.findall(r"\w+", pset_text or "")


class Verifier:
    """Drive the fixpoint and evaluate the HVD4xx rules."""

    def __init__(self):
        self.corpus = _Corpus()
        self.entries = []
        self._fixpointed = False

    def add_path(self, path):
        mod = self.corpus.load(path)
        if mod is not None:
            self.entries.append(mod)
            self._fixpointed = False
        return mod

    def add_source(self, src, filename="<string>"):
        mod = self.corpus.add_source(src, filename)
        self.entries.append(mod)
        self._fixpointed = False
        return mod

    def fixpoint(self):
        """Idempotent fixpoint: consumers that run AFTER the rules
        (the perf cost model) share the invocation's one call-graph
        fixpoint instead of re-walking the corpus."""
        if not self._fixpointed:
            self._fixpoint()
            self._compute_balance()
            self._fixpointed = True

    def _all_funcs(self):
        for path in sorted(self.corpus.modules):
            mod = self.corpus.modules[path]
            for qual in list(mod.funcs):
                yield mod.funcs[qual]

    # -- fixpoint ----------------------------------------------------------
    def _fixpoint(self):
        for _ in range(_MAX_PASSES):
            changed = False
            count_before = len(self.corpus.modules)
            for fn in list(self._all_funcs()):
                before = fn.summary()
                _FuncWalker(self.corpus, fn.module, fn).walk()
                if fn.summary() != before:
                    changed = True
            self._close_has_coll()
            for fn in self._all_funcs():
                before = fn.guard_params
                self._compute_guard_params(fn)
                if fn.guard_params != before:
                    changed = True
            if len(self.corpus.modules) != count_before:
                changed = True
            if not changed:
                break

    def _close_has_coll(self):
        funcs = list(self._all_funcs())
        for fn in funcs:
            fn.has_coll_trans = fn.has_coll
        moved = True
        while moved:
            moved = False
            for fn in funcs:
                if fn.has_coll_trans:
                    continue
                if any(c.callee.has_coll_trans for c in fn.calls):
                    fn.has_coll_trans = True
                    moved = True

    def _compute_guard_params(self, fn):
        guards = set(fn.guard_params)
        for event in fn.events:
            for frame in event.ctx:
                guards |= frame.test_params
        for call in fn.calls:
            if call.callee.has_coll_trans:
                for frame in call.ctx:
                    guards |= frame.test_params
            for callee_param, caller_params in call.arg_params.items():
                if callee_param in call.callee.guard_params:
                    guards |= caller_params
        fn.guard_params = frozenset(guards)

    # -- rules -------------------------------------------------------------
    def run(self):
        self.fixpoint()
        diags = []
        diags_404, cross_set_events = self._rule_404()
        diags += diags_404
        self._mark_reached()
        diags += self._rule_401(cross_set_events)
        diags += self._rule_402()
        diags += self._rule_403()
        diags += self._rule_405()
        return dedupe(sorted(diags, key=Diagnostic.sort_key))

    def _frame_events(self, fn):
        by_frame = {}
        for event in fn.events:
            for frame in event.ctx:
                by_frame.setdefault(frame, []).append(event)
        return by_frame

    def _frame_coll_calls(self, fn):
        by_frame = {}
        for call in fn.calls:
            if not call.callee.has_coll_trans:
                continue
            for frame in call.ctx:
                by_frame.setdefault(frame, []).append(call)
        return by_frame

    def _compute_balance(self):
        for fn in self._all_funcs():
            events = self._frame_events(fn)
            calls = self._frame_coll_calls(fn)
            for frame in fn.frames:
                if frame.kind != "if" or frame.partner is None:
                    continue
                mine = bool(events.get(frame)) or bool(calls.get(frame))
                theirs = (bool(events.get(frame.partner))
                          or bool(calls.get(frame.partner)))
                frame.balanced = frame.partner.balanced = mine and theirs

    @staticmethod
    def _divergent_frame(ctx, arg_names=frozenset()):
        """Innermost rank-tainted frame that actually diverges: not
        balanced (both arms issue collectives is SPMD-correct shape),
        not a loop (divergent trip counts are HVD402's finding, one
        per loop, not one per collective inside it), and not a
        membership guard for a set the call itself works on."""
        for frame in reversed(ctx):
            if not frame.tainted or frame.balanced or frame.loop:
                continue
            if frame.pset_guard and frame.pset_guard in arg_names:
                continue
            return frame
        return None

    def _mark_reached(self):
        worklist = []
        for fn in self._all_funcs():
            for call in fn.calls:
                frame = self._divergent_frame(call.ctx, call.arg_names)
                if frame is not None and call.callee.has_coll_trans \
                        and call.callee.reached is None:
                    call.callee.reached = (
                        f"called from {fn.qualname} at "
                        f"{_rel(fn.module.path)}:{call.line} under the "
                        f"rank-tainted `{frame.kind}` at line "
                        f"{frame.line}")
                    worklist.append(call.callee)
        while worklist:
            fn = worklist.pop()
            for call in fn.calls:
                callee = call.callee
                if callee.has_coll_trans and callee.reached is None:
                    callee.reached = (f"reached through {fn.qualname} "
                                      f"({fn.reached})")
                    worklist.append(callee)

    def _rule_401(self, cross_set_events=frozenset()):
        diags = []
        for fn in self._all_funcs():
            if fn.reached is not None:
                for event in fn.events:
                    diags.append(Diagnostic.make(
                        "HVD401",
                        f"collective `{event.kind}`"
                        + (f" (name={event.name!r})" if event.name
                           else "")
                        + " runs only on ranks that take a rank-"
                        "dependent path: " + fn.reached + " — the other "
                        "ranks never submit it and the job deadlocks",
                        file=fn.module.path, line=event.line,
                        hint="hoist the collective out of the rank-"
                             "dependent path (every rank must submit "
                             "every collective), or make the gating "
                             "condition replica-invariant; "
                             + _DOC_HINT))
                continue
            for event in fn.events:
                if id(event) in cross_set_events:
                    continue  # HVD404 is the more precise diagnosis
                frame = self._divergent_frame(
                    event.ctx, frozenset(re.findall(r"\w+",
                                                    event.pset or "")))
                if frame is None or frame.direct:
                    # direct one-hop guards are HVD201/HVD402 territory
                    continue
                diags.append(Diagnostic.make(
                    "HVD401",
                    f"collective `{event.kind}`"
                    + (f" (name={event.name!r})" if event.name else "")
                    + f" is guarded by the `{frame.kind}` at line "
                    f"{frame.line} whose condition is rank-tainted "
                    "through data flow (a variable or return value "
                    "derived from rank()): only some ranks reach it",
                    file=fn.module.path, line=event.line,
                    hint="make the condition replica-invariant "
                         "(allreduce the flag first) or hoist the "
                         "collective; " + _DOC_HINT))
            # a tainted argument steering a callee's guard
            for call in fn.calls:
                inter = call.tainted_params & call.callee.guard_params
                if not inter or call.callee.reached is not None:
                    continue
                callee = call.callee
                for event in callee.events:
                    if any(frame.test_params & inter
                           and not frame.balanced
                           for frame in event.ctx):
                        diags.append(Diagnostic.make(
                            "HVD401",
                            f"collective `{event.kind}` in "
                            f"{callee.qualname} is guarded by "
                            f"parameter(s) {sorted(inter)} that "
                            f"{fn.qualname} binds to a rank-tainted "
                            f"value at {_rel(fn.module.path)}:"
                            f"{call.line}: the guard differs per rank",
                            file=callee.module.path, line=event.line,
                            hint="pass a replica-invariant value, or "
                                 "restructure so every rank submits "
                                 "the collective; " + _DOC_HINT))
        return diags

    def _rule_402(self):
        diags = []
        for fn in self._all_funcs():
            events = self._frame_events(fn)
            calls = self._frame_coll_calls(fn)
            for loop in fn.loops:
                frame = loop.frame
                if not (events.get(frame) or calls.get(frame)):
                    continue
                if frame.tainted:
                    if loop.kind == "while" and frame.direct \
                            and events.get(frame):
                        continue  # HVD201's exact one-hop shape
                    diags.append(Diagnostic.make(
                        "HVD402",
                        f"`{loop.kind}` loop bound at line {loop.line} "
                        "is rank-tainted and the body submits "
                        "collectives: per-rank schedule LENGTHS "
                        "diverge (ranks run different iteration "
                        "counts), so some rank always waits on a "
                        "collective nobody else submits",
                        file=fn.module.path, line=loop.line,
                        hint="make the trip count replica-invariant "
                             "(pmax/allreduce the bound, pad the last "
                             "iterations); " + _DOC_HINT))
                elif loop.kind == "while" and any(
                        kind == "call"
                        for kind in loop.body_assigns.values()):
                    var = next(n for n, k in loop.body_assigns.items()
                               if k == "call")
                    diags.append(Diagnostic.make(
                        "HVD402",
                        f"`while` condition at line {loop.line} "
                        f"depends on `{var}`, updated inside the body "
                        "from rank-local compute: each rank's data "
                        "decides its own trip count, so collective "
                        "counts diverge (the convergence-loop stall)",
                        file=fn.module.path, line=loop.line,
                        hint=f"make `{var}` replica-invariant — e.g. "
                             "reduce it first (`done = hvd.allreduce("
                             "done_flag)`), so every rank agrees when "
                             "to stop; " + _DOC_HINT))
        return diags

    def _rule_403(self):
        diags = []
        for fn in self._all_funcs():
            if fn.reached is not None:
                continue  # the whole function is already HVD401
            for exit_ in fn.exits:
                frame = self._divergent_frame(exit_.ctx)
                if frame is None:
                    continue
                skipped = None
                for event in fn.events:
                    if event.line <= exit_.line or frame in event.ctx:
                        continue
                    if _mentions(event.pset, frame.pset_guard):
                        continue
                    if exit_.kind in ("continue", "break"):
                        loop_frames = [f for f in exit_.ctx if f.loop]
                        if loop_frames and \
                                loop_frames[-1] not in event.ctx:
                            continue
                    skipped = event
                    break
                if skipped is None:
                    for call in fn.calls:
                        if call.line <= exit_.line \
                                or not call.callee.has_coll_trans \
                                or frame in call.ctx:
                            continue
                        if exit_.kind in ("continue", "break"):
                            loop_frames = [f for f in exit_.ctx
                                           if f.loop]
                            if loop_frames and \
                                    loop_frames[-1] not in call.ctx:
                                continue
                        skipped = call
                        break
                if skipped is None:
                    continue
                what = (f"collective `{skipped.kind}`"
                        if isinstance(skipped, ScheduleEvent)
                        else f"call to `{skipped.callee.qualname}` "
                             "(which submits collectives)")
                diags.append(Diagnostic.make(
                    "HVD403",
                    f"early `{exit_.kind}` under the rank-tainted "
                    f"condition at line {frame.line} skips the {what} "
                    f"at line {skipped.line} that the other ranks "
                    "execute: schedule divergence, guaranteed stall",
                    file=fn.module.path, line=exit_.line,
                    hint="restructure so every rank reaches every "
                         "collective — move the early exit below the "
                         "collectives, or make the condition "
                         "replica-invariant; " + _DOC_HINT))
        return diags

    def _rule_404(self):
        diags = []
        cross_set_events = set()
        for fn in self._all_funcs():
            events = self._frame_events(fn)
            seen_pairs = set()
            for frame in fn.frames:
                if frame.kind != "if" or frame.partner is None \
                        or not frame.tainted or not frame.balanced:
                    continue
                key = (id(frame), id(frame.partner))
                if key in seen_pairs or (key[1], key[0]) in seen_pairs:
                    continue
                seen_pairs.add(key)
                mine = sorted((e for e in events.get(frame, [])),
                              key=lambda e: e.line)
                theirs = sorted((e for e in events.get(frame.partner,
                                                       [])),
                                key=lambda e: e.line)
                seq_a = [e.pset for e in mine]
                seq_b = [e.pset for e in theirs]
                if not seq_a or not seq_b or seq_a == seq_b:
                    continue
                if len(set(seq_a) | set(seq_b)) < 2:
                    continue
                where = mine[0] if mine else theirs[0]
                diags.append(Diagnostic.make(
                    "HVD404",
                    "branches of the rank-dependent `if` at line "
                    f"{frame.line} issue collectives on distinct "
                    f"process sets in divergent order ({seq_a} vs "
                    f"{seq_b}): ranks taking different branches wait "
                    "inside different sets' collectives — a cross-set "
                    "wait cycle that never resolves",
                    file=fn.module.path, line=where.line,
                    hint="issue cross-set collectives in one fixed "
                         "program order on every rank (hoist them out "
                         "of the rank-dependent branches); "
                         + _DOC_HINT))
            # a rank-gated collective on set A followed by an
            # unconditional collective on set B: gated ranks sit in A
            # while the rest enter B
            for event in fn.events:
                frame = self._divergent_frame(
                    event.ctx, frozenset(re.findall(r"\w+",
                                                    event.pset or "")))
                if frame is None:
                    continue
                follow = next(
                    (g for g in fn.events
                     if g.line > event.line and frame not in g.ctx
                     and g.pset != event.pset), None)
                if follow is None:
                    continue
                cross_set_events.add(id(event))
                diags.append(Diagnostic.make(
                    "HVD404",
                    f"collective `{event.kind}` on process set "
                    f"`{event.pset}` runs only under the rank-tainted "
                    f"`{frame.kind}` at line {frame.line}, while "
                    f"`{follow.kind}` on `{follow.pset}` (line "
                    f"{follow.line}) runs on every rank: gated ranks "
                    f"wait inside `{event.pset}` while the others have "
                    f"moved on to `{follow.pset}` — a cross-set wait "
                    "cycle",
                    file=fn.module.path, line=event.line,
                    hint="run cross-set collectives in the same "
                         "relative order on every rank; guard only "
                         "rank-local work; " + _DOC_HINT))
        return diags, frozenset(cross_set_events)

    def _rule_405(self):
        diags = []
        for fn in self._all_funcs():
            for event in fn.events:
                if event.op != "Adasum":
                    continue
                if event.kind.startswith(_GROUPED_PREFIX):
                    diags.append(Diagnostic.make(
                        "HVD405",
                        f"Adasum routed through `{event.kind}`: the "
                        "grouped path fuses tensors into buckets, but "
                        "Adasum's scale-invariant combination is "
                        "defined per WHOLE tensor — bucketing changes "
                        "the dot products it is built from and "
                        "silently alters the math",
                        file=fn.module.path, line=event.line,
                        hint="reduce Adasum tensors individually "
                             "(plain allreduce per tensor), or switch "
                             "the group to op=Average; " + _DOC_HINT))
                elif event.from_concat and \
                        event.kind.startswith("allreduce"):
                    diags.append(Diagnostic.make(
                        "HVD405",
                        f"Adasum over a concatenated payload at line "
                        f"{event.line}: concatenation merges tensors "
                        "into one buffer, so Adasum computes ONE "
                        "scale-invariant combination for the whole "
                        "bucket instead of one per tensor — silently "
                        "different updates",
                        file=fn.module.path, line=event.line,
                        hint="reduce each tensor separately under "
                             "Adasum — never concatenate/bucket its "
                             "inputs; " + _DOC_HINT))
            for call in fn.calls:
                inter = call.adasum_params & call.callee.grouped_op_params
                if inter:
                    diags.append(Diagnostic.make(
                        "HVD405",
                        f"Adasum passed as {sorted(inter)} into "
                        f"`{call.callee.qualname}`, which feeds it to "
                        "a grouped/bucketed collective: Adasum's "
                        "per-tensor semantics do not survive "
                        "bucketing",
                        file=fn.module.path, line=call.line,
                        hint="call the per-tensor reduction path for "
                             "Adasum, or pass op=Average/Sum here; "
                             + _DOC_HINT))
        return diags

    # -- schedule extraction ----------------------------------------------
    def schedules(self):
        self._fixpoint()
        out = []
        for mod in self.entries:
            for qual in mod.funcs:
                fn = mod.funcs[qual]
                for event in sorted(fn.events, key=lambda e: e.line):
                    out.append(event.to_dict(f"{_rel(mod.path)}:{qual}"))
        return out


def _rel(path):
    return relative_to_cwd(path)


def _suppress(diags, corpus):
    """Apply the standard ``# hvd-lint: disable=`` comments, grouped by
    the file each finding landed in (interprocedural findings may land
    in an imported module, which carries its own suppressions)."""
    by_file = {}
    for d in diags:
        by_file.setdefault(d.file, []).append(d)
    out = []
    for path, file_diags in by_file.items():
        mod = corpus.modules.get(os.path.abspath(path)) \
            or corpus.modules.get(path)
        if mod is not None:
            src = mod.src
        else:
            try:
                with open(path, encoding="utf-8",
                          errors="replace") as f:
                    src = f.read()
            except OSError:
                src = ""
        out.extend(_apply_suppressions(file_diags, src) if src
                   else file_diags)
    return sorted(out, key=Diagnostic.sort_key)


def verify_source(src, filename="<string>"):
    """Run the interprocedural verifier over one source text."""
    verifier = Verifier()
    try:
        verifier.add_source(src, filename)
    except SyntaxError as exc:
        return [Diagnostic.make(
            "HVD001", f"syntax error: {exc.msg}",
            file=filename, line=exc.lineno or 0)]
    return _suppress(verifier.run(), verifier.corpus)


def verify_paths(paths):
    """Run the interprocedural verifier over every ``.py`` file under
    ``paths``; one shared corpus, so cross-file call chains resolve."""
    verifier = Verifier()
    for path in iter_python_files(paths):
        verifier.add_path(path)
    return _suppress(verifier.run(), verifier.corpus)


def extract_schedule(src, filename="<string>"):
    """Symbolic per-rank collective schedule of one source text: a list
    of ``{function, kind, name, process_set, line, context}`` dicts in
    program order per function."""
    verifier = Verifier()
    verifier.add_source(src, filename)
    return verifier.schedules()
