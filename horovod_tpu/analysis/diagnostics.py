"""Structured diagnostics shared by every hvd-lint layer.

A finding is a :class:`Diagnostic`: rule id + severity + message +
``file:line`` + a fix hint. The rule catalog lives here so the jaxpr
analyzer, the AST linter, the runtime guard, and the CLI agree on ids
and severities (full prose catalog: docs/lint.md).
"""

import dataclasses
import os

ERROR = "error"
WARNING = "warning"

#: rule id -> (severity, one-line title)
RULES = {
    "HVD001": (ERROR, "file does not parse"),
    # -- jaxpr layer -------------------------------------------------------
    "HVD101": (ERROR, "collective axis name is not bound by any enclosing "
                      "mesh/shard_map"),
    "HVD102": (ERROR, "collective under rank-dependent control flow "
                      "(SPMD deadlock shape)"),
    "HVD103": (ERROR, "paired collectives disagree on dtype/shape across "
                      "branches"),
    # -- AST layer ---------------------------------------------------------
    "HVD201": (ERROR, "collective call guarded by a rank condition"),
    "HVD202": (WARNING, "initial broadcast_parameters/"
                        "broadcast_optimizer_state missing after init()"),
    "HVD203": (WARNING, "auto-named collective inside rank-dependent "
                        "control flow"),
    "HVD204": (ERROR, "checkpoint save/restore call guarded by a rank "
                      "condition (they barrier/broadcast internally)"),
    "HVD205": (WARNING, "lossy compressor applied to an integer/bool "
                        "tensor or a broadcast/initial-sync collective "
                        "(compression is for gradient reduction only)"),
    "HVD206": (WARNING, "per-tensor eager allreduce inside a loop "
                        "(serializes per-collective latency; use "
                        "grouped_allreduce or DistributedOptimizer's "
                        "bucketed dispatch)"),
    "HVD207": (WARNING, "raw time.time()/perf_counter() begin/end pair "
                        "feeding a metric observe() — use the "
                        "telemetry.spans.span API (one instrument for "
                        "histogram + timeline + trace plane)"),
    "HVD208": (ERROR, "ZeRO sharded update (zero=/HVDTPU_ZERO) combined "
                      "with Adasum or a non-global process set "
                      "(per-tensor Adasum semantics don't "
                      "reduce-scatter; a sub-cohort derives a wrong "
                      "shard plan — DistributedOptimizer rejects both "
                      "at __init__)"),
    "HVD209": (WARNING, "lossy compressor applied to an index tensor "
                        "or to the indices half of a sparse gradient "
                        "(indices must be exact — a rounded row id "
                        "scatter-adds into the wrong row with no "
                        "arithmetic error to catch it)"),
    "HVD210": (WARNING, "unbounded request buffering (bare "
                        "queue.Queue()/deque()/list-append) in serving "
                        "scheduler/router/handler code — backpressure "
                        "requires bounded queues that reject when full"),
    "HVD211": (WARNING, "hand-rolled resharding: device_get of a "
                        "sharded tree flowing (through reshape/concat "
                        "hops) into device_put outside "
                        "horovod_tpu/resharding/ — materializes the "
                        "full replica on host and skips the planner's "
                        "memory bound, digest verification, and "
                        "hvd-sim proofs"),
    "HVD212": (WARNING, "direct worker spawn/terminate "
                        "(SlotProcess(...) / terminate/kill on a "
                        "worker process handle) outside the driver/"
                        "actuator modules — hand-rolled cohort "
                        "mutation bypasses the journal, the fleet "
                        "lease ledger, and blacklist accounting"),
    "HVD213": (WARNING, "silent degradation: an except clause in "
                        "serving/fleet code swallows a transport error "
                        "(OSError, ConnectionError, URLError, "
                        "HTTPException, TimeoutError, ...) without a "
                        "log, metric, or re-raise — the failure "
                        "disappears and the fallback ladder "
                        "(docs/serving.md) loses its audit trail"),
    # -- interprocedural schedule verifier (hvd-lint verify) ---------------
    "HVD401": (ERROR, "collective reachable under rank-tainted control "
                      "flow through any call depth (the whole-program "
                      "generalization of HVD102/HVD201)"),
    "HVD402": (ERROR, "loop containing a collective whose trip count is "
                      "rank-tainted or data-dependent (schedule-length "
                      "divergence: ranks submit different collective "
                      "counts and the job stalls)"),
    "HVD403": (ERROR, "early return/raise/continue under a rank-tainted "
                      "condition skips a collective other ranks "
                      "execute"),
    "HVD404": (ERROR, "collectives on distinct process sets interleaved "
                      "where relative order can differ per rank "
                      "(deadlock by cross-set wait cycle)"),
    "HVD405": (ERROR, "per-tensor-semantics reduction (Adasum) routed "
                      "through a bucketing/concatenating path (its "
                      "scale-invariant combination is defined per whole "
                      "tensor; bucketing silently changes the math)"),
    # -- symbolic schedule simulator (analysis/simulate.py) ----------------
    "HVD501": (ERROR, "proven deadlock: symbolic N-rank simulation of the "
                      "extracted schedules finds irreconcilable per-rank "
                      "collective sequences (counterexample trace "
                      "attached — one event list per symbolic rank up to "
                      "the hang point)"),
    "HVD502": (ERROR, "proven digest mismatch: a matched collective slot "
                      "diverges in statically-computable fields "
                      "(kind/op) across symbolic ranks — the guardian "
                      "abort foretold at lint time"),
    "HVD503": (WARNING, "possible hang: bounded schedule simulation "
                        "(scenario caps, loop widening, inline depth) "
                        "could neither prove nor refute divergence "
                        "under rank-tainted control flow"),
    # -- cost-model layer: static performance (hvd-lint perf) --------------
    "HVD601": (WARNING, "bucket size pessimal at target scale: a "
                        "literal bucket-bytes knob sits >=2x away "
                        "from the cost model's predicted optimum at "
                        "the largest probed cohort"),
    "HVD602": (WARNING, "serialization point on the predicted "
                        "critical path: a per-step barrier or "
                        "synchronous per-tensor submits with zero "
                        "overlap opportunity"),
    "HVD603": (WARNING, "predicted scale cliff: the modeled comm "
                        "fraction crosses 50% between two probed "
                        "cohort sizes (the step goes "
                        "communication-bound)"),
    # -- AST layer: concurrency & liveness (hvd-sanitize) ------------------
    "HVD301": (WARNING, "mutable attribute shared between a thread "
                        "target and other methods written without a "
                        "lock"),
    "HVD302": (ERROR, "lock acquired outside `with` / try-finally "
                      "(an exception leaks the lock and wedges every "
                      "later acquirer)"),
    "HVD303": (WARNING, "unbounded blocking call inside a "
                        "cycle/watchdog/heartbeat loop body"),
    "HVD304": (WARNING, "HVDTPU_*/HOROVOD_* env read bypassing "
                        "utils/envparse.py (prefix fallback + knob "
                        "registry)"),
    "HVD305": (WARNING, "thread started with neither daemon=True nor "
                        "a join path"),
    "HVD306": (ERROR, "knob registry and docs/knobs.md disagree"),
    "HVD307": (ERROR, "metric registry and docs/metrics.md disagree"),
    # HVD7xx — protocol model checking (hvd-model, docs/modelcheck.md).
    "HVD701": (ERROR, "protocol safety invariant violated (minimized "
                      "counterexample attached)"),
    "HVD702": (ERROR, "protocol liveness goal unreachable under fair "
                      "scheduling (the protocol wedges once faults "
                      "stop)"),
    "HVD703": (WARNING, "model exploration exhausted its "
                        "depth/state/wall-clock budget before "
                        "covering the bounded space"),
    "HVD704": (WARNING, "actuation issued before the durable "
                        "ledger/journal write in a protocol module "
                        "(a crash in the window strands the effect)"),
    "HVD705": (WARNING, "KV/store write without a term= fence inside "
                        "a protocol module (stale-primary mutations "
                        "slip the split-brain fence)"),
}

_SEV_ORDER = {ERROR: 0, WARNING: 1}


@dataclasses.dataclass
class Diagnostic:
    """One finding, renderable as text or JSON.

    ``trace`` is the structured per-symbolic-rank counterexample the
    schedule simulator (analysis/simulate.py) attaches to proven
    HVD501/502 findings — rendered as SARIF ``codeFlows`` and by the
    CLI text formatter; ``None`` for every other rule."""

    rule: str
    severity: str
    message: str
    file: str = "<unknown>"
    line: int = 0
    hint: str = ""
    trace: dict = None

    @classmethod
    def make(cls, rule, message, file="<unknown>", line=0, hint="",
             trace=None):
        severity = RULES.get(rule, (ERROR, ""))[0]
        return cls(rule=rule, severity=severity, message=message,
                   file=file, line=int(line or 0), hint=hint,
                   trace=trace)

    @property
    def location(self):
        return f"{self.file}:{self.line}"

    def format(self):
        out = f"{self.location}: {self.severity} {self.rule}: {self.message}"
        if self.hint:
            out += f"\n    hint: {self.hint}"
        return out

    def to_dict(self):
        out = dataclasses.asdict(self)
        if out.get("trace") is None:
            del out["trace"]
        return out

    def sort_key(self):
        return (self.file, self.line, _SEV_ORDER.get(self.severity, 9),
                self.rule)


def relative_to_cwd(path, posix=False):
    """``path`` relative to cwd when it sits under it (stable across
    checkouts — what baseline keys, SARIF uris, and rendered locations
    all want to agree on), unchanged otherwise. ``posix=True`` forces
    forward slashes for serialized forms."""
    try:
        rel = os.path.relpath(path)
    except ValueError:
        rel = path
    if not rel.startswith(".."):
        path = rel
    return path.replace(os.sep, "/") if posix else path


def dedupe(diags):
    """Drop exact repeats (a fixpoint re-walk of a ``while`` body reports
    the same eqn more than once), preserving first-seen order."""
    seen, out = set(), []
    for d in diags:
        key = (d.rule, d.file, d.line, d.message)
        if key not in seen:
            seen.add(key)
            out.append(d)
    return out


def worst_severity(diags):
    if any(d.severity == ERROR for d in diags):
        return ERROR
    if diags:
        return WARNING
    return None
