"""``hvd-lint explain``: postmortem bundle → first divergent slot →
source line.

The flight recorder (tracing/recorder.py) leaves a per-rank postmortem
bundle on every coordinated abort: the last N trace records of every
live rank. This module closes the feedback loop the simulator opens at
lint time — it aligns the bundle's *runtime* per-rank submission
(``sub``) / completion (``fin``) sequences against the *statically
extracted* schedule of the program that produced them, finds the first
slot where the cohort diverged, and maps it back to the exact source
line (f-string collective names like ``f"step{epoch}"`` are matched
through the patterns the schedule extractor records).

Divergence taxonomy (mirrors the simulator's rule family):

- ``missing_submission`` → **HVD501**: some rank(s) never submitted a
  slot the others are waiting in — the runtime incarnation of a proven
  schedule fork (the guardian's "never submitted by rank(s) …" abort).
- ``field_mismatch`` → **HVD502**: every rank submitted the slot but
  with diverging collective kinds — the digest-mismatch abort.
- ``never_finished`` → **HVD503**: every rank submitted compatibly and
  the collective still never completed — a runtime stall
  (backend/network/chaos), not a schedule divergence; static analysis
  cannot prove more, so it stays a "possible hang" diagnosis.

Consumes :func:`horovod_tpu.tracing.merge.load_paths` /
:func:`bundle_by_rank` — one loader for every forensic consumer.
Pure stdlib + tracing.merge — no jax imports.
"""

import json
import os
import re

from .diagnostics import RULES, relative_to_cwd
from .schedule import Verifier
from .ast_lint import iter_python_files

#: how many trailing runtime events to show per rank in the report
_TAIL_EVENTS = 6


class ExplainError(ValueError):
    """Unusable bundle (no postmortem shards / no events)."""


def _load_bundle(bundle_dir):
    from ..tracing import merge
    shards = merge.load_paths([bundle_dir],
                              kinds=(merge.POSTMORTEM_PREFIX,))
    version, by_rank = merge.bundle_by_rank(shards)
    if not by_rank:
        raise ExplainError(
            f"no postmortem shards (postmortem.*.jsonl) under "
            f"{bundle_dir} — postmortems are dumped by the flight "
            "recorder on guardian aborts (docs/fault_tolerance.md)")
    return version, by_rank


def _rank_sequences(by_rank):
    """Per rank: ordered submissions + completion set, clock-aligned
    (meta ``off`` subtracted, the same alignment the trace merger
    applies)."""
    seqs = {}
    for rank, shard in sorted(by_rank.items()):
        off = shard["meta"].get("off") or 0.0
        subs, fins = [], set()
        for rec in shard["events"]:
            e = rec.get("e")
            if e == "sub":
                subs.append({"name": rec.get("n"),
                             "occ": rec.get("o", 0),
                             "kind": rec.get("k"),
                             "t": (rec.get("t") or 0.0) - off})
            elif e == "fin":
                fins.add((rec.get("n"), rec.get("o", 0)))
        seqs[rank] = {"subs": subs, "fins": fins}
    return seqs


def _find_divergence(seqs):
    """The first slot (name × occurrence) the cohort disagreed on,
    ordered by earliest aligned submit time. Returns None when every
    observed slot is fully submitted, compatible, and finished."""
    ranks = sorted(seqs)
    slots = {}
    for rank in ranks:
        for sub in seqs[rank]["subs"]:
            slot = slots.setdefault((sub["name"], sub["occ"]), {})
            slot[rank] = sub
    out = []
    for (name, occ), per_rank in slots.items():
        t0 = min(s["t"] for s in per_rank.values())
        # A rank whose sub record fell off the bounded flight ring but
        # whose fin record survived DID submit the slot (a completion
        # proves the submission) — window eviction, not divergence.
        missing = [r for r in ranks
                   if r not in per_rank
                   and (name, occ) not in seqs[r]["fins"]]
        kinds = {s["kind"] for s in per_rank.values()
                 if s["kind"] is not None}
        unfinished = [r for r in per_rank
                      if (name, occ) not in seqs[r]["fins"]]
        if missing:
            out.append((t0, "missing_submission", name, occ,
                        per_rank, missing))
        elif len(kinds) > 1:
            out.append((t0, "field_mismatch", name, occ, per_rank,
                        []))
        elif unfinished:
            out.append((t0, "never_finished", name, occ, per_rank,
                        unfinished))
    if not out:
        return None
    t0, dtype, name, occ, per_rank, involved = min(
        out, key=lambda item: item[0])
    return {"type": dtype, "name": name, "occurrence": occ,
            "submitted": per_rank, "involved": involved, "t": t0}


_RULE_FOR = {"missing_submission": "HVD501",
             "field_mismatch": "HVD502",
             "never_finished": "HVD503"}


def _static_sources(program_paths):
    """Extract the program's schedule events: ``(name -> sites)`` for
    constant names plus a list of ``(regex, site)`` for f-string
    names. A site is ``{file, line, kind, context}``."""
    verifier = Verifier()
    loaded = False
    for path in iter_python_files(program_paths):
        if verifier.add_path(path) is not None:
            loaded = True
    if program_paths and not loaded:
        raise ExplainError(
            "no analyzable .py file under --program path(s): "
            + ", ".join(map(str, program_paths)))
    verifier._fixpoint()
    exact, patterns = {}, []
    for mod_path in sorted(verifier.corpus.modules):
        mod = verifier.corpus.modules[mod_path]
        for qual in sorted(mod.funcs):
            fn = mod.funcs[qual]
            for ev in fn.events:
                site = {"file": relative_to_cwd(mod.path),
                        "line": ev.line, "kind": ev.kind,
                        "function": qual,
                        "context": [fr.describe() for fr in ev.ctx]}
                if ev.name is not None:
                    exact.setdefault(ev.name, []).append(site)
                elif ev.pattern is not None:
                    try:
                        patterns.append((re.compile(ev.pattern),
                                         site))
                    except re.error:
                        continue
    return exact, patterns


def _locate(name, kind, exact, patterns):
    """Source site(s) for a runtime collective name: exact ``name=``
    constants first, then f-string patterns; sites whose static kind
    matches the runtime kind are preferred."""
    candidates = list(exact.get(name, []))
    if not candidates and name is not None:
        candidates = [site for rx, site in patterns
                      if rx.fullmatch(name)]
    if kind:
        matching = [s for s in candidates if s["kind"] == kind]
        if matching:
            candidates = matching
    return candidates


def _check_programs(program_paths):
    """A named program path that does not exist is an
    :class:`ExplainError` — a typo'd ``--program`` must not silently
    degrade to 'no source mapping', even on a bundle with no
    divergence to map."""
    for p in program_paths:
        if not os.path.exists(p):
            raise ExplainError(f"program path not found: {p}")


def explain_bundle(bundle_dir, program_paths=()):
    """Analyze a postmortem bundle; returns the report dict. Raises
    :class:`ExplainError` when the directory holds no usable bundle
    or a ``program_paths`` entry does not exist."""
    _check_programs(program_paths)
    version, by_rank = _load_bundle(bundle_dir)
    seqs = _rank_sequences(by_rank)
    ranks = sorted(seqs)
    report = {
        "bundle": bundle_dir,
        "version": version,
        "ranks": ranks,
        "world_size": by_rank[ranks[0]]["meta"].get("size"),
        "reason": by_rank[ranks[0]]["meta"].get("reason"),
        "slots_observed": len({(s["name"], s["occ"])
                               for r in ranks
                               for s in seqs[r]["subs"]}),
        "tail": {r: seqs[r]["subs"][-_TAIL_EVENTS:] for r in ranks},
        "divergence": None,
    }
    div = _find_divergence(seqs)
    if div is None:
        return report
    rule = _RULE_FOR[div["type"]]
    entry = {
        "type": div["type"],
        "rule": rule,
        "rule_title": RULES[rule][1],
        "name": div["name"],
        "occurrence": div["occurrence"],
        "submitted_by": sorted(div["submitted"]),
        "involved_ranks": div["involved"],
        "sources": [],
    }
    kinds = {s["kind"] for s in div["submitted"].values()
             if s["kind"] is not None}
    entry["kinds"] = sorted(kinds)
    if program_paths:
        exact, patterns = _static_sources(program_paths)
        kind = next(iter(kinds)) if len(kinds) == 1 else None
        entry["sources"] = _locate(div["name"], kind, exact, patterns)
    report["divergence"] = entry
    return report


def render_report(report):
    """Human-readable explanation (the ``hvd-lint explain`` output)."""
    lines = [
        f"hvd-lint explain: postmortem bundle {report['bundle']}",
        f"  ranks: {report['ranks']} (world size "
        f"{report['world_size']}, elastic version {report['version']},"
        f" abort reason: {report['reason']})",
        f"  slots observed: {report['slots_observed']}",
    ]
    div = report["divergence"]
    if div is None:
        lines.append(
            "  no divergent slot: every observed collective was "
            "submitted by every rank, compatibly, and completed — "
            "the abort cause is outside the recorded window")
        return "\n".join(lines)
    slot = f"`{div['name']}` occurrence {div['occurrence']}"
    lines.append(f"  first divergent slot: {slot}")
    if div["type"] == "missing_submission":
        lines.append(
            f"    submitted by rank(s) {div['submitted_by']}; NEVER "
            f"submitted by rank(s) {div['involved_ranks']}")
    elif div["type"] == "field_mismatch":
        lines.append(
            f"    every rank submitted it, but kinds diverge: "
            f"{div['kinds']}")
    else:
        lines.append(
            f"    every rank submitted it compatibly; rank(s) "
            f"{div['involved_ranks']} never saw it finish (runtime "
            "stall, not a schedule divergence)")
    lines.append(f"  diagnosis: {div['rule']} — {div['rule_title']}")
    if div["sources"]:
        for site in div["sources"][:3]:
            ctx = ("; context: " + ", ".join(site["context"])
                   if site["context"] else "")
            lines.append(
                f"  source: {site['file']}:{site['line']} "
                f"`{site['kind']}` in {site['function']}{ctx}")
    else:
        lines.append(
            "  source: pass --program <train.py> to map the slot "
            "back to the submitting call site")
    return "\n".join(lines)


def to_json(report):
    return json.dumps(report, indent=1, sort_keys=True, default=str)
