"""SARIF 2.1.0 emitter for hvd-lint findings (``--format sarif``).

One run object, one driver (``hvd-lint``), rule metadata pulled from
the shared catalog (diagnostics.RULES) for every rule that appears in
the output, one result per finding with a physical location and the
content-addressed baseline key as a partial fingerprint. Findings
suppressed by a ``--baseline`` file are still emitted — with a
``suppressions`` entry of kind ``external`` — so CI code-scanning UIs
show them as suppressed instead of silently losing them (that is the
SARIF-blessed way to ship warning-strength rules without a flag-day).

Spec: SARIF 2.1.0 (OASIS). The emitted document restricts itself to
required properties plus the widely-consumed optional ones
(``rules``, ``partialFingerprints``, ``suppressions``), so it loads in
GitHub code scanning and the VS Code SARIF viewer.
"""

from .baseline import finding_keys
from .diagnostics import ERROR, RULES, relative_to_cwd

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/"
                "sarif-spec/master/Schemata/sarif-schema-2.1.0.json")
_INFO_URI = "https://example.invalid/horovod_tpu/docs/lint.md"


def _tool_version():
    try:
        from .. import __version__
        return str(__version__)
    except Exception:  # noqa: BLE001 — metadata only
        return "0.0.0"


def _level(severity):
    # SARIF level vocabulary: "error" | "warning" | "note" | "none"
    return "error" if severity == ERROR else "warning"


def _uri(path):
    """Relative forward-slash URI when the file sits under cwd (stable
    across checkouts — what baselines and CI artifacts want), the
    original path otherwise."""
    return relative_to_cwd(path, posix=True)


def _thread_flow_location(file, line, text):
    return {"location": {
        "physicalLocation": {
            "artifactLocation": {"uri": _uri(file)},
            "region": {"startLine": max(1, int(line or 0))},
        },
        "message": {"text": text},
    }}


def _code_flows(diag):
    """The simulator's counterexample trace as a SARIF
    ``codeFlows``/``threadFlows`` object — ONE threadFlow per symbolic
    rank, so code-scanning UIs render the interleaving that deadlocks:
    each rank's matched prefix, then its blocked/mismatched head (or
    exhaustion), then the fork points that split the paths."""
    trace = getattr(diag, "trace", None)
    if not trace:
        return None
    thread_flows = []
    for entry in trace.get("ranks", []):
        locations = []
        for ev in entry.get("events", []):
            name = f" name={ev['name']!r}" if ev.get("name") else ""
            locations.append(_thread_flow_location(
                ev["file"], ev["line"],
                f"rank {entry['rank']}: {ev['kind']}{name} "
                f"[{ev['status']}]"))
        if entry.get("end") == "exhausted":
            anchor = trace.get("forks") or [
                {"file": diag.file, "line": diag.line}]
            locations.append(_thread_flow_location(
                anchor[0]["file"], anchor[0]["line"],
                f"rank {entry['rank']}: schedule exhausted — "
                "submits nothing further"))
        if not locations:
            locations.append(_thread_flow_location(
                diag.file, diag.line,
                f"rank {entry['rank']}: no collective submissions"))
        thread_flows.append({"id": f"rank {entry['rank']}",
                             "locations": locations})
    if not thread_flows:
        return None
    flow = {"threadFlows": thread_flows}
    forks = trace.get("forks", [])
    if forks:
        flow["message"] = {"text": "schedules fork at " + "; ".join(
            f"{f['file']}:{f['line']} ({f['why']})" for f in forks)}
    return [flow]


def to_sarif(diags, suppressed=()):
    """Build the SARIF 2.1.0 document for ``diags`` (new findings) plus
    ``suppressed`` (baseline-suppressed findings, emitted with a
    ``suppressions`` entry). Returns a plain dict — ``json.dump`` it."""
    diags = list(diags)
    suppressed = list(suppressed)
    every = diags + suppressed
    rule_ids = sorted({d.rule for d in every})
    rule_index = {rule: i for i, rule in enumerate(rule_ids)}
    rules = []
    for rule in rule_ids:
        severity, title = RULES.get(rule, (ERROR, rule))
        rules.append({
            "id": rule,
            "name": rule,
            "shortDescription": {"text": title or rule},
            "helpUri": _INFO_URI,
            "defaultConfiguration": {"level": _level(severity)},
        })
    keys = finding_keys(every)
    results = []
    for d, key in zip(every, keys):
        message = d.message + (f" (hint: {d.hint})" if d.hint else "")
        result = {
            "ruleId": d.rule,
            "ruleIndex": rule_index[d.rule],
            "level": _level(d.severity),
            "message": {"text": message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": _uri(d.file)},
                    "region": {"startLine": max(1, int(d.line or 0))},
                },
            }],
            "partialFingerprints": {"hvdLintKey/v1": key},
        }
        code_flows = _code_flows(d)
        if code_flows:
            result["codeFlows"] = code_flows
        if len(results) >= len(diags):
            result["suppressions"] = [{
                "kind": "external",
                "justification": "recorded in the hvd-lint baseline "
                                 "(--baseline); fails only when new "
                                 "findings appear",
            }]
        results.append(result)
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {
                "driver": {
                    "name": "hvd-lint",
                    "informationUri": _INFO_URI,
                    "version": _tool_version(),
                    "rules": rules,
                },
            },
            "results": results,
        }],
    }
