"""SARIF 2.1.0 emitter for hvd-lint findings (``--format sarif``).

One run object, one driver (``hvd-lint``), rule metadata pulled from
the shared catalog (diagnostics.RULES) for every rule that appears in
the output, one result per finding with a physical location and the
content-addressed baseline key as a partial fingerprint. Findings
suppressed by a ``--baseline`` file are still emitted — with a
``suppressions`` entry of kind ``external`` — so CI code-scanning UIs
show them as suppressed instead of silently losing them (that is the
SARIF-blessed way to ship warning-strength rules without a flag-day).

Spec: SARIF 2.1.0 (OASIS). The emitted document restricts itself to
required properties plus the widely-consumed optional ones
(``rules``, ``partialFingerprints``, ``suppressions``), so it loads in
GitHub code scanning and the VS Code SARIF viewer.
"""

import json
import sys

from .baseline import finding_keys
from .diagnostics import ERROR, RULES, relative_to_cwd

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/"
                "sarif-spec/master/Schemata/sarif-schema-2.1.0.json")
_INFO_URI = "https://example.invalid/horovod_tpu/docs/lint.md"


def _tool_version():
    try:
        from .. import __version__
        return str(__version__)
    except Exception:  # noqa: BLE001 — metadata only
        return "0.0.0"


def _level(severity):
    # SARIF level vocabulary: "error" | "warning" | "note" | "none"
    return "error" if severity == ERROR else "warning"


def _uri(path):
    """Relative forward-slash URI when the file sits under cwd (stable
    across checkouts — what baselines and CI artifacts want), the
    original path otherwise."""
    return relative_to_cwd(path, posix=True)


def _thread_flow_location(file, line, text):
    return {"location": {
        "physicalLocation": {
            "artifactLocation": {"uri": _uri(file)},
            "region": {"startLine": max(1, int(line or 0))},
        },
        "message": {"text": text},
    }}


def _code_flows(diag):
    """The simulator's counterexample trace as a SARIF
    ``codeFlows``/``threadFlows`` object — ONE threadFlow per symbolic
    rank, so code-scanning UIs render the interleaving that deadlocks:
    each rank's matched prefix, then its blocked/mismatched head (or
    exhaustion), then the fork points that split the paths."""
    trace = getattr(diag, "trace", None)
    if not trace:
        return None
    thread_flows = []
    for entry in trace.get("ranks", []):
        locations = []
        for ev in entry.get("events", []):
            name = f" name={ev['name']!r}" if ev.get("name") else ""
            locations.append(_thread_flow_location(
                ev["file"], ev["line"],
                f"rank {entry['rank']}: {ev['kind']}{name} "
                f"[{ev['status']}]"))
        if entry.get("end") == "exhausted":
            anchor = trace.get("forks") or [
                {"file": diag.file, "line": diag.line}]
            locations.append(_thread_flow_location(
                anchor[0]["file"], anchor[0]["line"],
                f"rank {entry['rank']}: schedule exhausted — "
                "submits nothing further"))
        if not locations:
            locations.append(_thread_flow_location(
                diag.file, diag.line,
                f"rank {entry['rank']}: no collective submissions"))
        thread_flows.append({"id": f"rank {entry['rank']}",
                             "locations": locations})
    if not thread_flows:
        return None
    flow = {"threadFlows": thread_flows}
    forks = trace.get("forks", [])
    if forks:
        flow["message"] = {"text": "schedules fork at " + "; ".join(
            f"{f['file']}:{f['line']} ({f['why']})" for f in forks)}
    return [flow]


def to_sarif(diags, suppressed=(), tool="hvd-lint"):
    """Build the SARIF 2.1.0 document for ``diags`` (new findings) plus
    ``suppressed`` (baseline-suppressed findings, emitted with a
    ``suppressions`` entry). ``tool`` names the driver — every emitter
    in the package (``hvd-lint``, the perf sweep, ``hvd-model``) routes
    through this one builder so the artifacts stay schema-identical.
    Returns a plain dict — ``json.dump`` it."""
    diags = list(diags)
    suppressed = list(suppressed)
    every = diags + suppressed
    rule_ids = sorted({d.rule for d in every})
    rule_index = {rule: i for i, rule in enumerate(rule_ids)}
    rules = []
    for rule in rule_ids:
        severity, title = RULES.get(rule, (ERROR, rule))
        rules.append({
            "id": rule,
            "name": rule,
            "shortDescription": {"text": title or rule},
            "helpUri": _INFO_URI,
            "defaultConfiguration": {"level": _level(severity)},
        })
    keys = finding_keys(every)
    results = []
    for d, key in zip(every, keys):
        message = d.message + (f" (hint: {d.hint})" if d.hint else "")
        result = {
            "ruleId": d.rule,
            "ruleIndex": rule_index[d.rule],
            "level": _level(d.severity),
            "message": {"text": message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": _uri(d.file)},
                    "region": {"startLine": max(1, int(d.line or 0))},
                },
            }],
            "partialFingerprints": {"hvdLintKey/v1": key},
        }
        code_flows = _code_flows(d)
        if code_flows:
            result["codeFlows"] = code_flows
        if len(results) >= len(diags):
            result["suppressions"] = [{
                "kind": "external",
                "justification": "recorded in the hvd-lint baseline "
                                 "(--baseline); fails only when new "
                                 "findings appear",
            }]
        results.append(result)
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {
                "driver": {
                    "name": tool,
                    "informationUri": _INFO_URI,
                    "version": _tool_version(),
                    "rules": rules,
                },
            },
            "results": results,
        }],
    }


def write_sarif(path, diags, suppressed=(), tool="hvd-lint"):
    """Serialize :func:`to_sarif` to ``path`` (``None``/``"-"`` means
    stdout) with the one canonical encoding every CI artifact uses
    (sorted keys, indent 1, trailing newline). Returns the document."""
    doc = to_sarif(diags, suppressed=suppressed, tool=tool)
    text = json.dumps(doc, indent=1, sort_keys=True) + "\n"
    if path in (None, "-"):
        sys.stdout.write(text)
    else:
        with open(path, "w", encoding="utf-8") as fh:
            fh.write(text)
    return doc


# -- artifact validation (python -m horovod_tpu.analysis.sarif) ------------

def _results(doc):
    return [r for run in doc.get("runs", [])
            for r in run.get("results", [])]


def validate(doc, require_rules=(), require_families=(),
             require_flows=(), forbid_locations=(), expect_none=False):
    """Structural checks for one SARIF artifact; the list of failure
    messages (empty = pass). This is the single gate scripts/ci_lint.sh
    runs over every leg's artifact, replacing the per-leg ad-hoc
    canaries.

    - ``require_rules``: each named rule must appear among result
      ruleIds.
    - ``require_families``: each prefix (e.g. ``HVD5``) must match at
      least one result ruleId.
    - ``require_flows``: ``("RULE", n)`` pairs — every result of RULE
      must carry a codeFlow with at least n threadFlows.
    - ``forbid_locations``: no result location URI may contain the
      substring.
    - ``expect_none``: there must be no unsuppressed result at all.
    """
    problems = []
    if doc.get("version") != SARIF_VERSION:
        problems.append(f"version {doc.get('version')!r} != "
                        f"{SARIF_VERSION}")
    results = _results(doc)
    live = [r for r in results if not r.get("suppressions")]
    seen = {r.get("ruleId", "") for r in results}
    for rule in require_rules:
        if rule not in seen:
            problems.append(f"required rule {rule} missing "
                            f"(saw: {', '.join(sorted(seen)) or 'none'})")
    for family in require_families:
        if not any(rid.startswith(family) for rid in seen):
            problems.append(f"no result from family {family}*")
    for rule, min_flows in require_flows:
        for r in results:
            if r.get("ruleId") != rule:
                continue
            flows = r.get("codeFlows") or []
            n = len(flows[0].get("threadFlows", [])) if flows else 0
            if n < min_flows:
                problems.append(
                    f"{rule} result has {n} threadFlows < {min_flows}")
    for needle in forbid_locations:
        for r in results:
            for loc in r.get("locations", []):
                uri = (loc.get("physicalLocation", {})
                       .get("artifactLocation", {}).get("uri", ""))
                if needle in uri:
                    problems.append(
                        f"{r.get('ruleId')} hit forbidden location "
                        f"{uri} (contains {needle!r})")
    if expect_none and live:
        rids = sorted({r.get("ruleId", "") for r in live})
        problems.append(f"expected a clean artifact but found "
                        f"{len(live)} result(s): {', '.join(rids)}")
    return problems


def main(argv=None):
    import argparse
    parser = argparse.ArgumentParser(
        prog="python -m horovod_tpu.analysis.sarif",
        description="Validate a SARIF artifact's structure (the CI "
                    "gate shared by the hvd-lint, perf, and hvd-model "
                    "legs).")
    parser.add_argument("path", help="SARIF file to check")
    parser.add_argument("--require-rule", action="append", default=[],
                        metavar="RULE")
    parser.add_argument("--require-family", action="append",
                        default=[], metavar="PREFIX")
    parser.add_argument("--require-flows", action="append", default=[],
                        metavar="RULE:MIN",
                        help="every RULE result needs >= MIN "
                             "threadFlows")
    parser.add_argument("--forbid-location", action="append",
                        default=[], metavar="SUBSTRING")
    parser.add_argument("--expect-none", action="store_true",
                        help="fail on any unsuppressed result")
    args = parser.parse_args(argv)
    flows = []
    for spec in args.require_flows:
        rule, _, min_flows = spec.partition(":")
        if not min_flows.isdigit():
            parser.error(f"--require-flows wants RULE:MIN, got {spec!r}")
        flows.append((rule, int(min_flows)))
    try:
        with open(args.path, encoding="utf-8") as fh:
            doc = json.load(fh)
    except (OSError, ValueError) as exc:
        print(f"sarif-check: {args.path}: unreadable: {exc}",
              file=sys.stderr)
        return 2
    problems = validate(
        doc, require_rules=args.require_rule,
        require_families=args.require_family, require_flows=flows,
        forbid_locations=args.forbid_location,
        expect_none=args.expect_none)
    if problems:
        for p in problems:
            print(f"sarif-check: {args.path}: {p}", file=sys.stderr)
        return 1
    tool = (doc.get("runs") or [{}])[0].get("tool", {}) \
        .get("driver", {}).get("name", "?")
    print(f"sarif-check: {args.path} ok "
          f"({len(_results(doc))} result(s), tool {tool})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
