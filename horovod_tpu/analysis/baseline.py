"""Baseline workflow: land warning-strength rules without a flag-day.

``hvd-lint --write-baseline lint-baseline.json <paths>`` records every
current finding; subsequent runs with ``--baseline lint-baseline.json``
fail only on NEW findings — the recorded ones are reported as
suppressed (and marked so in SARIF output) until the code they flag is
actually touched.

Findings are keyed by **rule x file x content-hash of the flagged
line x occurrence index**, NOT by line number: editing an unrelated
part of the file shifts line numbers but not content hashes, so the
baseline survives rebases; editing the flagged line itself invalidates
its key, so the finding resurfaces exactly when someone touches the
code it is about. The occurrence index disambiguates identical lines
(two copy-pasted ``hvd.allreduce(x)`` both stay individually tracked).

File format (JSON, versioned)::

    {"version": 1, "tool": "hvd-lint",
     "findings": {"<rule>:<file>:<hash>:<n>": {"rule": ..., "file": ...,
                                               "line": ..., "message": ...}}}

The ``line``/``message`` fields are display metadata for humans
reading the baseline diff in review; only the key participates in
matching.
"""

import hashlib
import json
import os

from .diagnostics import relative_to_cwd

_VERSION = 1


def _norm_file(path):
    """Stable relative form of a finding's file (baselines are
    committed, so keys must not embed the checkout prefix)."""
    return relative_to_cwd(path, posix=True)


def _line_content(cache, path, line):
    if path not in cache:
        try:
            with open(path, encoding="utf-8", errors="replace") as f:
                cache[path] = f.read().splitlines()
        except OSError:
            cache[path] = None
    lines = cache[path]
    if lines is None or not (1 <= line <= len(lines)):
        return f"<line {line}>"
    return lines[line - 1].strip()


def finding_keys(diags):
    """Content-addressed key per finding, parallel to ``diags``.
    Deterministic: equal inputs, equal keys, independent of order."""
    cache = {}
    occurrence = {}
    keys = []
    for d in sorted(diags, key=lambda d: (d.file, d.line, d.rule)):
        content = _line_content(cache, d.file, int(d.line or 0))
        digest = hashlib.sha1(
            f"{d.rule}:{content}".encode("utf-8",
                                         "replace")).hexdigest()[:16]
        stem = f"{d.rule}:{_norm_file(d.file)}:{digest}"
        n = occurrence.get(stem, 0)
        occurrence[stem] = n + 1
        keys.append((id(d), f"{stem}:{n}"))
    order = {ident: key for ident, key in keys}
    return [order[id(d)] for d in diags]


def write_baseline(diags, path):
    """Record ``diags`` as the accepted baseline at ``path``."""
    findings = {}
    for d, key in zip(diags, finding_keys(diags)):
        findings[key] = {
            "rule": d.rule, "file": _norm_file(d.file),
            "line": int(d.line or 0), "message": d.message,
        }
    doc = {"version": _VERSION, "tool": "hvd-lint",
           "findings": dict(sorted(findings.items()))}
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return doc


def load_baseline(path):
    """Parsed baseline dict, or raise OSError/ValueError with a usable
    message (a corrupt baseline must fail loudly, not pass silently)."""
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or "findings" not in doc:
        raise ValueError(f"{path}: not an hvd-lint baseline "
                         "(missing 'findings')")
    if doc.get("version") != _VERSION:
        raise ValueError(f"{path}: baseline version "
                         f"{doc.get('version')!r} unsupported "
                         f"(expected {_VERSION})")
    return doc


def filter_new(diags, baseline_doc):
    """Split ``diags`` into (new, suppressed) against a loaded
    baseline. A key present in the baseline absorbs one finding per
    recorded occurrence — content changes resurface findings because
    the hash no longer matches."""
    known = set(baseline_doc.get("findings", {}))
    new, suppressed = [], []
    for d, key in zip(diags, finding_keys(diags)):
        (suppressed if key in known else new).append(d)
    return new, suppressed
