"""Static + runtime collective-correctness analysis (``hvd-lint``).

Three layers, one finding type (:class:`Diagnostic`):

1. **jaxpr analyzer** (:func:`check_fn` / :func:`check_jaxpr`) — walks a
   traced program and flags unbound collective axis names, collectives
   under rank-dependent ``cond``/``while``, and mismatched paired
   collectives across branches. Wired into the torch/tensorflow compile
   bridges behind their ``verify=`` flag.
2. **AST linter** (:func:`lint_paths` / :func:`lint_source`) — scans user
   scripts for rank-guarded collectives, missing initial broadcasts, and
   auto-named collectives under rank-dependent control flow. The
   ``hvd-lint`` CLI (analysis/cli.py) fronts this layer.
3. **interprocedural schedule verifier** (:func:`verify_paths` /
   :func:`verify_source` / :func:`extract_schedule`) — ``hvd-lint
   verify``: call graph + rank-dependence taint lattice + symbolic
   per-rank collective schedules, behind the HVD4xx rule family
   (analysis/schedule.py), then **executed** by the symbolic N-rank
   simulator (analysis/simulate.py, :func:`verify_and_simulate_paths`)
   whose lockstep matcher proves deadlocks/digest mismatches as
   HVD501/502 with per-rank counterexample traces (HVD503 for bounded
   approximations); ``hvd-lint explain`` (analysis/explain.py) maps a
   flight-recorder postmortem bundle back to the divergent slot's
   source line. SARIF 2.1.0 output (analysis/sarif.py, counterexamples
   as ``codeFlows``) and the content-hash baseline workflow
   (analysis/baseline.py) ride on the same Diagnostic stream.
4. **runtime order guard** (:class:`SubmissionOrderGuard`) — the opt-in
   ``HOROVOD_TPU_ORDER_CHECK=1`` dynamic backstop in the coordinator.
5. **runtime concurrency sanitizer** (``sanitizer``) — the opt-in
   ``HVDTPU_SANITIZE=1`` lock-order/liveness instrumentation behind the
   HVD3xx thread-safety rules (``hvd-lint --self`` runs the static
   side over this package itself).
6. **protocol model checker** (``protocol``/``hvd-model``) — the
   control-plane spec modules (analysis/protocol/) the HA journal,
   fleet ledger, and KV-migration runtimes execute, plus the
   explicit-state explorer that proves their invariants up to a
   bounded depth (HVD7xx; docs/modelcheck.md).

Public names resolve lazily (PEP 562): the control-plane runtime
imports the ``analysis.protocol`` spec modules on its hot import path,
so touching this package must not drag in jax, the parser stack, or
the simulator until a caller actually asks for them.

Rule catalog and suppression syntax: docs/lint.md.
"""

import importlib

from .diagnostics import (  # noqa: F401  (eager: stdlib-only)
    Diagnostic, RULES, ERROR, WARNING, dedupe, worst_severity,
)

#: public name -> the submodule that defines it (resolved on first use).
_LAZY_NAMES = {
    "check_fn": "jaxpr_lint", "check_jaxpr": "jaxpr_lint",
    "AliasResolver": "ast_lint", "lint_source": "ast_lint",
    "lint_file": "ast_lint", "lint_paths": "ast_lint",
    "iter_python_files": "ast_lint",
    "extract_schedule": "schedule", "verify_paths": "schedule",
    "verify_source": "schedule",
    "render_trace": "simulate", "simulate_paths": "simulate",
    "simulate_source": "simulate",
    "verify_and_simulate_paths": "simulate",
    "verify_and_simulate_source": "simulate",
    "ExplainError": "explain", "explain_bundle": "explain",
    "render_report": "explain",
    "to_sarif": "sarif", "write_sarif": "sarif",
    "filter_new": "baseline", "load_baseline": "baseline",
    "write_baseline": "baseline",
    "SubmissionOrderGuard": "order_guard",
}

_LAZY_MODULES = frozenset({
    "ast_lint", "baseline", "cli", "costmodel", "explain",
    "jaxpr_lint", "order_guard", "protocol", "sanitizer", "sarif",
    "schedule", "simulate",
})


def __getattr__(name):
    if name in _LAZY_NAMES:
        mod = importlib.import_module("." + _LAZY_NAMES[name], __name__)
        value = getattr(mod, name)
        globals()[name] = value
        return value
    if name in _LAZY_MODULES:
        mod = importlib.import_module("." + name, __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(_LAZY_NAMES) | _LAZY_MODULES)


def runtime_axis_sizes():
    """Axis sizes the initialized runtime's replica mesh binds — the
    default ``axis_sizes`` for verifying functions that will run under
    ``make_train_step``/``shard_map`` on that mesh. Empty when the
    runtime is not initialized."""
    from .. import basics
    if not basics.is_initialized():
        return {}
    return dict(basics.runtime().mesh.shape)


def enforce(diags, mode, what="function", logger=None):
    """Apply a ``verify=`` policy to analyzer findings.

    ``mode`` False/None: no-op. ``"warn"``: log every finding. ``True``
    or ``"error"``: log warnings, raise :class:`CollectiveLintError`
    when any error-severity finding exists.
    """
    if not mode or not diags:
        return diags
    from ..exceptions import CollectiveLintError
    if logger is None:
        from ..utils.logging_util import get_logger
        logger = get_logger()
    errors = [d for d in diags if d.severity == ERROR]
    for d in diags:
        logger.warning("hvd-lint [%s]: %s", what, d.format())
    if errors and mode is not False and mode != "warn":
        raise CollectiveLintError(errors)
    return diags


def verify_traceable(fn, args, kwargs=None, axis_sizes=None, mode=True,
                     what="compiled function"):
    """Trace ``fn`` and enforce the findings — the hook the compile
    bridges call behind ``verify=``. ``axis_sizes`` defaults to the
    runtime mesh's axes."""
    from .jaxpr_lint import check_fn
    if axis_sizes is None:
        axis_sizes = runtime_axis_sizes()
    diags = check_fn(fn, *args, axis_sizes=axis_sizes, **(kwargs or {}))
    return enforce(diags, mode, what=what)
