"""Layer 3: runtime submission-order guard.

The static layers cannot see dynamically-built name streams, so this is
the dynamic backstop (the analog of the reference controller noticing
rank-divergent request streams, reference: horovod/common/controller.cc
ComputeResponseList + stall_inspector.cc). Opt-in via
``HOROVOD_TPU_ORDER_CHECK=1``:

- every coordinator submission appends the tensor name to a running
  SHA-1 stream hash, and a **checkpoint digest** is snapshotted every
  ``checkpoint_every`` submissions;
- in SPMD mode a background checker periodically allgathers each rank's
  recent checkpoint digests and compares them at the newest submission
  index all ranks have reached — divergence raises
  :class:`SubmissionOrderError` naming the disagreeing ranks and the
  bounding submission window (count-aligned comparison: ranks at
  different submission counts are compared at a common checkpoint, not
  falsely flagged for mere skew);
- in single-controller mode the sequence is recorded instead and can be
  dumped as a JSON corpus for the linter's fixtures
  (``HOROVOD_TPU_ORDER_CHECK_RECORD=<path>``).

No jax imports; numpy only (digest payloads ride the eager allgather as
uint8 arrays). When the guard is off the coordinator holds ``None`` —
the hot path pays one attribute check and zero allocations.
"""

import hashlib
import json
import struct
import threading
from collections import deque

import numpy as np

from ..exceptions import SubmissionOrderError

DEFAULT_CHECKPOINT_EVERY = 64
DEFAULT_WINDOW = 16
_DIGEST_LEN = hashlib.sha1().digest_size  # 20
_HEADER = struct.Struct("<QQQ")  # checkpoint_every, latest_idx, n_digests


class SubmissionOrderGuard:
    """Per-process submission-sequence hasher + cross-rank comparator."""

    def __init__(self, rank=0, record=False,
                 checkpoint_every=DEFAULT_CHECKPOINT_EVERY,
                 window=DEFAULT_WINDOW, max_record=100_000):
        self.rank = rank
        self.checkpoint_every = int(checkpoint_every)
        self.window = int(window)
        self._hash = hashlib.sha1()
        self._count = 0
        self._lock = threading.Lock()
        # (checkpoint_index, digest) pairs; index k covers the first
        # k * checkpoint_every submissions.
        self._checkpoints = deque(maxlen=self.window)
        self._record = [] if record else None
        self._max_record = int(max_record)
        self.truncated = False

    # -- recording (coordinator submit path) ------------------------------
    def record(self, name, kind="", callsite=None):
        with self._lock:
            self._hash.update(name.encode("utf-8", "replace"))
            self._hash.update(b"\x00")
            self._count += 1
            if self._count % self.checkpoint_every == 0:
                self._checkpoints.append(
                    (self._count // self.checkpoint_every,
                     self._hash.copy().digest()))
            if self._record is not None:
                if len(self._record) < self._max_record:
                    self._record.append({
                        "n": self._count, "name": name, "kind": kind,
                        "site": callsite})
                else:
                    self.truncated = True

    @property
    def count(self):
        return self._count

    def digest(self):
        """Full-stream digest + count (exact comparison when two ranks
        are known to sit at the same submission count)."""
        with self._lock:
            return self._hash.copy().digest() + struct.pack(
                "<Q", self._count)

    # -- cross-rank protocol ----------------------------------------------
    def sync_payload(self):
        """Fixed-size uint8 array carrying the recent checkpoint digests;
        one allgather of these per check, any rank count."""
        with self._lock:
            cps = list(self._checkpoints)
        latest = cps[-1][0] if cps else 0
        buf = bytearray(_HEADER.pack(self.checkpoint_every, latest,
                                     len(cps)))
        for _, dg in cps:
            buf += dg
        buf += b"\x00" * ((self.window - len(cps)) * _DIGEST_LEN)
        return np.frombuffer(bytes(buf), dtype=np.uint8).copy()

    @staticmethod
    def _parse_payload(row):
        raw = bytes(np.asarray(row, dtype=np.uint8).tobytes())
        every, latest, n = _HEADER.unpack_from(raw, 0)
        digests = {}
        off = _HEADER.size
        for i in range(n):
            idx = latest - (n - 1 - i)
            digests[idx] = raw[off + i * _DIGEST_LEN:
                               off + (i + 1) * _DIGEST_LEN]
        return every, latest, digests

    @staticmethod
    def compare_payloads(rows):
        """Compare per-rank ``sync_payload`` rows (index = rank).

        Returns the checkpoint index compared, or ``None`` when no
        common checkpoint exists yet (early in the run / extreme skew).
        Raises :class:`SubmissionOrderError` on divergence.
        """
        parsed = [SubmissionOrderGuard._parse_payload(r) for r in rows]
        everies = {p[0] for p in parsed}
        if len(everies) != 1:
            raise ValueError(
                f"ORDER_CHECK checkpoint_every differs across ranks "
                f"({sorted(everies)}); set the same "
                "HOROVOD_TPU_ORDER_CHECK configuration everywhere")
        every = everies.pop()
        if any(p[1] == 0 for p in parsed):
            return None  # some rank has no checkpoint yet
        common = min(p[1] for p in parsed)
        if any(common not in p[2] for p in parsed):
            return None  # slid out of a rank's window
        groups = {}
        for rank, p in enumerate(parsed):
            groups.setdefault(p[2][common], []).append(rank)
        if len(groups) > 1:
            desc = "; ".join(
                f"ranks {r} -> {dg[:6].hex()}"
                for dg, r in sorted(groups.items(), key=lambda kv: kv[1]))
            raise SubmissionOrderError(
                f"collective submission order diverged across ranks "
                f"within the first {common * every} submissions "
                f"({desc}). Ranks are enqueueing named tensors in "
                "different orders or with different auto-generated "
                "names — typically a rank-dependent code path. Run "
                "`hvd-lint` on the training script (rules HVD201/"
                "HVD203, docs/lint.md); set "
                "HOROVOD_TPU_ORDER_CHECK_RECORD=<path> to dump each "
                "rank's sequence for diffing.")
        return common

    def verify(self, gathered, num_ranks):
        """Split a stacked/concatenated allgather result into per-rank
        rows and compare. Returns the checkpoint index compared."""
        arr = np.asarray(gathered, dtype=np.uint8).reshape(num_ranks, -1)
        return self.compare_payloads(list(arr))

    # -- fixture-corpus recording -----------------------------------------
    def dump(self, path):
        """Write the recorded sequence as JSON (one file per rank when
        the path contains ``{rank}``)."""
        if "{rank}" in path:
            path = path.format(rank=self.rank)
        with self._lock:
            payload = {
                "rank": self.rank,
                "count": self._count,
                "checkpoint_every": self.checkpoint_every,
                "digest": self._hash.copy().hexdigest(),
                "truncated": self.truncated,
                "sequence": list(self._record or ()),
            }
        with open(path, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=1)
        return path
