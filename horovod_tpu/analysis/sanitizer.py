"""hvd-sanitize runtime layer: concurrency & liveness sanitizer.

The control plane is a small crowd of background threads — coordinator
cycle loop, guardian watchdog scans, heartbeat lease, timeline writer,
runner HTTP server, telemetry pusher, data-loader prefetch — and the
failure modes that matter there (ABBA deadlocks, a blocking call
starving the cycle loop, a leaked thread pinning the process at exit)
never show up in unit tests that exercise one thread at a time. This
module is the runtime half of ``hvd-sanitize`` (the static half is the
HVD3xx rules in ast_lint.py); it is the thread-schedule analog of
verifying communication schedules before running them
(arXiv:2112.01075 applies that idea to collective schedules).

Three instruments, all gated by ``HVDTPU_SANITIZE``:

- **Lock-order graph** — ``make_lock``/``make_rlock``/``make_condition``
  factories return instrumented primitives that record, per process,
  the order in which locks nest ("acquired B while holding A" = edge
  A->B). An acquisition that would close a cycle raises
  :class:`~..exceptions.LockOrderError` *before* blocking, naming both
  acquisition stacks — the canonical ABBA deadlock caught at the first
  interleaving that could exhibit it, not the unlucky one that does.
- **Blocking-call tripwire** — threads that drive collectives register
  via ``mark_critical`` (the coordinator cycle loop, which also runs
  the watchdog scans); ``check_blocking`` call sites at the process's
  blocking choke points (``Handle.wait``, the KV client's ``urlopen``,
  worker spawns) plus a patched ``time.sleep`` (flagging sleeps longer
  than ``SLEEP_ALLOWANCE_S``) record a finding when executed on a
  critical thread — every such call starves every in-flight collective
  for its duration.
- **Shutdown thread-leak audit** — ``audit_shutdown`` (called by
  ``hvd.shutdown()``) names non-daemon threads still alive after
  teardown: the threads that will keep the interpreter hostage.

Cost model (the telemetry/chaos disabled-guard contract): with
``HVDTPU_SANITIZE`` unset the factories return *plain*
``threading.Lock``/``RLock``/``Condition`` objects — zero
instrumentation, zero wrappers — and ``mark_critical``/
``check_blocking``/``audit_shutdown`` cost one global read + compare.
``time.sleep`` is only patched while enabled; ``reset()`` restores it.
Pure stdlib — no jax/telemetry imports (the tripwire must be loadable
from the launcher process and from inside telemetry itself).
"""

import threading
import time
import traceback

from ..exceptions import LockOrderError
from ..utils import envparse
from ..utils.logging_util import get_logger

# A sleep at most this long on a critical thread is pacing, not
# blocking: the cycle loop's own `time.sleep(cycle_time_s)` (<= 10 ms
# even under autotune) and chaos `delay` defaults stay under it.
SLEEP_ALLOWANCE_S = 0.2
_STACK_LIMIT = 16


class Finding:
    """One runtime finding (blocking call or thread leak)."""

    __slots__ = ("kind", "what", "thread", "stack")

    def __init__(self, kind, what, thread, stack=""):
        self.kind = kind
        self.what = what
        self.thread = thread
        self.stack = stack

    def format(self):
        return f"hvd-sanitize [{self.kind}] {self.what} on {self.thread}"


def _stack_text(skip=2):
    """Formatted stack of the caller, trimmed of sanitizer frames."""
    return "".join(traceback.format_stack(limit=_STACK_LIMIT)[:-skip])


class _Sanitizer:
    """Per-process sanitizer state (exists only while enabled)."""

    def __init__(self):
        # Internal lock: PLAIN on purpose — instrumenting the graph's
        # own lock would recurse; it is a leaf held for dict ops only.
        self._mu = threading.Lock()
        # (holder_name, acquired_name) -> (thread_name, stack_text) at
        # the first time that nesting was observed.
        self._edges = {}
        self._adj = {}          # holder_name -> set(acquired_name)
        self._held = threading.local()
        self._allow = threading.local()  # depth of allowed() scopes
        self._critical = {}     # thread ident -> role
        self.findings = []
        self._finding_keys = set()
        self._log = get_logger()

    # -- lock-order graph --------------------------------------------------
    def _stack(self):
        held = getattr(self._held, "stack", None)
        if held is None:
            held = self._held.stack = []
        return held

    def before_acquire(self, lock, name):
        """Record nesting edges for ``name`` against every lock the
        current thread already holds; raise ``LockOrderError`` when the
        new edge closes a cycle. Runs BEFORE the real acquire so the
        report fires instead of the deadlock."""
        held = self._stack()
        if any(entry[0] is lock for entry in held):
            return  # reentrant acquire (RLock): no new ordering info
        stack_text = None
        for held_lock, held_name in held:
            if held_name == name:
                # A same-named sibling lock (two instances of one lock
                # class) nesting under itself: flag like a cycle — the
                # class has no instance order, so two threads nesting
                # opposite instances deadlock.
                self._raise_cycle(name, name, held_name)
            with self._mu:
                edge = (held_name, name)
                if edge in self._edges:
                    continue  # vetted when first recorded
                # Cycle check BEFORE recording: is held_name reachable
                # FROM name through previously recorded nestings? If
                # so, some code path acquires them in the opposite
                # order — raise WITHOUT inserting the reverse edge, or
                # the graph would be poisoned and the legitimate order
                # would raise forever after the first offender.
                if self._reachable(name, held_name):
                    first_on_path = self._first_edge_on_path(name,
                                                             held_name)
                    self._raise_cycle(name, held_name, first_on_path)
                if stack_text is None:
                    stack_text = _stack_text(skip=3)
                self._edges[edge] = (threading.current_thread().name,
                                     stack_text)
                self._adj.setdefault(held_name, set()).add(name)

    def after_acquire(self, lock, name):
        self._stack().append((lock, name))

    def after_release(self, lock):
        held = self._stack()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] is lock:
                del held[i]
                return

    def _reachable(self, src, dst):
        """DFS over the recorded nesting graph (caller holds _mu)."""
        seen = set()
        stack = [src]
        while stack:
            node = stack.pop()
            if node == dst:
                return True
            if node in seen:
                continue
            seen.add(node)
            stack.extend(self._adj.get(node, ()))
        return False

    def _first_edge_on_path(self, src, dst):
        """Some recorded edge leaving ``src`` on a path to ``dst`` —
        the reverse-order acquisition to show in the report (caller
        holds _mu). Falls back to the direct edge when present."""
        if (src, dst) in self._edges:
            return (src, dst)
        for nxt in self._adj.get(src, ()):
            if nxt == dst or self._reachable(nxt, dst):
                return (src, nxt)
        return None

    def _raise_cycle(self, acquiring, holding, edge_key_or_name):
        cur_thread = threading.current_thread().name
        cur_stack = _stack_text(skip=4)
        if acquiring == holding:
            prior = (f"(two distinct locks named {acquiring!r} nested "
                     "on one thread — a lock class cannot order its own "
                     "instances)")
        else:
            if isinstance(edge_key_or_name, tuple):
                edge = edge_key_or_name
            else:
                edge = (acquiring, holding)
            rec = self._edges.get(edge)
            if rec is None:
                prior = "(reverse-order acquisition stack not recorded)"
            else:
                prior = (f"-- first recorded {edge[0]!r} -> {edge[1]!r} "
                         f"nesting (thread {rec[0]!r}):\n{rec[1]}")
        raise LockOrderError(
            f"lock-order cycle: acquiring {acquiring!r} while holding "
            f"{holding!r} reverses a nesting recorded earlier in this "
            "process — two threads interleaving these paths can "
            "deadlock (ABBA).\n"
            f"-- current acquisition (thread {cur_thread!r}):\n"
            f"{cur_stack}{prior}\n"
            "Pick one global acquisition order (docs/lint.md, "
            "hvd-sanitize).")

    # -- blocking-call tripwire --------------------------------------------
    def mark_critical(self, role):
        self._critical[threading.get_ident()] = role

    def unmark_critical(self):
        self._critical.pop(threading.get_ident(), None)

    def critical_role(self):
        return self._critical.get(threading.get_ident())

    def push_allowed(self):
        self._allow.depth = getattr(self._allow, "depth", 0) + 1

    def pop_allowed(self):
        self._allow.depth = max(0, getattr(self._allow, "depth", 1) - 1)

    def note_blocking(self, what):
        role = self.critical_role()
        if role is None or getattr(self._allow, "depth", 0) > 0:
            return
        stack = _stack_text(skip=3)
        key = (role, what.split("(")[0], stack.splitlines()[-2:][0]
               if stack.splitlines() else "")
        thread = f"{role} thread ({threading.current_thread().name})"
        finding = Finding("blocking-call", what, thread, stack)
        with self._mu:
            # One finding (and one log line) per call-site: a blocking
            # call inside a ms-cadence loop must not grow the findings
            # list by one multi-KB stack per cycle for hours.
            if key in self._finding_keys:
                return
            self._finding_keys.add(key)
            self.findings.append(finding)
        self._log.warning(
            "hvd-sanitize: blocking call %s on the %s — it starves "
            "every in-flight collective for its duration; bound it "
            "(timeout=/deadline=) or move it off this thread. At:\n%s",
            what, thread, stack)

    # -- shutdown audit ----------------------------------------------------
    def audit_shutdown(self):
        current = threading.current_thread()
        leaks = []
        for t in threading.enumerate():
            if t is current or t is threading.main_thread():
                continue
            if t.daemon or not t.is_alive():
                continue
            leaks.append(t.name)
            with self._mu:
                self.findings.append(
                    Finding("thread-leak", f"non-daemon thread "
                            f"{t.name!r} still alive", t.name))
        if leaks:
            self._log.warning(
                "hvd-sanitize: %d non-daemon thread(s) still alive "
                "after shutdown(): %s — they will keep the process "
                "from exiting (start with daemon=True or join them "
                "before shutdown)", len(leaks), ", ".join(sorted(leaks)))
        return leaks


# -- instrumented primitives ------------------------------------------------

class TrackedLock:
    """A named Lock/RLock wrapper feeding the lock-order graph. Supports
    the full acquire/release + context-manager surface, and delegates
    the private Condition hooks so ``threading.Condition`` can wrap a
    tracked RLock."""

    __slots__ = ("_lock", "_name", "_san")

    def __init__(self, lock, name, san):
        self._lock = lock
        self._name = name
        self._san = san

    @property
    def name(self):
        return self._name

    def acquire(self, blocking=True, timeout=-1):
        # Non-blocking try-acquires are the standard deadlock-AVOIDANCE
        # pattern: they cannot deadlock, so they neither get the order
        # check (a reverse-order try is legitimate) nor record an edge
        # (a failed try must not poison the graph).
        if blocking:
            self._san.before_acquire(self, self._name)
        # Instrumented pass-through: callers own the release
        # discipline, TrackedLock.release() mirrors this acquire.
        # hvd-lint: disable=HVD302
        got = self._lock.acquire(blocking, timeout)
        if got:
            self._san.after_acquire(self, self._name)
        return got

    def release(self):
        self._lock.release()
        self._san.after_release(self)

    __enter__ = acquire

    def __exit__(self, *exc):
        self.release()

    def locked(self):
        return self._lock.locked()

    # Condition integration (only RLocks have these in CPython).
    def _is_owned(self):
        return self._lock._is_owned()

    def _release_save(self):
        state = self._lock._release_save()
        self._san.after_release(self)
        return state

    def _acquire_restore(self, state):
        self._lock._acquire_restore(state)
        self._san.after_acquire(self, self._name)


# -- module state -----------------------------------------------------------

_STATE = None       # tri-state: None = unresolved, False = off, _Sanitizer
_ORIG_SLEEP = None  # time.sleep before patching (only while enabled)
# Resolution must be serialized: two threads racing _resolve() could
# both see time.sleep unpatched, and the loser would capture the
# WRAPPER as _ORIG_SLEEP — every later sleep then recurses forever.
_RESOLVE_LOCK = threading.Lock()


# time.sleep as imported, before any patching — the fallback for a
# _traced_sleep already in flight when reset() nulls _ORIG_SLEEP.
_REAL_SLEEP = time.sleep


def _traced_sleep(seconds):
    s = _STATE
    if (s not in (None, False) and seconds > SLEEP_ALLOWANCE_S
            and s.critical_role() is not None):
        s.note_blocking(f"time.sleep({float(seconds):.3f}s)")
    orig = _ORIG_SLEEP
    (orig if orig is not None else _REAL_SLEEP)(seconds)


_traced_sleep.__hvd_sanitize__ = True


def _resolve():
    global _STATE, _ORIG_SLEEP
    with _RESOLVE_LOCK:
        if _STATE is not None:      # lost the race: already resolved
            return _STATE
        if envparse.get_bool(envparse.SANITIZE):
            state = _Sanitizer()
            if not getattr(time.sleep, "__hvd_sanitize__", False):
                _ORIG_SLEEP = time.sleep
                time.sleep = _traced_sleep
            _STATE = state
        else:
            _STATE = False
        return _STATE


def _state():
    s = _STATE
    return _resolve() if s is None else s


def enabled():
    """True when HVDTPU_SANITIZE is on. Resolved once, lazily, at the
    first factory/guard call (the telemetry/chaos pattern)."""
    return bool(_state())


def reset():
    """Drop all graph/finding state, restore ``time.sleep``, and
    re-resolve from the environment (test hook)."""
    global _STATE, _ORIG_SLEEP
    with _RESOLVE_LOCK:
        if _ORIG_SLEEP is not None:
            time.sleep = _ORIG_SLEEP
            _ORIG_SLEEP = None
        _STATE = None


def findings():
    """Recorded runtime findings (empty when disabled)."""
    s = _state()
    return list(s.findings) if s else []


def make_lock(name):
    """A ``threading.Lock`` — instrumented and named when the sanitizer
    is on, the plain primitive otherwise (zero added work)."""
    s = _state()
    if not s:
        return threading.Lock()
    return TrackedLock(threading.Lock(), name, s)


def make_rlock(name):
    s = _state()
    if not s:
        return threading.RLock()
    return TrackedLock(threading.RLock(), name, s)


def make_condition(name, lock=None):
    """A ``threading.Condition`` over a tracked RLock (or a caller-
    provided tracked lock) when on; a plain Condition otherwise."""
    s = _state()
    if not s:
        return threading.Condition(lock)
    return threading.Condition(lock if lock is not None
                               else make_rlock(name))


def mark_critical(role):
    """Register the current thread as collective-critical (cycle loop,
    watchdog): blocking calls on it become findings."""
    s = _state()
    if s:
        s.mark_critical(role)


def unmark_critical():
    s = _state()
    if s:
        s.unmark_critical()


class _AllowedScope:
    """Context manager suppressing the tripwire for calls a critical
    thread makes DELIBERATELY with a bound (the guardian's short-budget
    board I/O, an injected chaos delay). Shared no-op when disabled."""

    __slots__ = ()

    def __enter__(self):
        s = _state()
        if s:
            s.push_allowed()
        return self

    def __exit__(self, *exc):
        s = _STATE
        if s:
            s.pop_allowed()


_ALLOWED = _AllowedScope()


def allowed(reason=""):
    """``with sanitizer.allowed("bounded board I/O"):`` — mark a block
    as intentionally blocking-with-a-bound on a critical thread."""
    return _ALLOWED


def check_blocking(what, detail=""):
    """Tripwire call site for a potentially long blocking operation
    (``Handle.wait``, ``urlopen``, ``subprocess``): records a finding
    when executed on a critical thread. Disabled cost: one global read
    + compare."""
    s = _STATE
    if s is None:
        s = _resolve()
    if not s:
        return
    s.note_blocking(f"{what}({detail})" if detail else what)


def audit_shutdown():
    """Name non-daemon threads still alive after ``hvd.shutdown()``.
    Returns the leaked thread names (empty when disabled or clean)."""
    s = _state()
    if not s:
        return []
    return s.audit_shutdown()
