"""Layer 1: static collective-correctness analysis of jaxprs.

The reference framework catches cross-rank divergence at *runtime*: the
controller sees which named tensors every rank submitted and stalls — or
warns — when they disagree (reference: horovod/common/controller.cc:73
ComputeResponseList + stall_inspector.cc). On TPU the collectives are
compiled into one XLA program, so the same divergence becomes a silent
deadlock at trace time. This module walks a closed jaxpr instead and
flags the three compile-time-detectable shapes:

- **HVD101** — a collective (``psum``, ``all_gather``, ``ppermute``, …)
  whose axis name is bound by no enclosing ``shard_map``/``pmap`` mesh
  and was not declared by the caller (``axis_sizes``).
- **HVD102** — a collective nested inside ``cond``/``while`` whose
  predicate data-flows from ``axis_index`` (the in-graph rank): ranks
  disagree on whether/how often the collective runs, and since every
  XLA collective instruction carries its own channel id, branch-local
  collectives never pair across replicas — the SPMD deadlock shape.
- **HVD103** — ``cond`` branches under a rank-dependent predicate whose
  collective sequences disagree in op/axis/shape/dtype: even when every
  rank *does* enter a collective, the pairs exchange mismatched buffers.

Everything here is trace-level only: no device computation is run and
nothing is compiled. JAX imports stay inside functions so importing the
linter (e.g. from the CLI) costs nothing.
"""

from .diagnostics import Diagnostic, dedupe

# Cross-replica collective primitives (jax.lax.parallel + psum_scatter).
COLLECTIVE_PRIMS = frozenset({
    "psum", "pmin", "pmax", "ppermute", "pshuffle", "pgather",
    "all_gather", "all_to_all", "psum_scatter", "reduce_scatter",
    "psum_invariant",
})
# Primitives whose output is the replica id: the taint sources for the
# rank-dependent control-flow analysis.
RANK_PRIMS = frozenset({"axis_index"})

_DOC_HINT = "see docs/lint.md"


def _source_of(eqn):
    """(file, line) of an eqn's user frame, best effort."""
    try:
        from jax._src import source_info_util
        summary = source_info_util.summarize(eqn.source_info)
        # "path/to/file.py:123 (fn_name)"
        loc = summary.split(" ")[0]
        file, _, line = loc.rpartition(":")
        return file or loc, int(line or 0)
    except Exception:  # noqa: BLE001 - diagnostics must never crash
        return "<jaxpr>", 0


def _as_jaxpr(obj):
    """Normalize Jaxpr | ClosedJaxpr | None to a Jaxpr (or None)."""
    if obj is None:
        return None
    return getattr(obj, "jaxpr", obj)


def _sub_jaxprs(params):
    """Every jaxpr nested in an eqn's params (lists/tuples included)."""
    out = []

    def scan(v):
        if hasattr(v, "eqns") or hasattr(v, "jaxpr"):
            sub = _as_jaxpr(v)
            if sub is not None and hasattr(sub, "eqns"):
                out.append(sub)
        elif isinstance(v, (list, tuple)):
            for item in v:
                scan(item)

    for v in params.values():
        scan(v)
    return out


def _eqn_axis_names(eqn):
    """String axis names a collective eqn operates over (positional int
    axes from vmap are not mesh axes and are skipped)."""
    axes = eqn.params.get("axes")
    if axes is None:
        axes = eqn.params.get("axis_name")
    if axes is None:
        return ()
    if not isinstance(axes, (tuple, list)):
        axes = (axes,)
    return tuple(a for a in axes if isinstance(a, str))


def _collectives_in(jaxpr, _cache=None):
    """Ordered (prim, axes, shapes, dtypes, file, line) for every
    collective in the jaxpr, recursing into sub-jaxprs."""
    if _cache is None:
        _cache = {}
    key = id(jaxpr)
    if key in _cache:
        return _cache[key]
    found = []
    _cache[key] = found  # break cycles
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name in COLLECTIVE_PRIMS:
            shapes = tuple(tuple(getattr(v.aval, "shape", ()))
                           for v in eqn.invars if hasattr(v, "aval"))
            dtypes = tuple(str(getattr(v.aval, "dtype", ""))
                           for v in eqn.invars if hasattr(v, "aval"))
            file, line = _source_of(eqn)
            found.append((name, _eqn_axis_names(eqn), shapes, dtypes,
                          file, line))
        for sub in _sub_jaxprs(eqn.params):
            found.extend(_collectives_in(sub, _cache))
    return found


class _Walker:
    """Taint-propagating jaxpr walker.

    ``walk`` returns the taint (rank-dependence) of the jaxpr's outvars
    given its invars' taint; diagnostics accumulate on ``self.diags``
    (dedupe at the end — ``while``-body fixpoint iteration revisits
    eqns)."""

    def __init__(self, diags):
        self.diags = diags

    @staticmethod
    def _taint(env, v):
        # Literals have no .count/.aval identity to track — never tainted.
        return env.get(id(v), False) if hasattr(v, "aval") else False

    def walk(self, jaxpr, bound, taint_in):
        env = {}
        for v, t in zip(jaxpr.invars, taint_in):
            env[id(v)] = bool(t)
        for v in jaxpr.constvars:
            env[id(v)] = False
        for eqn in jaxpr.eqns:
            self._eqn(eqn, bound, env)
        return [self._taint(env, v) for v in jaxpr.outvars]

    # -- per-eqn dispatch --------------------------------------------------
    def _eqn(self, eqn, bound, env):
        prim = eqn.primitive.name
        in_taint = any(self._taint(env, v) for v in eqn.invars)
        out_taint = in_taint or prim in RANK_PRIMS

        if prim in COLLECTIVE_PRIMS:
            self._check_axes(eqn, bound)
        elif prim == "shard_map":
            out_taint = self._shard_map(eqn, bound, env, in_taint)
        elif prim in ("pmap", "xla_pmap"):
            out_taint = self._pmap(eqn, bound, env, in_taint)
        elif prim == "cond":
            out_taint = self._cond(eqn, bound, env, in_taint)
        elif prim == "while":
            out_taint = self._while(eqn, bound, env, in_taint)
        else:
            subs = _sub_jaxprs(eqn.params)
            if subs:
                out_taint = self._generic_call(eqn, bound, env, subs,
                                               in_taint)
        for v in eqn.outvars:
            env[id(v)] = bool(out_taint)

    def _check_axes(self, eqn, bound):
        for axis in _eqn_axis_names(eqn):
            if axis not in bound:
                file, line = _source_of(eqn)
                bound_desc = (", ".join(sorted(bound))
                              if bound else "<none>")
                self.diags.append(Diagnostic.make(
                    "HVD101",
                    f"collective `{eqn.primitive.name}` uses axis "
                    f"{axis!r} which is not bound by any enclosing "
                    f"shard_map/pmap mesh (bound axes: {bound_desc})",
                    file=file, line=line,
                    hint="bind the axis with shard_map over a mesh that "
                         f"names {axis!r}, or declare it via "
                         "axis_sizes= if an outer caller binds it; "
                         + _DOC_HINT))

    def _fit(self, taints, invars, in_taint):
        """Map caller-side taints onto a sub-jaxpr's invars; when arity
        does not line up (consts got hoisted), fall back to the
        conservative any-input taint."""
        if len(taints) == len(invars):
            return taints
        return [in_taint] * len(invars)

    def _shard_map(self, eqn, bound, env, in_taint):
        inner = _as_jaxpr(eqn.params.get("jaxpr"))
        mesh = eqn.params.get("mesh")
        names = tuple(getattr(mesh, "axis_names", ()) or ())
        if inner is None:
            return in_taint
        taints = [self._taint(env, v) for v in eqn.invars]
        outs = self.walk(inner, bound | set(names),
                         self._fit(taints, inner.invars, in_taint))
        return any(outs) or in_taint

    def _pmap(self, eqn, bound, env, in_taint):
        inner = _as_jaxpr(eqn.params.get("call_jaxpr"))
        axis = eqn.params.get("axis_name")
        names = {axis} if isinstance(axis, str) else set()
        if inner is None:
            return in_taint
        taints = [self._taint(env, v) for v in eqn.invars]
        outs = self.walk(inner, bound | names,
                         self._fit(taints, inner.invars, in_taint))
        return any(outs) or in_taint

    def _cond(self, eqn, bound, env, in_taint):
        branches = [_as_jaxpr(b) for b in eqn.params.get("branches", ())]
        pred_tainted = self._taint(env, eqn.invars[0])
        op_taints = [self._taint(env, v) for v in eqn.invars[1:]]
        out_taint = in_taint
        branch_colls = []
        for br in branches:
            if br is None:
                branch_colls.append([])
                continue
            outs = self.walk(br, bound,
                             self._fit(op_taints, br.invars, in_taint))
            out_taint = out_taint or any(outs)
            branch_colls.append(_collectives_in(br))

        if pred_tainted and any(branch_colls):
            file, line = _source_of(eqn)
            prims = sorted({c[0] for colls in branch_colls for c in colls})
            self.diags.append(Diagnostic.make(
                "HVD102",
                "cond predicate depends on axis_index (the replica id) "
                "and a branch contains collective(s) "
                f"{', '.join(prims)}: ranks will disagree on which "
                "collective program point runs, and branch-local XLA "
                "collectives never pair across replicas — this deadlocks "
                "or corrupts the exchange",
                file=file, line=line,
                hint="hoist the collective out of the cond (compute both "
                     "sides, select with jnp.where), or make the "
                     "predicate replica-invariant; " + _DOC_HINT))
            # Dtype/shape pairing check is only meaningful when ranks
            # actually take different branches, i.e. the pred is
            # rank-dependent and >1 branch exchanges data.
            with_colls = [c for c in branch_colls if c]
            if len(with_colls) >= 2:
                sigs = {tuple((p, a, s, d) for p, a, s, d, _, _ in colls)
                        for colls in with_colls}
                if len(sigs) > 1:
                    self.diags.append(Diagnostic.make(
                        "HVD103",
                        "collectives in the branches of this "
                        "rank-dependent cond disagree on "
                        "op/axis/shape/dtype — ranks taking different "
                        "branches would exchange mismatched buffers",
                        file=file, line=line,
                        hint="give every branch an identical collective "
                             "signature, or restructure without "
                             "rank-dependent branching; " + _DOC_HINT))
        return out_taint

    def _while(self, eqn, bound, env, in_taint):
        cn = eqn.params.get("cond_nconsts", 0)
        bn = eqn.params.get("body_nconsts", 0)
        cond_j = _as_jaxpr(eqn.params.get("cond_jaxpr"))
        body_j = _as_jaxpr(eqn.params.get("body_jaxpr"))
        taints = [self._taint(env, v) for v in eqn.invars]
        cond_consts = taints[:cn]
        body_consts = taints[cn:cn + bn]
        carry = taints[cn + bn:]
        pred_tainted = in_taint
        if cond_j is not None and body_j is not None:
            # Fixpoint over the carry: the body can taint a carried value
            # (e.g. accumulate axis_index) that feeds the next trip's
            # predicate. Converges in <= len(carry)+1 rounds; cap small.
            for _ in range(4):
                pred = self.walk(
                    cond_j, bound,
                    self._fit(cond_consts + carry, cond_j.invars,
                              in_taint))
                pred_tainted = any(pred)
                body_out = self.walk(
                    body_j, bound,
                    self._fit(body_consts + carry, body_j.invars,
                              in_taint))
                body_out = self._fit(body_out, carry, any(body_out))
                new_carry = [a or b for a, b in zip(carry, body_out)]
                if new_carry == carry:
                    break
                carry = new_carry
        body_colls = _collectives_in(body_j) if body_j is not None else []
        if pred_tainted and body_colls:
            file, line = _source_of(eqn)
            prims = sorted({c[0] for c in body_colls})
            self.diags.append(Diagnostic.make(
                "HVD102",
                "while-loop trip count depends on axis_index (the "
                "replica id) and the body contains collective(s) "
                f"{', '.join(prims)}: ranks run the collective a "
                "different number of times and the program deadlocks",
                file=file, line=line,
                hint="make the trip count replica-invariant (e.g. psum/"
                     "pmax the bound first), or mask the extra "
                     "iterations instead of skipping them; " + _DOC_HINT))
        return in_taint or any(carry) or pred_tainted

    def _generic_call(self, eqn, bound, env, subs, in_taint):
        # pjit / closed_call / scan / remat / custom_* — axes pass
        # through unchanged; map taint 1:1 when arity matches.
        taints = [self._taint(env, v) for v in eqn.invars]
        out = in_taint
        for sub in subs:
            outs = self.walk(sub, bound,
                             self._fit(taints, sub.invars, in_taint))
            out = out or any(outs)
        return out


def check_jaxpr(jaxpr, axis_sizes=None, bound_axes=None):
    """Analyze a (closed) jaxpr; returns a list of :class:`Diagnostic`.

    ``bound_axes`` (or the keys of ``axis_sizes``) are axis names the
    caller promises an enclosing mesh binds — collectives over them are
    legal even with no shard_map in this jaxpr.
    """
    bound = set(bound_axes or ())
    bound |= set(axis_sizes or ())
    inner = _as_jaxpr(jaxpr)
    diags = []
    walker = _Walker(diags)
    walker.walk(inner, frozenset(bound), [False] * len(inner.invars))
    return dedupe(diags)


def check_fn(fn, *args, axis_sizes=None, **kwargs):
    """Trace ``fn(*args, **kwargs)`` and analyze the resulting jaxpr.

    ``axis_sizes`` maps externally-bound axis names to sizes — the axes
    an enclosing ``shard_map`` (or the runtime's replica mesh) will bind
    around ``fn``. Tracing runs under an extended axis env so bare
    collectives over those axes trace cleanly; an axis bound nowhere at
    all surfaces as an HVD101 diagnostic instead of a NameError.

    Accepts concrete arrays or ``jax.ShapeDtypeStruct`` args; nothing is
    compiled or executed on devices.
    """
    import jax

    axis_sizes = dict(axis_sizes or {})
    try:
        core = jax.core
        extend = core.extend_axis_env_nd
    except AttributeError:  # pragma: no cover - jax version drift
        from jax._src import core as _core
        extend = _core.extend_axis_env_nd

    try:
        if axis_sizes:
            with extend(list(axis_sizes.items())):
                closed = jax.make_jaxpr(fn)(*args, **kwargs)
        else:
            closed = jax.make_jaxpr(fn)(*args, **kwargs)
    except NameError as exc:
        # "unbound axis name: X" — the trace itself proves HVD101.
        return [Diagnostic.make(
            "HVD101",
            f"tracing failed with {exc}: the function performs a "
            "collective over an axis bound by no enclosing shard_map/"
            "pmap and not declared via axis_sizes=",
            hint="pass axis_sizes={'<axis>': <size>} if an outer mesh "
                 "binds it, or wrap the function in shard_map; "
                 + _DOC_HINT)]
    return check_jaxpr(closed, axis_sizes=axis_sizes)
