"""Autotune knob overlay: tuned values for construction-time knobs.

Most tuned knobs apply instantly (the coordinator's fusion threshold
and cycle time are plain attributes the cycle thread re-reads). Two do
not: ``HVDTPU_BUCKET_BYTES`` and ``HVDTPU_ZERO_BUCKET_BYTES`` are read
once when a ``DistributedOptimizer`` is constructed and baked into the
traced train step. The overlay is the indirection that closes that
gap: the tuner (a warm-started cache hit at init, or a zero-arm
candidate mid-sweep) writes here, and the constructors read through
:func:`get_int` so a tuned value wins over the raw environment. The
ZeRO step wrapper additionally polls :func:`generation` (one int
compare per step) so a mid-run change triggers a deterministic
re-plan + reshard at the next step boundary.

Values persist across elastic re-inits on purpose: the new cohort's
fresh ParameterManager re-validates them against the warm-start store
(docs/autotune.md) instead of silently dropping the tuned config.
"""

import threading

_lock = threading.Lock()
_values = {}
_generation = 0


def set_int(name, value):
    """Overlay knob ``name`` (an envparse registry name, no prefix)
    with a tuned integer value; bumps the generation counter consumers
    poll for cheap change detection."""
    global _generation
    with _lock:
        _values[name] = int(value)
        _generation += 1


def get_int(name, default=None):
    """Tuned value for ``name``, or ``default`` when the tuner never
    touched it."""
    with _lock:
        return _values.get(name, default)


def resolve_int(name, default=None):
    """The one overlay-then-env-then-default resolution every
    construction-time reader uses: a tuned value wins over the raw
    environment knob, which wins over ``default``."""
    value = get_int(name)
    if value is not None:
        return value
    from ..utils import envparse
    return envparse.get_int(name, default)


def generation():
    """Monotonic change counter (0 = nothing overlaid yet)."""
    return _generation


def snapshot():
    """Copy of the overlay dict (CLI / test surface)."""
    with _lock:
        return dict(_values)


def clear():
    """Drop every overlaid value (test hook; bumps the generation so
    pollers notice)."""
    global _generation
    with _lock:
        _values.clear()
        _generation += 1
