"""Autotuning parameter manager (trace-driven, warm-started).

The reference tunes fusion-threshold / cycle-time / cache knobs with
Gaussian-process Bayesian optimization (reference:
horovod/common/parameter_manager.cc, optim/bayesian_optimization.cc),
scoring each candidate by observed bytes/sec and broadcasting winners
(reference: controller.cc:39-53 SynchronizeParameters).

TPU-native rethink, round 2 (docs/autotune.md):

**Search structure — per-plane arms.** The perf stack is wider than
the host pair now: overlap bucket bytes (PR 7), compression codec and
threshold (PR 6), ZeRO leg buckets (PR 9). A joint grid over all of
them explodes combinatorially, so the space is factored into *arms* —
one small grid per perf plane, tuned in sequence (coordinate descent):

- ``host`` — fusion threshold x cycle time x delegated-plane min
  bucket (the original joint grid; the knobs interact, so they stay
  joint);
- ``overlap`` — ``HVDTPU_BUCKET_BYTES`` (eager overlap plane, and the
  overlay consumed by in-jit optimizer construction);
- ``compression`` — codec x threshold applied as the live plane's
  catch-all policy (only when the user already opted into a pure
  catch-all policy — per-glob rules are never overwritten);
- ``zero`` — ``HVDTPU_ZERO_BUCKET_BYTES`` through the overlay; the
  ZeRO step wrapper re-plans + reshards at the next step boundary
  (single-controller mode only, where that re-plan is deterministic
  by construction).

Within an arm, **successive halving** (itself the classic fixed-budget
bandit): every candidate gets a short scoring window, the top half
survives into a longer round, repeat until one remains; the final
head-to-head runs at the full configured window.

**Score source.** Candidates are judged by what actually bounds the
step: steps/sec derived from the flight-recorder ring's correlated
submit/finish spans (score.TraceScore), falling back to the legacy
cycle-thread bytes/sec when no step structure is visible
(``HVDTPU_AUTOTUNE_SCORE``).

**Warm start.** Converged winners persist per (model-signature,
world-size, codec-availability) key in ``HVDTPU_AUTOTUNE_CACHE``
(store.py). A repeat run applies the stored winner before the first
scored window and skips the sweep; an elastic-version bump instead
triggers deterministic re-validation — one short baseline window, one
short warm window, full re-sweep only on regression.

Determinism (unchanged contract): candidate changes are driven by the
ACTIVE-cycle counter, identical on every rank in SPMD mode, so all
ranks apply the same candidate at the same cycle. Scores are
timing-noisy and rank-local, so every decision that depends on them —
round survivors, the warm-start verdict, the re-validation verdict —
broadcasts rank 0's choice over the data plane (the
SynchronizeParameters analog).
"""

import math
import time

import numpy as np

from . import overlay, score as score_mod, store
from ..telemetry import core as telemetry
from ..utils import envparse
from ..utils.logging_util import get_logger

# Discrete candidate grids (reference sweeps similar ranges).
FUSION_CANDIDATES_MIB = [0, 1, 2, 4, 8, 16, 32, 64, 128]
CYCLE_CANDIDATES_MS = [0.1, 0.5, 1.0, 2.5, 5.0, 10.0]
BUCKET_CANDIDATES = [256, 4096, 65536]
BUCKET_BYTES_CANDIDATES_MIB = [1, 4, 16, 64]
ZERO_BUCKET_CANDIDATES_MIB = [4, 16, 64]
WARMUP_CYCLES = 10
CYCLES_PER_CANDIDATE = 20   # budget of the FINAL round; early rounds
                            # screen at budget >> 2^(rounds remaining)
CONFIRM_CYCLES = 10         # warm-start re-validation window

#: Re-validation tolerance: the warm config keeps its crown unless it
#: scores more than this fraction BELOW the baseline window (scores
#: are noisy; ties and noise must not trigger a full re-sweep).
REGRESSION_TOLERANCE = 0.1

#: Fixed codec table for the SPMD warm-config broadcast encoding.
CODEC_ORDER = ("none", "fp16", "bf16", "int8", "fp8")

# Warm decisions (index 0 of the broadcast vector).
_SWEEP, _HIT, _REVALIDATE = 0, 1, 2


def _env_list(name, default, conv):
    raw = envparse.get_str(name, "")
    if not raw:
        return default
    return [conv(x.strip()) for x in raw.split(",") if x.strip()]


class Arm:
    """One perf plane's candidate grid + apply function."""

    __slots__ = ("name", "candidates", "_apply_fn", "fmt")

    def __init__(self, name, candidates, apply_fn, fmt=str):
        self.name = name
        self.candidates = list(candidates)
        self._apply_fn = apply_fn
        self.fmt = fmt

    def apply(self, value):
        self._apply_fn(value)


class ParameterManager:
    """Cycle-driven per-arm successive-halving sweep with trace-driven
    scoring and a persistent warm start; see module docstring."""

    def __init__(self, runtime):
        self.runtime = runtime
        self.enabled = True
        self._log = get_logger()
        self._log_path = envparse.get_str(envparse.AUTOTUNE_LOG, "")
        self._warmup = envparse.get_int(envparse.AUTOTUNE_WARMUP_CYCLES,
                                        WARMUP_CYCLES)
        self._final_budget = envparse.get_int(
            envparse.AUTOTUNE_CYCLES_PER_CANDIDATE, CYCLES_PER_CANDIDATE)
        self._confirm_budget = max(2, envparse.get_int(
            envparse.AUTOTUNE_CONFIRM_CYCLES, CONFIRM_CYCLES))
        self._world = int(getattr(runtime, "size", 1) or 1)
        rank = getattr(getattr(runtime, "topology", None), "rank", 0)
        self._rank = int(rank or 0)
        self._source = score_mod.make_source(
            runtime, envparse.get_str(envparse.AUTOTUNE_SCORE, "auto"),
            rank=self._rank)
        self._score_label = self._source.name

        # -- current config + arms ------------------------------------
        self._current = {k: None for k in store.CONFIG_KEYS}
        self._arms = []
        self._build_arms()
        #: Legacy surface: the host arm's joint grid.
        self._grid = self._arms[0].candidates

        # -- sweep state ----------------------------------------------
        self._arm_idx = 0
        self._active = list(range(len(self._grid)))
        # Cost-model warm-start prior (HVDTPU_COSTMODEL): probe the
        # host grid in the model's predicted order. Pure prior —
        # measured scores still decide, and the order is a pure
        # function of (table, world, grid), so every rank derives the
        # same sequence and the broadcast determinism pin holds.
        self._prior_table = None
        order = self._costmodel_priors(self._arms[0])
        if order is not None:
            self._active = order
        self._budget = self._round_budget(len(self._active))
        self._pos = -1               # index into _active; -1 = no cand
        self._cycle = 0
        self._window = 0
        self._cycle_rates = []
        self._round_scores = {}      # cand idx -> [window scores]
        self._history = []           # (arm, round, cand_idx, mean)
        self._round = 0
        self._winners = {}           # arm name -> winning value
        self._winner_idx = {}        # arm name -> winning cand idx
        self._last_score = 0.0
        self._last_bytes = 0
        self._last_time = time.monotonic()
        self._phase = "warmup"
        self.best = None             # host tuple, set at convergence
        self.best_config = None      # full config dict at convergence
        #: Applied-knob sequence [(plane, value-str)] — the cross-rank
        #: determinism pin (tests assert every rank logs the same one).
        self.applied = []

        # -- warm-start store -----------------------------------------
        self._store_path = envparse.get_str(envparse.AUTOTUNE_CACHE, "")
        self._store_entries = None
        self._store_corrupt = False
        self._store_key = None
        self._signature = None
        self._warm_cfg = None
        self._base_score = None

        # -- observability (NULL no-ops when metrics off) --------------
        # The knob gauges track the APPLIED values and are seeded from
        # the coordinator's / backend's / planes' CURRENT config, so a
        # scrape before the first candidate shows reality (the
        # min-bucket gauge included — it previously read 0 until the
        # first bucket candidate applied).
        self._m_fusion = telemetry.gauge(
            "hvd_autotune_fusion_threshold_bytes",
            "Fusion threshold currently applied")
        self._m_cycle = telemetry.gauge(
            "hvd_autotune_cycle_time_ms",
            "Coordinator cycle time currently applied")
        self._m_bucket = telemetry.gauge(
            "hvd_autotune_min_bucket",
            "Delegated-plane min bucket currently applied")
        self._m_bucket_bytes = telemetry.gauge(
            "hvd_autotune_bucket_bytes",
            "Overlap-plane bucket bytes currently applied")
        self._m_zero_bucket = telemetry.gauge(
            "hvd_autotune_zero_bucket_bytes",
            "ZeRO-leg bucket bytes currently applied (overlay)")
        self._m_codec = telemetry.gauge(
            "hvd_autotune_compression_codec",
            "1 on the label of the catch-all codec currently applied",
            labelnames=("codec",))
        self._m_comp_threshold = telemetry.gauge(
            "hvd_autotune_compression_threshold",
            "Compression element threshold currently applied")
        self._m_score = telemetry.gauge(
            "hvd_autotune_score",
            "Score of the last closed autotune window")
        self._m_switches = telemetry.counter(
            "hvd_autotune_candidate_switches_total",
            "Candidate knob applications")
        self._m_rounds = telemetry.counter(
            "hvd_autotune_rounds_total", "Completed halving rounds")
        self._m_converged = telemetry.gauge(
            "hvd_autotune_converged", "1 once the sweep has converged")
        self._m_warm = telemetry.counter(
            "hvd_autotune_warm_start_total",
            "Warm-start cache consultations by outcome",
            labelnames=("outcome",))
        self._codec_label = None
        self._seed_gauges()
        self._m_converged.set(0)

        if self._store_path:
            try:
                self._store_entries = store.load(self._store_path)
            except store.StoreError as exc:
                self._store_corrupt = True
                self._m_warm.labels(outcome="corrupt").inc()
                self._log.warning(
                    "autotune: warm-start cache unusable (%s) — "
                    "running a fresh sweep; convergence rewrites the "
                    "file", exc)

    # -- arm construction --------------------------------------------------
    def _build_arms(self):
        runtime = self.runtime
        coord = runtime.coordinator
        backend = runtime.backend
        cfg = self._current
        if coord is not None:
            cfg["fusion_threshold"] = coord.fusion_threshold
            cfg["cycle_time_ms"] = coord.cycle_time_s * 1000.0

        # host: the original joint fusion x cycle x min-bucket grid.
        fusion = _env_list(envparse.AUTOTUNE_FUSION_CANDIDATES_MIB,
                           FUSION_CANDIDATES_MIB, float)
        cycle = _env_list(envparse.AUTOTUNE_CYCLE_CANDIDATES_MS,
                          CYCLE_CANDIDATES_MS, float)
        # The bucket knob only exists on delegated (XLA data plane)
        # backends; tuning it elsewhere would burn windows on a no-op.
        if hasattr(backend, "set_min_bucket"):
            bucket = _env_list(envparse.AUTOTUNE_BUCKET_CANDIDATES,
                               BUCKET_CANDIDATES, int)
            cfg["min_bucket"] = getattr(backend, "min_bucket", None)
        else:
            bucket = [None]
        grid = [(int(f * 1024 * 1024), c, b)
                for f in fusion for c in cycle for b in bucket]
        self._arms.append(Arm("host", grid, self._apply_host,
                              fmt=lambda v: f"{v[0]}/{v[1]}/{v[2]}"))

        # overlap: eager-plane bucket bytes (+ construction overlay).
        if coord is not None and getattr(coord, "_overlap", False):
            cands = [int(m * 1024 * 1024) for m in _env_list(
                envparse.AUTOTUNE_BUCKET_BYTES_CANDIDATES_MIB,
                BUCKET_BYTES_CANDIDATES_MIB, float)]
            cur = int(getattr(coord, "_bucket_bytes", 0) or 0)
            if cur and cur not in cands:
                cands.append(cur)
            cfg["bucket_bytes"] = cur or None
            if len(cands) > 1:
                self._arms.append(Arm("overlap", cands,
                                      self._apply_bucket_bytes))

        # compression: codec x threshold as the plane's catch-all.
        plane = getattr(coord, "_compression", None)
        cur_codec = self._catchall_codec(plane)
        if cur_codec is not None:
            cfg["compression"] = cur_codec
            cfg["compression_threshold"] = plane.policy.threshold
            codecs = _env_list(envparse.AUTOTUNE_COMPRESSION_CANDIDATES,
                               None, str)
            if codecs is None:
                codecs = self._default_codec_candidates(cur_codec)
            else:
                for name in codecs:
                    self._check_codec(name)
            thresholds = _env_list(
                envparse.AUTOTUNE_COMPRESSION_THRESHOLD_CANDIDATES,
                [plane.policy.threshold], int)
            # 'none' ignores the threshold (rules=[]): crossing it with
            # every threshold would burn a full scoring window per
            # behaviorally-identical duplicate.
            cands = []
            for c in codecs:
                for t in (thresholds if c != "none" else thresholds[:1]):
                    if (c, t) not in cands:
                        cands.append((c, t))
            if len(cands) > 1:
                self._arms.append(Arm(
                    "compression", cands, self._apply_compression,
                    fmt=lambda v: f"{v[0]}@{v[1]}"))

        # zero: leg bucket bytes through the overlay; the step wrapper
        # re-plans at the next boundary. Single-controller only — in
        # SPMD the per-process step loops would observe the overlay at
        # different step indices and compute divergent shard plans.
        from .. import basics
        if (coord is not None and envparse.get_bool(envparse.ZERO)):
            from ..ops.bucketing import DEFAULT_BUCKET_BYTES
            cur = overlay.resolve_int(envparse.ZERO_BUCKET_BYTES,
                                      DEFAULT_BUCKET_BYTES)
            cfg["zero_bucket_bytes"] = cur
            if getattr(runtime, "mode", None) == basics.MODE_SINGLE:
                cands = [int(m * 1024 * 1024) for m in _env_list(
                    envparse.AUTOTUNE_ZERO_BUCKET_CANDIDATES_MIB,
                    ZERO_BUCKET_CANDIDATES_MIB, float)]
                if cur not in cands:
                    cands.append(cur)
                if len(cands) > 1:
                    self._arms.append(Arm("zero", cands,
                                          self._apply_zero_bucket))

    @staticmethod
    def _catchall_codec(plane):
        """The plane's pure catch-all codec name ('none' for an empty
        rule list), or None when there is no plane — or when the policy
        carries per-glob rules the tuner must not overwrite."""
        if plane is None or getattr(plane, "_delegated", False):
            return None
        rules = plane.policy.rules
        if not rules:
            return "none"
        if len(rules) == 1 and rules[0][0] == "*":
            return rules[0][1]
        return None

    def _check_codec(self, name):
        from ..compression import codecs
        if name != "none":
            codecs.get_codec(name)  # loud on unknown/unsupported

    def _default_codec_candidates(self, current):
        from ..compression import codecs
        out = []
        for name in (current, "none", "int8", "bf16"):
            if name == "fp8" and not codecs.fp8_supported():
                continue
            if name not in out:
                out.append(name)
        return out

    # -- gauge seeding (a scrape before the first candidate shows the
    # -- coordinator's reality, not zeros) ---------------------------------
    def _seed_gauges(self):
        cfg = self._current
        if cfg["fusion_threshold"] is not None:
            self._m_fusion.set(cfg["fusion_threshold"])
        if cfg["cycle_time_ms"] is not None:
            self._m_cycle.set(cfg["cycle_time_ms"])
        if cfg["min_bucket"] is not None:
            self._m_bucket.set(cfg["min_bucket"])
        if cfg["bucket_bytes"] is not None:
            self._m_bucket_bytes.set(cfg["bucket_bytes"])
        if cfg["zero_bucket_bytes"] is not None:
            self._m_zero_bucket.set(cfg["zero_bucket_bytes"])
        if cfg["compression"] is not None:
            self._set_codec_gauge(cfg["compression"])
        if cfg["compression_threshold"] is not None:
            self._m_comp_threshold.set(cfg["compression_threshold"])

    def _set_codec_gauge(self, name):
        if self._codec_label is not None and self._codec_label != name:
            self._m_codec.labels(codec=self._codec_label).set(0)
        self._m_codec.labels(codec=name).set(1)
        self._codec_label = name

    # -- called once per coordinator cycle --------------------------------
    def record_cycle(self):
        if not self.enabled:
            return
        coord = self.runtime.coordinator
        now = time.monotonic()
        bytes_now = coord.bytes_processed
        if bytes_now == self._last_bytes:
            # Idle cycle: don't advance the sweep (the reference scores
            # traffic, not wall time). Per-cycle executed-byte totals are
            # the negotiated response sizes — identical on every rank and
            # recorded on the cycle thread (delegated completions too:
            # _drain_delegated runs inside the same run_cycle) — so
            # "active cycle" counting keeps the cross-rank determinism.
            self._last_time = now
            return
        self._cycle += 1
        elapsed = now - self._last_time
        rate = (bytes_now - self._last_bytes) / max(elapsed, 1e-9)
        self._last_bytes = bytes_now
        self._last_time = now

        if self._phase == "warmup":
            # Warming up (warmup=0 => the decision runs on the first
            # active cycle; scoring starts the cycle after it).
            if self._cycle >= self._warmup:
                self._end_warmup()
            return
        self._cycle_rates.append(rate)
        self._window += 1
        if self._window < self._budget:
            return
        window = self._source.close_window(self._cycle_rates)
        self._score_label = ("steps" if window["steps"] is not None
                             else "bytes")
        self._m_score.set(window["steps"]
                          if window["steps"] is not None
                          else window["bytes"])
        if self._phase == "confirm_base":
            self._base_score = window
            self._apply_config(self._warm_cfg)
            self._phase = "confirm_warm"
            self._open_window(self._confirm_budget)
        elif self._phase == "confirm_warm":
            self._finish_confirm(window)
        else:
            cand = self._active[self._pos]
            self._round_scores.setdefault(cand, []).append(window)
            if self._pos + 1 < len(self._active):
                self._set_position(self._pos + 1)
            else:
                self._halve()

    # -- warm start --------------------------------------------------------
    def _end_warmup(self):
        decision, cfg, local_reason = self._warm_decision()
        decision, cfg = self._sync_warm(decision, cfg)
        # Outcomes are counted/logged from the FINAL (broadcast)
        # decision, not the rank-local one: a rank whose own cache file
        # missed but which warm-starts on rank 0's broadcast config DID
        # warm-start — counting its local miss would make the one
        # warm-start health signal wrong exactly when the cross-host
        # cache drift it exists to surface occurs.
        if decision == _HIT:
            self._m_warm.labels(outcome="hit").inc()
            self._log.info(
                "autotune: warm start — cache %s key %s applies before "
                "the first scored window", self._store_path,
                self._store_key)
            self._finish_warm(cfg)
            return
        if decision == _REVALIDATE:
            self._m_warm.labels(outcome="revalidate").inc()
            self._log.info(
                "autotune: elastic version moved since key %s was "
                "cached — re-validating the stored winner (%d-cycle "
                "baseline window, then %d-cycle warm window)",
                self._store_key, self._confirm_budget,
                self._confirm_budget)
            self._warm_cfg = cfg
            self._baseline_cfg = dict(self._current)
            self._phase = "confirm_base"
            self._open_window(self._confirm_budget)
            return
        if local_reason == "miss":
            self._m_warm.labels(outcome="miss").inc()
            self._log.info(
                "autotune: no cache entry for key %s — full sweep",
                self._store_key)
        elif local_reason == "stale":
            self._m_warm.labels(outcome="stale").inc()
            self._log.warning(
                "autotune: cache entry %s is stale — full sweep "
                "rewrites it at convergence", self._store_key)
        self._phase = "sweep"
        self._set_position(0)

    def _warm_decision(self):
        """Rank-local cache consultation -> (decision, config|None,
        reason). The caller counts/logs outcomes AFTER the cross-rank
        sync; ``reason`` names why THIS rank voted sweep."""
        if (not self._store_path or self._store_corrupt
                or self._store_entries is None):
            return _SWEEP, None, None
        sig = envparse.get_str(envparse.AUTOTUNE_SIGNATURE, "")
        if not sig:
            sig = store.model_signature(self._ring_names())
        self._signature = sig
        self._store_key = store.make_key(
            sig, self._world, store.codec_signature(self.runtime))
        entry = self._store_entries.get(self._store_key)
        if entry is None:
            return _SWEEP, None, "miss"
        reason = store.validate_entry(entry)
        if reason is not None:
            return _SWEEP, None, "stale"
        cfg = {k: entry["config"].get(k) for k in store.CONFIG_KEYS}
        cur = envparse.get_str(envparse.ELASTIC_VERSION, "0")
        if str(entry.get("elastic_version")) != cur:
            return _REVALIDATE, cfg, None
        return _HIT, cfg, None

    def _ring_names(self):
        tracer = getattr(self.runtime, "tracer", None)
        flight = getattr(tracer, "_flight", None)
        if flight is None:
            return ()
        return [ev.get("n") for ev in flight.snapshot()
                if ev.get("e") == "sub"]

    def _sync_warm(self, decision, cfg):
        """SPMD: rank 0's warm decision + config wins — cache files can
        diverge across hosts, and a divergent decision here would put
        ranks into different phases (different collective schedules).
        Encoded as a fixed-length float64 vector so no shape
        negotiation is needed; no-op without a store or off SPMD."""
        if not self._store_path:
            return decision, cfg
        rt = self.runtime
        from .. import basics
        if rt.mode != basics.MODE_SPMD or rt.topology.size <= 1:
            return decision, cfg
        from ..process_sets import global_process_set
        vec = np.full(8, -1.0, np.float64)
        vec[0] = decision
        if cfg is not None:
            for slot, key in ((1, "fusion_threshold"),
                              (2, "cycle_time_ms"), (3, "min_bucket"),
                              (4, "bucket_bytes"),
                              (6, "compression_threshold"),
                              (7, "zero_bucket_bytes")):
                if cfg.get(key) is not None:
                    vec[slot] = float(cfg[key])
            if cfg.get("compression") in CODEC_ORDER:
                vec[5] = CODEC_ORDER.index(cfg["compression"])
        out = np.asarray(
            rt.backend.broadcast([vec], 0, global_process_set)[0])
        decision = int(out[0])
        if decision == _SWEEP:
            return _SWEEP, None

        def num(slot, conv):
            return None if out[slot] < 0 else conv(out[slot])

        cfg = {
            "fusion_threshold": num(1, int),
            "cycle_time_ms": num(2, float),
            "min_bucket": num(3, int),
            "bucket_bytes": num(4, int),
            "compression": (CODEC_ORDER[int(out[5])]
                            if out[5] >= 0 else None),
            "compression_threshold": num(6, int),
            "zero_bucket_bytes": num(7, int),
        }
        return decision, cfg

    def _sync_verdict(self, flag):
        """Broadcast rank 0's boolean re-validation verdict (same
        rationale as _sync_warm: rank-local scores are noisy and a
        divergent verdict forks the collective schedule)."""
        rt = self.runtime
        from .. import basics
        if rt.mode != basics.MODE_SPMD or rt.topology.size <= 1:
            return flag
        from ..process_sets import global_process_set
        vec = np.asarray([1.0 if flag else 0.0], np.float64)
        out = rt.backend.broadcast([vec], 0, global_process_set)
        return bool(np.asarray(out[0])[0] > 0.5)

    def _finish_confirm(self, warm_window):
        # Same unit on both sides (see _halve): steps only when both
        # confirm windows saw step structure, else the always-present
        # bytes rate — a fallback window must not beat a steps baseline
        # on magnitude alone.
        base = self._base_score
        use_steps = (base["steps"] is not None
                     and warm_window["steps"] is not None)
        unit = "steps" if use_steps else "bytes"
        self._score_label = unit
        base_score, warm_score = base[unit], warm_window[unit]
        ok = warm_score >= base_score * (1.0 - REGRESSION_TOLERANCE)
        ok = self._sync_verdict(ok)
        if ok:
            self._m_warm.labels(outcome="revalidated").inc()
            self._last_score = warm_score
            self._log.info(
                "autotune: stored winner re-validated under the new "
                "cohort (warm %.1f vs baseline %.1f %s)", warm_score,
                base_score, unit)
            self._finish_warm(self._warm_cfg, update_store=True)
            return
        self._m_warm.labels(outcome="regressed").inc()
        self._log.warning(
            "autotune: stored winner REGRESSED under the new cohort "
            "(warm %.1f vs baseline %.1f %s) — full re-sweep",
            warm_score, base_score, unit)
        self._apply_config(self._baseline_cfg)
        self._phase = "sweep"
        self._budget = self._round_budget(len(self._active))
        self._set_position(0)

    def _finish_warm(self, cfg, update_store=False):
        self._apply_config(cfg)
        self.best = (self._current["fusion_threshold"],
                     self._current["cycle_time_ms"],
                     self._current["min_bucket"])
        self.best_config = dict(self._current)
        if update_store:
            self._save_store()
        self._m_converged.set(1)
        # Last: observers poll `enabled`, so best/knobs must be in place
        # before the flag flips (the worker thread races this method).
        self.enabled = False
        self._log.info("autotune: warm-started config active: %s",
                       self.best_config)

    # -- sweep mechanics ---------------------------------------------------
    def _costmodel_priors(self, arm):
        """Candidate probe order from the α–β cost model, or None when
        ``HVDTPU_COSTMODEL`` is off (the knob check is the ONLY thing
        that runs then — disabled mode constructs no model, guard-
        tested) or the model is unusable (grid order is always a safe
        fallback — the prior only reorders, never filters)."""
        if not envparse.get_bool(envparse.COSTMODEL):
            return None
        try:
            from ..analysis import costmodel
            if self._prior_table is None:
                self._prior_table = costmodel.resolve_table()
            order = costmodel.rank_candidates(
                arm.name, arm.candidates, self._world,
                self._prior_table)
        except Exception as exc:  # noqa: BLE001 — prior is optional
            self._log.warning(
                "autotune: cost-model prior unavailable for arm %r "
                "(%s); probing in grid order", arm.name, exc)
            return None
        if order != list(range(len(arm.candidates))):
            self._log.info(
                "autotune: arm %r probe order seeded from cost-model "
                "prior: %s", arm.name,
                [arm.fmt(arm.candidates[i]) for i in order])
        return order

    def _predicted_costs(self):
        """Per-arm predicted cost of the converged winners (the store
        entry's ``predicted`` audit field); None when the model is
        off."""
        if not envparse.get_bool(envparse.COSTMODEL):
            return None
        try:
            from ..analysis import costmodel
            table = self._prior_table or costmodel.resolve_table()
            out = {}
            for arm in self._arms:
                if arm.name in self._winners:
                    out[arm.name] = costmodel.predicted_cost(
                        arm.name, self._winners[arm.name],
                        self._world, table)
            return out or None
        except Exception:  # noqa: BLE001 — audit data only
            return None

    def _round_budget(self, n_active):
        """Scoring window for a round with n_active candidates: the LAST
        round (2 survivors) runs at exactly AUTOTUNE_CYCLES_PER_CANDIDATE;
        earlier rounds screen at that budget halved once per remaining
        halving (floor 2). keep=n//2 needs ceil(log2 n) halvings."""
        if n_active <= 1:
            return self._final_budget
        rounds_left = max(1, math.ceil(math.log2(n_active)))
        return max(2, self._final_budget >> (rounds_left - 1))

    def _open_window(self, budget=None):
        self._window = 0
        self._cycle_rates = []
        if budget is not None:
            self._budget = budget
        self._source.open_window()

    def _set_position(self, pos):
        self._pos = pos
        arm = self._arms[self._arm_idx]
        self._open_window()
        arm.apply(arm.candidates[self._active[pos]])

    def _agree(self, indices, n):
        """Rank 0's candidate-index selection broadcasts over the data
        plane (the SynchronizeParameters analog); every rank reaches this
        at the same active cycle, so the collective lines up. The vector
        is fixed-length (arm-grid-sized mask) so no shape negotiation is
        needed."""
        rt = self.runtime
        from .. import basics
        if rt.mode != basics.MODE_SPMD or rt.topology.size <= 1:
            return indices
        from ..process_sets import global_process_set
        mask = np.zeros(n, np.int32)
        mask[np.asarray(indices, np.int32)] = 1
        out = rt.backend.broadcast([mask], 0, global_process_set)
        got = np.flatnonzero(np.asarray(out[0]))
        return [int(i) for i in got]

    def _halve(self):
        arm = self._arms[self._arm_idx]
        # One unit for the whole comparison set: steps only when EVERY
        # window of every candidate saw step structure — a bytes/sec
        # fallback (~1e8) compared against a steps/sec (~10) would
        # always survive regardless of actual step pacing.
        use_steps = all(w["steps"] is not None
                        for ws in self._round_scores.values()
                        for w in ws)
        unit = "steps" if use_steps else "bytes"
        self._score_label = unit
        means = {i: sum(w[unit] for w in ws) / len(ws)
                 for i, ws in self._round_scores.items()}
        for i, m in sorted(means.items()):
            self._history.append((arm.name, self._round, i, m))
        keep = max(1, len(self._active) // 2)
        # Ordered by score desc, ties broken by grid order (deterministic
        # on rank 0; everyone else takes the broadcast).
        survivors = sorted(sorted(means), key=lambda i: -means[i])[:keep]
        survivors = self._agree(sorted(survivors), len(arm.candidates))
        if len(survivors) == 1:
            self._winner_idx[arm.name] = survivors[0]
            self._arm_converged(survivors[0],
                                means.get(survivors[0], 0.0))
            return
        self._active = survivors
        self._round += 1
        self._m_rounds.inc()
        self._budget = self._round_budget(len(survivors))
        self._round_scores = {}
        self._set_position(0)

    def _arm_converged(self, winner_idx, winner_score):
        arm = self._arms[self._arm_idx]
        value = arm.candidates[winner_idx]
        self._winners[arm.name] = value
        self._last_score = winner_score
        arm.apply(value)
        if arm.name == "host":
            self.best = value
        self._log.info(
            "autotune: arm %r converged after %d halving round(s): %s",
            arm.name, self._round + 1, arm.fmt(value))
        self._arm_idx += 1
        if self._arm_idx < len(self._arms):
            nxt = self._arms[self._arm_idx]
            self._active = list(range(len(nxt.candidates)))
            order = self._costmodel_priors(nxt)
            if order is not None:
                self._active = order
            self._round = 0
            self._round_scores = {}
            self._budget = self._round_budget(len(self._active))
            self._set_position(0)
            return
        self._converge_all()

    def _converge_all(self):
        self.best_config = dict(self._current)
        if self.best is None:
            self.best = (self._current["fusion_threshold"],
                         self._current["cycle_time_ms"],
                         self._current["min_bucket"])
        self._save_store()
        self._m_converged.set(1)
        # Last: observers poll `enabled`, so best/knobs must be in place
        # before the flag flips (the worker thread races this method).
        self.enabled = False
        self._log.info(
            "autotune converged (%d arm(s), score source %s): %s",
            len(self._arms), self._score_label, self.best_config)
        self._write_log()

    def _store_history(self):
        by_name = {a.name: a for a in self._arms}
        return [(arm, rnd, by_name[arm].fmt(by_name[arm].candidates[i]),
                 mean) for arm, rnd, i, mean in self._history]

    def _save_store(self):
        """Persist the converged winner (rank 0 only — one writer per
        shared filesystem; peers warm-start from the broadcast-applied
        config next run)."""
        if not self._store_path or self._rank != 0:
            return
        if self._signature is None:
            sig = envparse.get_str(envparse.AUTOTUNE_SIGNATURE, "")
            self._signature = sig or store.model_signature(
                self._ring_names())
            self._store_key = store.make_key(
                self._signature, self._world,
                store.codec_signature(self.runtime))
        history = self._store_history()
        if not history and self._store_entries:
            # A successful re-validation ran no sweep this session;
            # keep the original converged sweep's history instead of
            # overwriting it with [] (hvd-autotune history would
            # otherwise report zero windows for a swept winner).
            prev = self._store_entries.get(self._store_key)
            if isinstance(prev, dict):
                history = prev.get("history") or []
        entry = store.make_entry(
            self.best_config if self.best_config is not None
            else self._current,
            self._last_score, self._score_label, self._signature,
            self._world, store.codec_signature(self.runtime),
            envparse.get_str(envparse.ELASTIC_VERSION, "0"),
            history, predicted=self._predicted_costs())
        try:
            store.save_entry(self._store_path, self._store_key, entry)
            self._log.info("autotune: winner cached under key %s in %s",
                           self._store_key, self._store_path)
        except OSError as exc:
            self._log.warning(
                "autotune: could not persist winner to %s: %s",
                self._store_path, exc)

    def _write_log(self):
        if not self._log_path:
            return
        by_name = {a.name: a for a in self._arms}
        with open(self._log_path, "a") as f:
            for arm_name, rnd, idx, mean in self._history:
                arm = by_name[arm_name]
                cand = arm.candidates[idx]
                marker = ("*" if self._winner_idx.get(arm_name) == idx
                          else "")
                if arm_name == "host":
                    f.write(f"r{rnd},{cand[0]},{cand[1]},{cand[2]},"
                            f"{mean:.1f}{marker}\n")
                else:
                    f.write(f"r{rnd},{arm_name}={arm.fmt(cand)},"
                            f"{mean:.1f}{marker}\n")

    # -- knob application --------------------------------------------------
    def _apply_host(self, cand):
        fusion, cycle_ms, bucket = cand
        coord = self.runtime.coordinator
        coord.fusion_threshold = max(int(fusion), 1)
        coord.cycle_time_s = cycle_ms / 1000.0
        self._current["fusion_threshold"] = coord.fusion_threshold
        self._current["cycle_time_ms"] = float(cycle_ms)
        self._m_switches.inc()
        self._m_fusion.set(coord.fusion_threshold)
        self._m_cycle.set(cycle_ms)
        self.applied.append(("host", f"{coord.fusion_threshold}"
                                     f"/{cycle_ms}/{bucket}"))
        backend = self.runtime.backend
        if hasattr(backend, "core"):
            # Push the threshold into the native controller (reference:
            # the parameter manager's winners land in the controller's
            # fusion logic). Deterministic across ranks: candidate changes
            # are cycle-count driven.
            backend.core.set_fusion_threshold(max(int(fusion), 1))
        if bucket is not None and hasattr(backend, "set_min_bucket"):
            backend.set_min_bucket(bucket)
            self._current["min_bucket"] = int(bucket)
            self._m_bucket.set(bucket)

    def _apply_bucket_bytes(self, v):
        v = int(v)
        coord = self.runtime.coordinator
        coord._bucket_bytes = v
        # Construction-time readers (in-jit optimizer bucketing) pick
        # the tuned value up through the overlay on their next build.
        overlay.set_int(envparse.BUCKET_BYTES, v)
        self._current["bucket_bytes"] = v
        self._m_switches.inc()
        self._m_bucket_bytes.set(v)
        self.applied.append(("overlap", str(v)))

    def _apply_compression(self, cand):
        codec, threshold = cand
        plane = self.runtime.coordinator._compression
        from ..compression.policy import CompressionPolicy, parse_rules
        rules = [] if codec == "none" else parse_rules(codec)
        plane.policy = CompressionPolicy(rules, threshold=int(threshold))
        self._current["compression"] = codec
        self._current["compression_threshold"] = int(threshold)
        self._m_switches.inc()
        self._set_codec_gauge(codec)
        self._m_comp_threshold.set(int(threshold))
        self.applied.append(("compression", f"{codec}@{threshold}"))

    def _apply_zero_bucket(self, v):
        v = int(v)
        overlay.set_int(envparse.ZERO_BUCKET_BYTES, v)
        self._current["zero_bucket_bytes"] = v
        self._m_switches.inc()
        self._m_zero_bucket.set(v)
        self.applied.append(("zero", str(v)))

    def _apply_config(self, cfg):
        """Apply a stored warm-start config across every plane it
        names (unnamed planes keep their current values)."""
        if cfg.get("fusion_threshold") is not None:
            self._apply_host((cfg["fusion_threshold"],
                              float(cfg["cycle_time_ms"]),
                              cfg.get("min_bucket")))
        coord = self.runtime.coordinator
        if (cfg.get("bucket_bytes") is not None
                and hasattr(coord, "_bucket_bytes")):
            self._apply_bucket_bytes(cfg["bucket_bytes"])
        if cfg.get("compression") is not None:
            plane = getattr(coord, "_compression", None)
            if self._catchall_codec(plane) is not None:
                threshold = cfg.get("compression_threshold")
                if threshold is None:   # 0 = compress everything, keep it
                    threshold = plane.policy.threshold
                self._apply_compression((cfg["compression"], threshold))
            else:
                self._log.warning(
                    "autotune: cached compression codec %r not applied "
                    "— the live policy is absent or carries per-glob "
                    "rules the tuner must not overwrite",
                    cfg["compression"])
        # Same mode gate as the zero arm in _build_arms: in SPMD the
        # per-process step loops would observe the overlay bump at
        # different step indices and re-plan onto divergent shard
        # geometries — a cached value must not re-introduce that.
        from .. import basics
        if (cfg.get("zero_bucket_bytes") is not None
                and envparse.get_bool(envparse.ZERO)
                and getattr(self.runtime, "mode", None)
                == basics.MODE_SINGLE):
            self._apply_zero_bucket(cfg["zero_bucket_bytes"])
