"""Persistent warm-start store for converged autotune winners.

One JSON file (``HVDTPU_AUTOTUNE_CACHE``) maps a workload key —
``(model-signature, world-size, codec-availability)`` — to the
converged knob config, its score, the elastic version it was validated
under, and the sweep history that produced it. A repeat run loads the
file at init and applies the stored winner before the first scored
window (core.ParameterManager warm-start); ``hvd-autotune`` renders,
diffs and clears it.

The model signature is trace-driven: the sorted set of collective
tensor names observed during the warmup window (the flight-recorder
ring — on by default — already holds them), hashed. Tensor names are
identical on every rank of a correct program (the same invariant the
tracer's correlation keys and the guardian's sampled slots rely on),
so every rank derives the same key without a collective.
``HVDTPU_AUTOTUNE_SIGNATURE`` overrides it for jobs that disable the
flight recorder or want explicit cache identities.

Failure contract: a corrupt or schema-stale file NEVER breaks init —
:func:`load` raises :class:`StoreError`, the tuner logs it loudly,
counts it (``hvd_autotune_warm_start_total{outcome=corrupt}``) and
runs a fresh sweep; the next converged save atomically replaces the
bad file.
"""

import hashlib
import json
import os
import time

#: Schema version of the cache file; entries written under a different
#: format are stale and trigger a fresh sweep (loudly).
FORMAT = 1

#: Config keys a valid entry must carry (None allowed per plane).
CONFIG_KEYS = ("fusion_threshold", "cycle_time_ms", "min_bucket",
               "bucket_bytes", "compression", "compression_threshold",
               "zero_bucket_bytes")


class StoreError(Exception):
    """Cache file unreadable / corrupt / schema-stale."""


def model_signature(names):
    """Hash of the sorted collective-name set observed during warmup
    (``hvdlint.*`` guard-internal ops excluded — they submit on a
    timer, not per step)."""
    keep = sorted({n for n in names
                   if n and not n.startswith("hvdlint.")})
    if not keep:
        return "default"
    digest = hashlib.sha1(",".join(keep).encode()).hexdigest()[:12]
    return f"m{digest}"


def codec_signature(runtime):
    """Availability half of the key: which wire codecs this build
    carries and whether the backend has the quantized pipeline — a
    cache entry tuned with fp8 must not warm-start a build without
    it."""
    from ..compression import codecs
    avail = ["int8"] + (["fp8"] if codecs.fp8_supported() else [])
    backend = getattr(runtime, "backend", None)
    if backend is not None and hasattr(backend, "allreduce_quantized"):
        avail.append("q")
    return "+".join(avail)


def make_key(signature, world, codec_sig):
    return f"{signature}|w{world}|{codec_sig}"


def load(path):
    """Entries dict of a cache file. Missing file -> ``{}`` (a first
    run is not an error); anything unreadable/invalid ->
    :class:`StoreError` naming the problem."""
    if not os.path.exists(path):
        return {}
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError) as exc:
        raise StoreError(f"cannot parse autotune cache {path}: {exc}")
    if not isinstance(data, dict) or "entries" not in data:
        raise StoreError(
            f"autotune cache {path} has no 'entries' table")
    if data.get("format") != FORMAT:
        raise StoreError(
            f"autotune cache {path} is format {data.get('format')!r}, "
            f"this build writes format {FORMAT}")
    entries = data["entries"]
    if not isinstance(entries, dict):
        raise StoreError(f"autotune cache {path}: 'entries' is not a "
                         "table")
    return entries


def validate_entry(entry):
    """None when ``entry`` is usable, else a short reason string (the
    tuner treats a bad entry as stale: loud warning + fresh sweep)."""
    if not isinstance(entry, dict):
        return "entry is not an object"
    cfg = entry.get("config")
    if not isinstance(cfg, dict):
        return "no config object"
    missing = [k for k in CONFIG_KEYS if k not in cfg]
    if missing:
        return f"config missing {missing}"
    for k in ("fusion_threshold", "cycle_time_ms"):
        if not isinstance(cfg[k], (int, float)):
            return f"config.{k} is not numeric"
    return None


def _write(path, entries):
    """Atomic whole-file write (tmp + rename) of an entries table."""
    payload = {"format": FORMAT, "entries": entries}
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1, sort_keys=True)
    os.replace(tmp, path)


def save_entry(path, key, entry):
    """Read-modify-write the cache with one entry upserted, atomically.
    An existing corrupt file is replaced rather than crashed on — the
    save IS the repair. Raises OSError on unwritable paths (the caller
    logs; tuning results must never kill a job)."""
    try:
        entries = load(path)
    except StoreError:
        entries = {}
    entries[key] = entry
    _write(path, entries)


def clear(path, key=None):
    """Remove one entry (or the whole file). Returns the number of
    entries removed."""
    if key is None:
        if os.path.exists(path):
            try:
                n = len(load(path))
            except StoreError:
                n = 0
            os.remove(path)
            return n
        return 0
    entries = load(path)
    if key not in entries:
        return 0
    del entries[key]
    _write(path, entries)
    return 1


def make_entry(config, score, source, signature, world, codec_sig,
               elastic_version, history, predicted=None):
    """The JSON shape one converged sweep persists. ``predicted``
    (optional) carries the α–β cost model's per-arm predicted cost of
    the winner when the sweep ran with ``HVDTPU_COSTMODEL`` priors —
    audit data for prediction-vs-measured drift, ignored by
    validate_entry so old readers and old entries interoperate."""
    entry = {
        "config": dict(config),
        "score": float(score),
        "score_source": source,
        "signature": signature,
        "world": int(world),
        "codecs": codec_sig,
        "elastic_version": str(elastic_version),
        "updated_unix": time.time(),
        "history": [[arm, int(rnd), cand, float(mean)]
                    for arm, rnd, cand, mean in history],
    }
    if predicted is not None:
        entry["predicted"] = predicted
    return entry
