"""Candidate score sources: what a knob config is judged by.

The original tuner scored candidates by coordinator bytes/sec — a
proxy that rewards moving bytes, not finishing steps (a config that
inflates traffic scores *better*). The trace plane (PR 8) measures the
thing that actually bounds training: per-step critical-path time. This
module turns its always-on flight-recorder ring into a live score:

Every closed window reports BOTH units — ``{"bytes": rate, "steps":
rate-or-None}`` — because fallback windows and trace-scored windows
are not comparable (a bytes/sec ~1e8 would always beat a steps/sec
~10); the tuner's decisions (halving survivors, the re-validation
verdict) pick ONE unit per comparison set: steps when every window in
the set has step structure, else bytes, which every window carries.

- :class:`BytesScore` — the legacy cycle-thread bytes/sec (mean of the
  window's per-active-cycle rates). Always available.
- :class:`TraceScore` — **steps/sec** over the scoring window. A step
  is one occurrence number with every submitted collective finished
  (the same name x occurrence correlation the offline analyzer joins
  on); the window's score is completed steps over the span from the
  first submit to the last finish — submit-to-finish critical path
  plus the compute gaps between collectives, i.e. real step pacing.
  The window's mean step span and collective overlap fraction are
  published as gauges so a sweep is debuggable from /metrics. When the
  live ``hvd_straggler_delay_seconds`` gauge is being fed (a job
  running ``hvd-trace report --metrics`` alongside), this rank's newly
  attributed straggler delay stretches the effective span — a config
  that makes THIS rank the one gating peers scores worse even when its
  local throughput looks fine. Falls back to bytes/sec when the
  window saw fewer than two complete steps (or the ring is off).

``HVDTPU_AUTOTUNE_SCORE`` picks: ``auto`` (trace when it has step
structure, bytes otherwise — the default), ``steps`` (trace or loud
fallback), ``bytes`` (legacy only).

Scores stay rank-local and timing-noisy by design — the determinism
contract lives in the cycle-driven candidate switches and the
round-boundary broadcast of rank 0's survivors (core.py), not in the
scores.
"""

import time

from ..telemetry import core as telemetry
from ..utils.logging_util import get_logger

#: Minimum complete steps a window must show before steps/sec is
#: trusted over bytes/sec.
MIN_STEPS = 2


def _mean(values):
    return sum(values) / len(values) if values else 0.0


class BytesScore:
    """Legacy score: mean per-active-cycle bytes/sec of the window."""

    name = "bytes"

    def open_window(self):
        pass

    def close_window(self, cycle_rates):
        return {"bytes": _mean(cycle_rates), "steps": None}


def window_stats(events, t0, t1):
    """Step structure of the ring events in ``(t0, t1]``.

    Returns ``None`` when fewer than :data:`MIN_STEPS` occurrence
    groups completed cleanly, else a dict with ``steps`` (count),
    ``span_s`` (first submit -> last finish over the complete steps),
    ``mean_step_s`` and ``overlap_fraction`` (1 - union/total of the
    completed collectives' in-flight intervals). Groups that saw a
    finish without its submit (the submit predates the window or fell
    off the ring) are dirty and excluded rather than miscounted.
    """
    pending = {}
    groups = {}
    intervals = []
    for ev in events:
        t = ev.get("t")
        if t is None or t <= t0 or t > t1:
            continue
        kind = ev.get("e")
        if kind == "sub":
            key = (ev.get("n"), ev.get("o"))
            pending[key] = t
            g = groups.setdefault(ev.get("o"),
                                  {"sub": [], "fin": [], "open": 0,
                                   "dirty": False})
            g["sub"].append(t)
            g["open"] += 1
        elif kind == "fin":
            key = (ev.get("n"), ev.get("o"))
            sub_t = pending.pop(key, None)
            # A finish without its submit straddles the window start
            # (or the submit fell off the ring) — the whole occurrence
            # is dirty, even when the group doesn't exist yet: later
            # in-window collectives of the same occurrence must not
            # make it look like a clean (shorter) step. An err-flagged
            # finish is dirty too: a fast-FAILING collective must not
            # score as a fast step.
            g = groups.setdefault(ev.get("o"),
                                  {"sub": [], "fin": [], "open": 0,
                                   "dirty": False})
            if sub_t is None or ev.get("err"):
                g["dirty"] = True
                continue
            g["fin"].append(t)
            g["open"] -= 1
            intervals.append((sub_t, t))
    complete = [g for g in groups.values()
                if g["fin"] and not g["open"] and not g["dirty"]]
    if len(complete) < MIN_STEPS:
        return None
    first_sub = min(min(g["sub"]) for g in complete)
    last_fin = max(max(g["fin"]) for g in complete)
    span = last_fin - first_sub
    if span <= 0:
        return None
    spans = [max(g["fin"]) - min(g["sub"]) for g in complete]
    total = sum(b - a for a, b in intervals)
    union, cur = 0.0, None
    for a, b in sorted(intervals):
        if cur is None or a > cur[1]:
            if cur is not None:
                union += cur[1] - cur[0]
            cur = [a, b]
        else:
            cur[1] = max(cur[1], b)
    if cur is not None:
        union += cur[1] - cur[0]
    return {
        "steps": len(complete),
        "span_s": span,
        "mean_step_s": _mean(spans),
        "overlap_fraction": (1.0 - union / total) if total > 0 else 0.0,
    }


class TraceScore:
    """Steps/sec from the flight-recorder ring, bytes/sec fallback."""

    name = "steps"

    def __init__(self, runtime, rank=0, strict=False):
        self._runtime = runtime
        self._rank = str(rank)
        self._strict = strict
        self._warned = False
        self._t0 = time.time()
        self._straggler0 = 0.0
        self._log = get_logger()
        self._metrics_on = telemetry.enabled()
        self._m_step_s = telemetry.gauge(
            "hvd_autotune_step_seconds",
            "Mean step span (first submit -> last finish) of the last "
            "trace-scored autotune window")
        self._m_overlap = telemetry.gauge(
            "hvd_autotune_window_overlap_fraction",
            "Collective overlap fraction of the last trace-scored "
            "autotune window (ring-derived)")

    def _ring(self):
        tracer = getattr(self._runtime, "tracer", None)
        flight = getattr(tracer, "_flight", None)
        return None if flight is None else flight.snapshot()

    def _straggler_delay(self):
        """This rank's cumulative attributed straggler delay, when a
        live analyzer feeds the gauge (0.0 otherwise). Read through
        the registry snapshot: one dict walk per window, nothing per
        cycle."""
        if not self._metrics_on:
            return 0.0
        fam = (telemetry.snapshot().get("families") or {}).get(
            "hvd_straggler_delay_seconds")
        if not fam:
            return 0.0
        for sample in fam.get("samples") or []:
            if (sample.get("labels") or {}).get("rank") == self._rank:
                return float(sample.get("value") or 0.0)
        return 0.0

    def open_window(self):
        self._t0 = time.time()
        self._straggler0 = self._straggler_delay()

    def close_window(self, cycle_rates):
        events = self._ring()
        stats = None
        if events is not None:
            stats = window_stats(events, self._t0, time.time())
        out = {"bytes": _mean(cycle_rates), "steps": None}
        if stats is None:
            if self._strict and not self._warned:
                self._warned = True
                self._log.warning(
                    "autotune: HVDTPU_AUTOTUNE_SCORE=steps but the "
                    "window shows no step structure (flight recorder "
                    "off, or traffic has no repeated collective "
                    "names); scoring falls back to bytes/sec")
            return out
        self._m_step_s.set(stats["mean_step_s"])
        self._m_overlap.set(stats["overlap_fraction"])
        span = stats["span_s"]
        delta = max(0.0, self._straggler_delay() - self._straggler0)
        out["steps"] = stats["steps"] / (span + delta)
        return out


def make_source(runtime, mode, rank=0):
    """Score source for ``HVDTPU_AUTOTUNE_SCORE`` = auto|steps|bytes.
    Unknown values raise (the loud-typo contract every knob grammar in
    this codebase follows)."""
    if mode == "bytes":
        return BytesScore()
    if mode == "auto":
        return TraceScore(runtime, rank=rank, strict=False)
    if mode == "steps":
        return TraceScore(runtime, rank=rank, strict=True)
    raise ValueError(
        f"HVDTPU_AUTOTUNE_SCORE={mode!r}: expected auto, steps or "
        "bytes (docs/autotune.md)")
