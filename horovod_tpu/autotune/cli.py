"""``hvd-autotune``: inspect the warm-start store (docs/autotune.md).

Subcommands:

- ``show``    — render every cache entry (key, config, score, elastic
  version, age); ``--json`` for machines.
- ``history`` — dump one entry's sweep history (the per-round
  candidate scores the winner emerged from).
- ``diff``    — compare two store files (or the same file over time):
  added/removed keys and per-key config/score deltas.
- ``clear``   — delete one entry (``--key``) or the whole file.

The cache path comes from ``--cache`` or ``HVDTPU_AUTOTUNE_CACHE``.
Exit codes: 0 success, 1 usage/subcommand failure, 2 unreadable store.
"""

import argparse
import json
import sys
import time

from . import store
from ..utils import envparse


def _resolve_cache(args):
    path = args.cache or envparse.get_str(envparse.AUTOTUNE_CACHE, "")
    if not path:
        print("hvd-autotune: no cache path (pass --cache or set "
              "HVDTPU_AUTOTUNE_CACHE)", file=sys.stderr)
        raise SystemExit(1)
    return path


def _load(path):
    try:
        return store.load(path)
    except store.StoreError as exc:
        print(f"hvd-autotune: {exc}", file=sys.stderr)
        raise SystemExit(2)


def _fmt_config(cfg):
    parts = []
    for key in store.CONFIG_KEYS:
        val = cfg.get(key)
        if val is not None:
            parts.append(f"{key}={val}")
    return " ".join(parts) or "(empty)"


def _age(entry):
    ts = entry.get("updated_unix")
    if not ts:
        return "-"
    return f"{(time.time() - float(ts)) / 3600.0:.1f}h"


def cmd_show(args):
    entries = _load(_resolve_cache(args))
    if args.json:
        print(json.dumps(entries, indent=1, sort_keys=True))
        return 0
    if not entries:
        print("(empty store)")
        return 0
    for key in sorted(entries):
        e = entries[key]
        print(f"{key}")
        print(f"  config:  {_fmt_config(e.get('config') or {})}")
        print(f"  score:   {e.get('score', 0.0):.1f} "
              f"({e.get('score_source', '?')})  "
              f"elastic_version={e.get('elastic_version', '?')}  "
              f"age={_age(e)}")
    return 0


def _pick_entry(entries, key, path):
    if key:
        if key not in entries:
            print(f"hvd-autotune: no entry {key!r} in {path}",
                  file=sys.stderr)
            raise SystemExit(1)
        return key
    if len(entries) == 1:
        return next(iter(entries))
    print(f"hvd-autotune: {len(entries)} entries in {path}; pick one "
          "with --key (see `hvd-autotune show`)", file=sys.stderr)
    raise SystemExit(1)


def cmd_history(args):
    path = _resolve_cache(args)
    entries = _load(path)
    key = _pick_entry(entries, args.key, path)
    rows = entries[key].get("history") or []
    if args.json:
        print(json.dumps({"key": key, "history": rows}, indent=1))
        return 0
    print(f"{key}: {len(rows)} scored window(s)")
    print("  arm          round  candidate             score")
    for arm, rnd, cand, mean in rows:
        print(f"  {arm:<12} {rnd:>5}  {str(cand):<20} {mean:>9.1f}")
    return 0


def cmd_diff(args):
    a, b = _load(args.old), _load(args.new)
    changed = False
    for key in sorted(set(a) | set(b)):
        if key not in a:
            print(f"+ {key}: {_fmt_config(b[key].get('config') or {})}")
            changed = True
            continue
        if key not in b:
            print(f"- {key}")
            changed = True
            continue
        ca, cb = a[key].get("config") or {}, b[key].get("config") or {}
        deltas = [f"{k}: {ca.get(k)} -> {cb.get(k)}"
                  for k in store.CONFIG_KEYS if ca.get(k) != cb.get(k)]
        sa, sb = a[key].get("score", 0.0), b[key].get("score", 0.0)
        if abs(sa - sb) > 1e-9:
            deltas.append(f"score: {sa:.1f} -> {sb:.1f}")
        if deltas:
            changed = True
            print(f"~ {key}")
            for d in deltas:
                print(f"    {d}")
    if not changed:
        print("(no differences)")
    return 0


def cmd_clear(args):
    path = _resolve_cache(args)
    try:
        n = store.clear(path, key=args.key or None)
    except (store.StoreError, OSError) as exc:
        print(f"hvd-autotune: {exc}", file=sys.stderr)
        return 2
    what = f"entry {args.key!r}" if args.key else "store"
    print(f"cleared {what} ({n} entr{'y' if n == 1 else 'ies'}) "
          f"at {path}")
    return 0


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="hvd-autotune",
        description="Inspect the autotune warm-start store "
                    "(docs/autotune.md)")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("show", help="render every cache entry")
    p.add_argument("--cache", default="")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_show)

    p = sub.add_parser("history", help="one entry's sweep history")
    p.add_argument("--cache", default="")
    p.add_argument("--key", default="")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_history)

    p = sub.add_parser("diff", help="compare two store files")
    p.add_argument("old")
    p.add_argument("new")
    p.set_defaults(fn=cmd_diff)

    p = sub.add_parser("clear", help="delete an entry or the store")
    p.add_argument("--cache", default="")
    p.add_argument("--key", default="")
    p.set_defaults(fn=cmd_clear)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
