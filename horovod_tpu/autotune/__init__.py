"""Trace-driven online autotuner (ISSUE 12; ROADMAP item 5).

The feedback loop between the trace plane's live measurements and the
knob registry: an online tuner that searches the landed perf planes —
fusion threshold x cycle time x delegated min bucket, overlap bucket
bytes, compression codec x threshold, ZeRO leg buckets — with
per-plane successive-halving arms, scores candidates by real per-step
signals from the flight-recorder ring (steps/sec over correlated
submit/finish spans, not just cycle-thread bytes/sec), and persists
converged winners per (model-signature, world-size,
codec-availability) key for instant warm start on repeat runs.

Modules:

- :mod:`core`    — the :class:`ParameterManager` state machine
  (warmup -> warm-start decision -> confirm windows or per-arm sweep);
- :mod:`score`   — the bytes/sec and trace-derived steps/sec sources;
- :mod:`store`   — the persistent warm-start JSON store;
- :mod:`overlay` — tuned values for construction-time knobs
  (``HVDTPU_BUCKET_BYTES`` / ``HVDTPU_ZERO_BUCKET_BYTES``);
- :mod:`cli`     — the ``hvd-autotune`` console entry
  (show/history/diff/clear).

Disabled contract (the telemetry/chaos/guardian standard): with
``HVDTPU_AUTOTUNE`` unset, ``basics.init`` never constructs a
ParameterManager — ``runtime.autotuner`` stays ``None`` and the
coordinator cycle pays one attribute check (guard-tested).

See docs/autotune.md for the search structure, score sources, cache
format and CLI walkthrough.
"""

from . import overlay, score, store  # noqa: F401  (subsystem surface)
from .core import (  # noqa: F401  (re-exported API)
    BUCKET_BYTES_CANDIDATES_MIB, BUCKET_CANDIDATES,
    CYCLE_CANDIDATES_MS, CYCLES_PER_CANDIDATE, FUSION_CANDIDATES_MIB,
    ParameterManager, WARMUP_CYCLES, ZERO_BUCKET_CANDIDATES_MIB,
)
