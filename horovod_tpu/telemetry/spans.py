"""Span API: one context manager that feeds BOTH planes.

The coordinator used to bracket work with ad-hoc ``timeline.begin`` /
``timeline.end`` pairs; metrics would have added a second pair of
``perf_counter`` reads next to each. A span is the single instrument:
entering emits the timeline begin event, exiting emits the end event and
feeds the elapsed seconds into a histogram. Either sink may be absent —
with neither, the shared ``NULL_SPAN`` is returned so a disabled hot
path allocates nothing.
"""

import time

from .core import NULL


class Span:
    __slots__ = ("_names", "_activity", "_timeline", "_histogram", "_t0")

    def __init__(self, names, activity, timeline=None, histogram=None):
        self._names = names
        self._activity = activity
        self._timeline = timeline
        self._histogram = histogram
        self._t0 = 0.0

    def __enter__(self):
        if self._timeline is not None:
            self._timeline.begin(self._names, self._activity)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        elapsed = time.perf_counter() - self._t0
        if self._histogram is not None:
            self._histogram.observe(elapsed)
        if self._timeline is not None and exc_type is None:
            # Failure paths leave the timeline event open, matching the
            # previous begin/end behavior (the error is what matters).
            self._timeline.end(self._names, self._activity)
        return False


class _NullSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False


NULL_SPAN = _NullSpan()


def span(names, activity, timeline=None, histogram=None):
    """Build a span over ``names``; no-op when both sinks are absent."""
    if histogram is None or histogram is NULL:
        if timeline is None:
            return NULL_SPAN
        histogram = None
    return Span(names, activity, timeline=timeline, histogram=histogram)
