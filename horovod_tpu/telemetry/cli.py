"""``hvd-metrics``: console client for the metrics plane.

    hvd-metrics dump  --url http://driver:port --token T   # one snapshot
    hvd-metrics dump  snapshot.json --format prom          # from a file
    hvd-metrics watch --url ... --interval 2               # live deltas
    hvd-metrics diff  before.json after.json               # two snapshots

``dump`` prints a snapshot as Prometheus text (default) or JSON; a URL
source hits the runner HTTP server's token-gated ``/metrics.json``
route, a file source reads a snapshot written by ``HVDTPU_METRICS_DUMP``
or ``bench.py``. ``watch`` re-scrapes on an interval and prints per-
second rates for counters. ``diff`` subtracts two snapshot files —
counter deltas and histogram count/sum deltas — the evidence format
perf PRs cite. Exit codes: 0 ok, 2 usage/fetch error.
"""

import argparse
import json
import sys
import time
import urllib.error
import urllib.request

from . import aggregate, exposition


def _fetch_url(url, token):
    req = urllib.request.Request(url.rstrip("/") + "/metrics.json")
    if token:
        from ..runner.http_server import AUTH_HEADER
        req.add_header(AUTH_HEADER, token)
    with urllib.request.urlopen(req, timeout=10) as resp:
        return json.loads(resp.read().decode())


def _load(source, token):
    """A snapshot dict from a URL (http[s]://) or a JSON file path."""
    if source.startswith(("http://", "https://")):
        payload = _fetch_url(source, token)
        # The route returns {"local": ..., "ranks": {...}}; a bare
        # registry snapshot has "families" at top level.
        if "families" in payload:
            return payload
        snaps = {int(r): s for r, s in payload.get("ranks", {}).items()}
        if snaps:
            merged = dict(payload.get("local", {"families": {}}))
            merged = {"ts": merged.get("ts", time.time()),
                      "families": dict(merged.get("families", {}))}
            merged["families"].update(
                aggregate.aggregate(snaps)["families"])
            return merged
        return payload.get("local", {"families": {}})
    with open(source) as f:
        return json.load(f)


def _flatten(snap):
    """{(family, label-tuple): scalar} for diff/watch — counters and
    gauges by value, histograms by (count, sum) pseudo-series."""
    out = {}
    for name, fam in snap.get("families", {}).items():
        for sample in fam["samples"]:
            key = (name, tuple(sorted(sample.get("labels", {}).items())))
            if fam["type"] == "histogram":
                out[key + (("__count__",),)] = float(sample["count"])
                out[key + (("__sum__",),)] = float(sample["sum"])
            else:
                out[key] = float(sample["value"])
    return out


def _key_str(key):
    name, labels = key[0], key[1]
    suffix = ""
    if len(key) == 3:
        suffix = ".count" if key[2] == ("__count__",) else ".sum"
    label_s = ",".join(f"{k}={v}" for k, v in labels)
    return f"{name}{suffix}" + (f"{{{label_s}}}" if label_s else "")


def _cmd_dump(args):
    snap = _load(args.source, args.token)
    if args.format == "json":
        print(exposition.render_json(snap, indent=1))
    else:
        sys.stdout.write(exposition.render_prometheus(snap))
    return 0


def _cmd_watch(args):
    prev = None
    try:
        while True:
            snap = _load(args.source, args.token)
            flat = _flatten(snap)
            now = time.strftime("%H:%M:%S")
            print(f"-- {now} ({len(flat)} series) " + "-" * 30)
            for key in sorted(flat):
                line = f"{_key_str(key):64s} {flat[key]:14.6g}"
                if prev is not None and key in prev:
                    delta = flat[key] - prev[key]
                    if delta:
                        line += f"  (+{delta:.6g}/{args.interval:g}s)"
                print(line)
            prev = flat
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


def _cmd_diff(args):
    before = _flatten(_load(args.before, args.token))
    after = _flatten(_load(args.after, args.token))
    changed = 0
    for key in sorted(set(before) | set(after)):
        a, b = before.get(key, 0.0), after.get(key, 0.0)
        if a != b:
            changed += 1
            print(f"{_key_str(key):64s} {a:14.6g} -> {b:14.6g} "
                  f"({b - a:+.6g})")
    print(f"hvd-metrics: {changed} series changed")
    return 0


def _build_parser():
    parser = argparse.ArgumentParser(
        prog="hvd-metrics",
        description="Inspect horovod_tpu runtime metrics (see "
                    "docs/metrics.md).")
    sub = parser.add_subparsers(dest="command", required=True)

    def _source_args(p):
        p.add_argument("source", nargs="?", default=None,
                       help="snapshot JSON file, or use --url")
        p.add_argument("--url", default=None,
                       help="runner HTTP server base URL "
                            "(http://driver:port)")
        p.add_argument("--token", default="",
                       help="job token for the /metrics route")

    dump = sub.add_parser("dump", help="print one snapshot")
    _source_args(dump)
    dump.add_argument("--format", choices=("prom", "json"),
                      default="prom")

    watch = sub.add_parser("watch", help="re-scrape and print rates")
    _source_args(watch)
    watch.add_argument("--interval", type=float, default=2.0)

    diff = sub.add_parser("diff", help="subtract two snapshot files")
    diff.add_argument("before")
    diff.add_argument("after")
    diff.add_argument("--token", default="")
    return parser


def main(argv=None):
    args = _build_parser().parse_args(argv)
    if args.command in ("dump", "watch"):
        args.source = args.url or args.source
        if not args.source:
            print("hvd-metrics: need a snapshot file or --url",
                  file=sys.stderr)
            return 2
    try:
        if args.command == "dump":
            return _cmd_dump(args)
        if args.command == "watch":
            return _cmd_watch(args)
        return _cmd_diff(args)
    except (OSError, urllib.error.URLError, json.JSONDecodeError) as exc:
        print(f"hvd-metrics: {exc}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
