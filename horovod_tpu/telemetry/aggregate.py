"""Driver-side cluster aggregation: per-rank snapshots → roll-ups.

Each worker pushes its registry snapshot (JSON) into the launcher's KV
store under ``metrics/<rank>`` on a timer (MetricsPusher, started by
``basics.init`` when metrics are on and the job has a rendezvous). The
driver's /metrics route — and the ``hvd-metrics`` CLI — then roll the
per-rank snapshots up: scalar families get min/max/mean across ranks,
histograms are bucket-merged and additionally report p50/p99 estimated
from the merged cumulative counts. Aggregated families are emitted as
``<name>_cluster{stat=...}`` gauges so one Prometheus scrape of the
driver carries the whole job.
"""

import json
import threading
import time

from . import core

METRICS_SCOPE = "metrics"
DEFAULT_PUSH_INTERVAL_S = 5.0


def quantile_from_buckets(buckets, q):
    """Estimate quantile ``q`` from cumulative ``[(le, cum), ...]``
    (Prometheus-style: the answer is the upper bound of the bucket the
    quantile falls in — conservative, monotone)."""
    if not buckets:
        return 0.0
    total = buckets[-1][1]
    if total == 0:
        return 0.0
    target = q * total
    prev_bound = 0.0
    for bound, cum in buckets:
        if cum >= target:
            return bound if bound != float("inf") else prev_bound
        prev_bound = bound
    return prev_bound


def _merge_buckets(per_rank):
    """Sum cumulative counts across ranks (bucket bounds are identical:
    every rank runs the same metric definitions)."""
    merged = {}
    for buckets in per_rank:
        for bound, cum in buckets:
            merged[bound] = merged.get(bound, 0) + cum
    return sorted(merged.items())


def aggregate(snapshots):
    """Roll a ``{rank: snapshot}`` map up into one snapshot-like dict of
    ``<name>_cluster`` gauge families with a ``stat`` label."""
    fams = {}
    # family -> label-key -> list of per-rank samples
    collected = {}
    for _rank, snap in sorted(snapshots.items()):
        for name, fam in snap.get("families", {}).items():
            meta = collected.setdefault(
                name, {"type": fam["type"], "help": fam.get("help", ""),
                       "series": {}})
            for sample in fam["samples"]:
                key = tuple(sorted(sample.get("labels", {}).items()))
                meta["series"].setdefault(key, []).append(sample)

    for name, meta in sorted(collected.items()):
        samples = []
        for key, per_rank in sorted(meta["series"].items()):
            labels = dict(key)
            if meta["type"] == "histogram":
                merged = _merge_buckets(
                    [s["buckets"] for s in per_rank])
                count = sum(s["count"] for s in per_rank)
                total = sum(s["sum"] for s in per_rank)
                stats = {
                    "mean": (total / count) if count else 0.0,
                    "p50": quantile_from_buckets(merged, 0.50),
                    "p99": quantile_from_buckets(merged, 0.99),
                    "count": float(count),
                }
            else:
                values = [s["value"] for s in per_rank]
                stats = {
                    "min": min(values),
                    "max": max(values),
                    "mean": sum(values) / len(values),
                    "sum": float(sum(values)),
                }
            for stat, value in sorted(stats.items()):
                samples.append(
                    {"labels": {**labels, "stat": stat}, "value": value})
        fams[f"{name}_cluster"] = {
            "type": "gauge",
            "help": (meta["help"] + " (cluster roll-up)").strip(),
            "labelnames": [], "samples": samples}
    return {"ts": time.time(), "ranks": len(snapshots), "families": fams}


# -- KV-store plumbing -----------------------------------------------------

def push_snapshot(addr, port, token, rank, snap=None):
    """PUT this process's snapshot under metrics/<rank> (worker side)."""
    from ..runner import http_client
    snap = snap if snap is not None else core.snapshot()
    http_client.put_kv(addr, port, METRICS_SCOPE, str(rank),
                       json.dumps(snap), token=token)


def parse_rank_snapshots(raw):
    """``{rank_key: json bytes/str}`` → ``{rank: snapshot}``.
    Unparseable entries are skipped, not fatal — one wedged worker must
    not take down the whole roll-up."""
    snaps = {}
    for key, value in raw.items():
        try:
            snaps[int(key)] = json.loads(
                value.decode() if isinstance(value, bytes) else value)
        except (ValueError, AttributeError):
            continue
    return snaps


def store_snapshots(server):
    """Read every pushed rank snapshot out of a KVStoreServer
    (driver side)."""
    return parse_rank_snapshots(
        {key: server.get(METRICS_SCOPE, key)
         for key in server.scope_keys(METRICS_SCOPE)})


class MetricsPusher:
    """Daemon thread pushing snapshots on an interval; one final push on
    stop so shutdown-time counters (elastic restarts) reach the driver.

    Thread-ownership contract (hvd-sanitize audit): every attribute is
    set in __init__ before start() and never reassigned — the roll-up
    thread only READS them, so no lock is needed. The one deliberate
    overlap: stop() joins with a timeout, so a push wedged in the KV
    client can still be mid-flight while stop() issues the final push;
    both write the same per-rank key, so last-writer-wins is correct
    (and _push swallows transport errors either way)."""

    def __init__(self, addr, port, token, rank,
                 interval_s=DEFAULT_PUSH_INTERVAL_S):
        self._args = (addr, port, token, rank)
        self._interval = max(0.5, float(interval_s))
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="hvd-tpu-metrics-push", daemon=True)

    def start(self):
        self._thread.start()
        return self

    def _push(self):
        try:
            push_snapshot(*self._args)
        except OSError:
            pass  # driver gone / restarting: metrics must never kill a job

    def _loop(self):
        while not self._stop.wait(self._interval):
            self._push()

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=5)
        self._push()
