"""horovod_tpu.telemetry: the metrics & observability plane.

The quantitative counterpart to the Chrome timeline: counters, gauges
and log-bucketed histograms with labels (core.py), a span API feeding
both the histograms and the timeline (spans.py), Prometheus/JSON
exposition (exposition.py) served from the runner HTTP server's
token-gated ``/metrics`` route, driver-side cluster roll-ups
(aggregate.py), and the ``hvd-metrics`` console CLI (cli.py).

Enable with ``HOROVOD_TPU_METRICS=1``; when off, every factory returns
a shared no-op and instrumented hot paths cost one dead method call.
Snapshot programmatically via ``hvd.metrics_snapshot()``.
"""

from .core import (  # noqa: F401
    NULL, BYTES_BUCKETS, SECONDS_BUCKETS, Counter, Gauge, Histogram,
    Registry, counter, enabled, gauge, histogram, log_buckets, registry,
    reset, snapshot,
)
from .spans import NULL_SPAN, Span, span  # noqa: F401
from .exposition import (  # noqa: F401
    PROMETHEUS_CONTENT_TYPE, parse_prometheus, render_json,
    render_prometheus,
)
from .aggregate import (  # noqa: F401
    METRICS_SCOPE, MetricsPusher, parse_rank_snapshots, push_snapshot,
    quantile_from_buckets, store_snapshots,
)
# The roll-up function under a non-module-shadowing name (the submodule
# stays reachable as telemetry.aggregate).
from .aggregate import aggregate as aggregate_snapshots  # noqa: F401
