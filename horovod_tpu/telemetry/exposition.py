"""Snapshot renderers: Prometheus text format v0.0.4 and JSON.

The wire formats are deliberately dependency-free: Prometheus's text
exposition is a stable line protocol (``# HELP`` / ``# TYPE`` headers,
``name{label="v"} value`` samples, cumulative ``_bucket{le=...}``
series for histograms) and the JSON form is just the registry snapshot
(core.Registry.snapshot) — both render the same dict, so the /metrics
route, the CLI, and the cluster aggregator share one code path.
"""

import json

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _escape_label(value):
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _format_value(v):
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    f = float(v)
    return repr(int(f)) if f == int(f) else repr(f)


def _label_str(labels, extra=None):
    pairs = list(labels.items()) + list((extra or {}).items())
    if not pairs:
        return ""
    inner = ",".join(f'{k}="{_escape_label(v)}"' for k, v in pairs)
    return "{" + inner + "}"


def render_prometheus(snapshot):
    """Prometheus text format v0.0.4 of a registry snapshot."""
    lines = []
    for name in sorted(snapshot.get("families", {})):
        fam = snapshot["families"][name]
        if fam.get("help"):
            lines.append(f"# HELP {name} {fam['help']}")
        lines.append(f"# TYPE {name} {fam['type']}")
        for sample in fam["samples"]:
            labels = sample.get("labels", {})
            if fam["type"] == "histogram":
                for bound, cum in sample["buckets"]:
                    lines.append(
                        f"{name}_bucket"
                        f"{_label_str(labels, {'le': _format_value(bound)})}"
                        f" {cum}")
                lines.append(f"{name}_sum{_label_str(labels)} "
                             f"{_format_value(sample['sum'])}")
                lines.append(f"{name}_count{_label_str(labels)} "
                             f"{sample['count']}")
            else:
                lines.append(f"{name}{_label_str(labels)} "
                             f"{_format_value(sample['value'])}")
    return "\n".join(lines) + "\n"


def render_json(snapshot, indent=None):
    return json.dumps(snapshot, indent=indent, sort_keys=True)


def parse_prometheus(text):
    """Minimal parser for the text we render: returns
    ``{metric_name: {label_tuple: value}}`` (no bucket reconstruction).
    Used by the CLI's watch/diff against a live /metrics route and by
    tests asserting the exposition round-trips."""
    out = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        name_part, _, value = line.rpartition(" ")
        if "{" in name_part:
            name, _, rest = name_part.partition("{")
            labels = tuple(
                p for p in rest.rstrip("}").split('",')
                if p) if rest else ()
        else:
            name, labels = name_part, ()
        try:
            v = float(value)
        except ValueError:
            continue
        out.setdefault(name, {})[labels] = v
    return out
