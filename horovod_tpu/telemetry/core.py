"""Lock-cheap, thread-safe metrics core: Counter / Gauge / Histogram.

The reference framework exposes no quantitative runtime signal at all —
its only introspection is the Chrome timeline and the stall inspector's
log lines. This core is the missing instrument panel: named metric
families with labels, log-bucketed latency/byte histograms, and a
registry that snapshots to JSON and Prometheus text (exposition.py).

Cost model (the contract every instrumented hot path relies on):

- **Disabled** (``HOROVOD_TPU_METRICS`` unset/0): every factory returns
  the shared ``NULL`` singleton whose methods are empty — no metric
  objects are created, the registry stays empty, and an instrumented
  call site pays one no-op method call. Nothing accumulates.
- **Enabled**: one small ``threading.Lock`` per child (uncontended
  acquire ~100 ns) guards the read-modify-write; histogram observe is a
  bisect over ~20 precomputed bucket bounds. No allocation per update.

Enablement is resolved once, lazily, at the first factory call; tests
flip it via ``reset()`` after monkeypatching the env knob.
"""

import bisect
import math
import threading
import time

from ..analysis import sanitizer
from ..utils import envparse


def log_buckets(lo, hi, factor=2.0):
    """Geometric (log-spaced) bucket upper bounds from ``lo`` until
    ``hi`` is covered. The +Inf bucket is implicit."""
    if lo <= 0 or factor <= 1:
        raise ValueError("log_buckets needs lo > 0 and factor > 1")
    bounds = [lo]
    while bounds[-1] < hi:
        bounds.append(bounds[-1] * factor)
    return bounds


# Defaults: latency spans 10 us .. ~80 s; byte sizes span 64 B .. 1 GiB.
SECONDS_BUCKETS = log_buckets(1e-5, 80.0)
BYTES_BUCKETS = log_buckets(64.0, float(1 << 30), factor=4.0)


class _NullMetric:
    """Shared no-op stand-in for every metric type when metrics are off
    (and for spans' "no histogram" case). One instance, no state."""

    __slots__ = ()

    def labels(self, **kwargs):
        return self

    def inc(self, amount=1):
        pass

    def dec(self, amount=1):
        pass

    def set(self, value):
        pass

    def observe(self, value):
        pass

    @property
    def value(self):
        return 0.0


NULL = _NullMetric()


class _Child:
    """One labeled time series. Value semantics differ per kind but the
    storage is shared: scalar for counter/gauge, bucket counts + sum for
    histograms."""

    __slots__ = ("_lock", "_value", "_bounds", "_counts", "_sum")

    def __init__(self, bounds=None):
        # Leaf lock, deliberately uninstrumented: one per labeled
        # series on the hottest paths, held for a scalar update, and
        # nothing is ever acquired under it — it cannot participate in
        # an ordering cycle.
        self._lock = threading.Lock()
        self._value = 0.0
        self._bounds = bounds
        if bounds is not None:
            self._counts = [0] * (len(bounds) + 1)  # +1 for +Inf
            self._sum = 0.0

    # counter / gauge -----------------------------------------------------
    def inc(self, amount=1):
        with self._lock:
            self._value += amount

    def dec(self, amount=1):
        with self._lock:
            self._value -= amount

    def set(self, value):
        with self._lock:
            self._value = float(value)

    @property
    def value(self):
        return self._value

    # histogram -----------------------------------------------------------
    def observe(self, value):
        idx = bisect.bisect_left(self._bounds, value)
        with self._lock:
            self._counts[idx] += 1
            self._sum += value

    @property
    def sum(self):
        return self._sum

    @property
    def count(self):
        return sum(self._counts)

    def bucket_counts(self):
        """[(upper_bound, cumulative_count), ...] ending with +Inf."""
        with self._lock:
            counts = list(self._counts)
        out, cum = [], 0
        for bound, c in zip(self._bounds + [float("inf")], counts):
            cum += c
            out.append((bound, cum))
        return out


class MetricFamily:
    """A named metric with a fixed label schema; children are the
    labeled series. Label-less families proxy updates to their single
    ``()`` child so ``counter("x").inc()`` just works."""

    kind = None

    def __init__(self, name, help="", labelnames=()):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._children = {}
        self._lock = sanitizer.make_lock("telemetry.family")

    def _new_child(self):
        return _Child()

    def labels(self, **labels):
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"metric {self.name} expects labels {self.labelnames}, "
                f"got {tuple(labels)}")
        key = tuple(str(labels[n]) for n in self.labelnames)
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.setdefault(key, self._new_child())
        return child

    # label-less convenience proxies
    def inc(self, amount=1):
        self.labels().inc(amount)

    def dec(self, amount=1):
        self.labels().dec(amount)

    def set(self, value):
        self.labels().set(value)

    def observe(self, value):
        self.labels().observe(value)

    @property
    def value(self):
        return self.labels().value

    def samples(self):
        with self._lock:
            items = sorted(self._children.items())
        out = []
        for key, child in items:
            labels = dict(zip(self.labelnames, key))
            if self.kind == "histogram":
                out.append({"labels": labels, "sum": child.sum,
                            "count": child.count,
                            "buckets": child.bucket_counts()})
            else:
                out.append({"labels": labels, "value": child.value})
        return out


class Counter(MetricFamily):
    kind = "counter"


class Gauge(MetricFamily):
    kind = "gauge"


class Histogram(MetricFamily):
    kind = "histogram"

    def __init__(self, name, help="", labelnames=(), buckets=None):
        super().__init__(name, help, labelnames)
        self.buckets = list(buckets if buckets is not None
                            else SECONDS_BUCKETS)

    def _new_child(self):
        return _Child(bounds=self.buckets)


class Registry:
    """Name -> family table. Factories are get-or-create so the same
    metric defined from two modules (or across elastic re-inits) shares
    one series instead of raising."""

    def __init__(self):
        self._families = {}
        self._lock = sanitizer.make_lock("telemetry.registry")

    def _get_or_create(self, cls, name, help, labelnames, **kwargs):
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = cls(name, help, labelnames, **kwargs)
                self._families[name] = fam
                return fam
        if not isinstance(fam, cls) or fam.labelnames != tuple(labelnames):
            raise ValueError(
                f"metric {name} re-registered with a different "
                f"type/label schema")
        return fam

    def counter(self, name, help="", labelnames=()):
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name, help="", labelnames=()):
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name, help="", labelnames=(), buckets=None):
        return self._get_or_create(Histogram, name, help, labelnames,
                                   buckets=buckets)

    def families(self):
        with self._lock:
            return dict(self._families)

    def snapshot(self):
        """JSON-able view of every family (exposition.py renders it)."""
        fams = {}
        for name in sorted(self.families()):
            fam = self._families[name]
            fams[name] = {"type": fam.kind, "help": fam.help,
                          "labelnames": list(fam.labelnames),
                          "samples": fam.samples()}
        return {"ts": time.time(), "families": fams}


_REGISTRY = Registry()
_ENABLED = None  # tri-state: None = not yet resolved


def enabled():
    """True when HOROVOD_TPU_METRICS is on. Resolved once; the cached
    answer keeps disabled call sites at one global read + compare."""
    global _ENABLED
    if _ENABLED is None:
        _ENABLED = envparse.get_bool(envparse.METRICS)
    return _ENABLED


def reset():
    """Drop every recorded series and re-resolve enablement from the
    environment (test hook; also used by elastic full restarts)."""
    global _REGISTRY, _ENABLED
    _REGISTRY = Registry()
    _ENABLED = None


def registry():
    return _REGISTRY


def counter(name, help="", labelnames=()):
    if not enabled():
        return NULL
    return _REGISTRY.counter(name, help, labelnames)


def gauge(name, help="", labelnames=()):
    if not enabled():
        return NULL
    return _REGISTRY.gauge(name, help, labelnames)


def histogram(name, help="", labelnames=(), buckets=None):
    if not enabled():
        return NULL
    return _REGISTRY.histogram(name, help, labelnames, buckets=buckets)


def snapshot():
    if not enabled():
        return {"ts": time.time(), "families": {}}
    return _REGISTRY.snapshot()


def payload_nbytes(x):
    """Total bytes of an array or nested list of arrays (duck-typed on
    ``.shape``/``.dtype``; non-arrays count 0) — shared by the backends'
    per-collective byte counters."""
    if isinstance(x, (list, tuple)):
        return sum(payload_nbytes(a) for a in x)
    try:
        return math.prod(x.shape) * x.dtype.itemsize
    except (AttributeError, TypeError):
        return 0
