"""Error-feedback residual store.

Plain quantized SGD is biased: gradient components smaller than the
per-block quantization step round to zero on every step and their
contribution is lost forever. Error feedback closes the loop — the
quantization error of step t is carried into step t+1's input
(``compress(g + e)``; e' = (g + e) - decompress(compress(g + e))), which
restores convergence to the uncompressed limit for SGD-family updates
(the satellite convergence test pins exactly this).

Residuals are keyed by **tensor name × elastic version**: a name is the
only identity stable across steps on the eager plane, and a membership
change invalidates every residual — the new cohort's virtual-rank slices
do not line up with the old one's, so a stale residual would inject one
cohort's quantization debt into another's gradients. The store checks
the joined elastic version on every access and drops everything when it
moves (exit-restart workers get a fresh process — and a fresh store —
anyway; the in-process reset path gets the same guarantee from this
check, plus a second line of defense: each ``basics.init()`` builds a
new coordinator and with it a new plane).

Residuals live in float32 regardless of the gradient dtype (a bf16
residual would itself round away the small components it exists to
preserve) and cost one extra copy of each compressed tensor — the
documented memory price of ``HVDTPU_COMPRESSION_ERROR_FEEDBACK=1``
(docs/compression.md).
"""

from ..analysis import sanitizer
from ..utils import envparse
from ..utils.logging_util import get_logger


class ResidualStore:
    """name -> list of per-array residuals (stacked like the entry's
    arrays). Touched only on the compressed dispatch path, so the lock
    is uncontended; it exists for the elastic-reset race (a framework
    thread reading while the cycle thread writes)."""

    def __init__(self):
        self._lock = sanitizer.make_lock("compression.residuals")
        self._store = {}
        self._version = self._current_version()
        self._log = get_logger()

    @staticmethod
    def _current_version():
        return envparse.get_str(envparse.ELASTIC_VERSION, "0")

    def _maybe_reset_locked(self):
        version = self._current_version()
        if version != self._version:
            dropped = len(self._store)
            self._store.clear()
            self._log.warning(
                "compression: residual store reset (elastic version "
                "%s -> %s, %d residual(s) dropped) — error-feedback "
                "state never crosses cohorts", self._version, version,
                dropped)
            self._version = version

    def get(self, name):
        """Residual list for ``name`` or None (first occurrence)."""
        with self._lock:
            self._maybe_reset_locked()
            return self._store.get(name)

    def put(self, name, residuals):
        with self._lock:
            self._maybe_reset_locked()
            self._store[name] = list(residuals)

    def reset(self):
        with self._lock:
            self._store.clear()

    def __len__(self):
        with self._lock:
            return len(self._store)
