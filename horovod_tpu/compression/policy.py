"""Per-tensor compression policy.

``HVDTPU_COMPRESSION`` selects the codec; the grammar is either a bare
codec name (applies to every eligible tensor)::

    HVDTPU_COMPRESSION=int8

or a semicolon-separated, first-match-wins list of ``glob=codec`` rules
over tensor names, with a bare codec acting as the ``*`` catch-all::

    HVDTPU_COMPRESSION='*bias*=none;embed*=bf16;int8'

Eligibility (checked before the rules): the entry is an allreduce of a
floating tensor with at least ``HVDTPU_COMPRESSION_THRESHOLD`` elements
(default 1024 — tiny tensors pay more in scale metadata and dispatch
overhead than their bytes are worth) under a Sum or Average reduction.
Min/Max/Product reductions are not gradient math and are silently left
uncompressed.

Two interactions are rejected LOUDLY instead of silently skipped
(ISSUE 6 contract — a user who turned compression on must never get
different numerics than they asked for without an explanation):

- **Adasum**: the scale-invariant combination is computed from exact
  dot products of the un-reduced per-rank gradients; quantizing its
  inputs silently changes the projection. ``ValueError`` tells the
  user to exclude the tensors (``<glob>=none``) or drop Adasum.
- **Non-global process sets**: the quantized pipeline is only wired
  (and only tested) over the global cohort; a subset mesh would need
  its own residual scoping. ``ValueError`` until that exists.

Malformed specs raise at plane construction (``hvd.init()`` time) —
the chaos-spec contract: a typo'd knob must never silently disable the
feature it configures.
"""

import fnmatch

from . import codecs
from ..ops import reduce_ops
from ..utils import envparse

DEFAULT_THRESHOLD = 1024


def parse_rules(spec):
    """``spec`` -> [(glob, codec_name)]; validates codec names (and the
    fp8 build requirement) eagerly."""
    rules = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        if "=" in part:
            glob, _, codec_name = part.partition("=")
            glob, codec_name = glob.strip(), codec_name.strip()
            if not glob or not codec_name:
                raise ValueError(
                    f"malformed HVDTPU_COMPRESSION rule {part!r}: "
                    "expected '<name-glob>=<codec>'")
        else:
            glob, codec_name = "*", part
        codecs.get_codec(codec_name)  # loud on unknown/unsupported
        rules.append((glob, codec_name))
    return rules


class CompressionPolicy:
    """Evaluates the rule list for one TensorEntry's metadata."""

    def __init__(self, rules, threshold=DEFAULT_THRESHOLD):
        self.rules = list(rules)
        self.threshold = int(threshold)

    @classmethod
    def from_env(cls):
        spec = envparse.get_str(envparse.COMPRESSION, "")
        rules = parse_rules(spec)
        threshold = envparse.get_int(envparse.COMPRESSION_THRESHOLD,
                                     DEFAULT_THRESHOLD)
        return cls(rules, threshold=threshold)

    def codec_for_name(self, name):
        """First matching rule's codec name, or None."""
        for glob, codec_name in self.rules:
            if fnmatch.fnmatchcase(name or "", glob):
                return codec_name
        return None

    def select(self, name, nelems, dtype, op, process_set_id):
        """Codec name for an allreduce with this metadata, or None.
        Raises on the Adasum / process-set interactions (module doc)."""
        if not self.rules:
            return None
        import jax.numpy as jnp
        if dtype is None or not jnp.issubdtype(dtype, jnp.floating):
            return None
        if nelems < self.threshold:
            return None
        codec_name = self.codec_for_name(name)
        if codec_name is None or codec_name == "none":
            return None
        if not codecs.CODECS[codec_name].lossy:
            return None
        if op == reduce_ops.Adasum:
            raise ValueError(
                f"HVDTPU_COMPRESSION selected codec {codec_name!r} for "
                f"Adasum allreduce {name!r}: Adasum's scale-invariant "
                "combination needs exact per-rank gradients, and "
                "quantizing them would silently change the result. "
                "Exclude these tensors ('<glob>=none' rule) or use "
                "Sum/Average (docs/compression.md).")
        if op not in (reduce_ops.Sum, reduce_ops.Average):
            return None  # Min/Max/Product: not gradient reductions
        if process_set_id not in (0, None):
            raise ValueError(
                f"HVDTPU_COMPRESSION selected codec {codec_name!r} for "
                f"allreduce {name!r} on process set {process_set_id}: "
                "quantized collectives are only wired for the global "
                "process set (residual scoping for subset cohorts does "
                "not exist). Exclude these tensors with a "
                "'<glob>=none' rule (docs/compression.md).")
        return codec_name


def simple_wire_policy():
    """(codec_name, block, threshold) for planes that have sizes and
    dtypes but no tensor names (the xla-global delegated data plane —
    fused native responses carry handles, not names). Only a catch-all
    ``*`` wire rule applies there; named globs need names and stay on
    the python fusion plane. Returns (None, block, threshold) when
    compression is off or cast-only."""
    spec = envparse.get_str(envparse.COMPRESSION, "")
    block = envparse.get_int(envparse.COMPRESSION_BLOCK,
                             codecs.DEFAULT_BLOCK)
    threshold = envparse.get_int(envparse.COMPRESSION_THRESHOLD,
                                 DEFAULT_THRESHOLD)
    for glob, codec_name in parse_rules(spec):
        if glob == "*":
            if codecs.CODECS[codec_name].wire:
                return codec_name, block, threshold
            return None, block, threshold
    return None, block, threshold
