"""Gradient compression plane (ISSUE 6; ROADMAP item 1 — EQuARX).

First-class subsystem behind the ``HVDTPU_COMPRESSION`` knob family:

- :mod:`codecs` — block-wise int8/fp8 quantization (per-block scales)
  and the none/fp16/bf16 casts behind one jit-traceable ``Codec``
  interface, plus the in-jit :func:`codecs.quantized_allreduce_axis`.
- :mod:`residual` — the error-feedback store (tensor name × elastic
  version; reset whenever the joined version moves).
- :mod:`policy` — per-tensor selection: size threshold, dtype, name
  globs; loud Adasum / process-set rejects.
- :class:`CompressionPlane` — what the coordinator holds: stamps
  entries at submit time (so the guardian digest and the fusion
  grouping both see the selected codec), hands residuals to the
  backend's quantized pipeline, and feeds the telemetry metrics
  (``hvd_compression_ratio`` / ``_bytes_saved_total`` / ``_error``).

Disabled contract (the telemetry/chaos/guardian standard): with
``HVDTPU_COMPRESSION`` unset, :func:`make_plane` returns ``None`` — the
coordinator's submit path pays one attribute check and allocates
nothing, no residual state exists, and no extra collectives run
(guard-tested in tests/test_compression.py).
"""

import numpy as np

from . import codecs, policy, residual  # noqa: F401  (subsystem surface)
from ..telemetry import core as telemetry
from ..utils import envparse
from ..utils.logging_util import get_logger

# Quantization-error histogram range: gradients live well under 1.0 and
# errors bottom out around f32 epsilon of the block scale.
_ERROR_BUCKETS = telemetry.log_buckets(1e-9, 1.0, factor=4.0)


class CompressionPlane:
    """Policy + residual store + metrics, attached to one coordinator
    (rebuilt on every ``init()``, like the guardian)."""

    def __init__(self, pol, delegated=False):
        self.policy = pol
        self.block = envparse.get_int(envparse.COMPRESSION_BLOCK,
                                      codecs.DEFAULT_BLOCK)
        if self.block <= 0:
            raise ValueError(
                f"HVDTPU_COMPRESSION_BLOCK must be positive, got "
                f"{self.block}")
        self.error_feedback = envparse.get_bool(
            envparse.COMPRESSION_ERROR_FEEDBACK, True)
        self.residuals = residual.ResidualStore()
        self._delegated = delegated
        self._warned_native = False
        self._warned_fallback = False
        self._log = get_logger()
        self._metrics_on = telemetry.enabled()
        # hvd_compression_error forces a device→host sync of every
        # residual it reads, on the cycle thread — sample 1-in-16
        # buckets (first bucket included) so the histogram stays
        # populated without making metrics a per-step transfer of the
        # whole gradient set.
        self._err_buckets = 0
        self._m_ratio = telemetry.gauge(
            "hvd_compression_ratio",
            "Wire bytes / original payload bytes of the last "
            "compressed bucket", labelnames=("codec",))
        self._m_saved = telemetry.counter(
            "hvd_compression_bytes_saved_total",
            "Payload bytes kept off the wire by compression",
            labelnames=("codec",))
        self._m_err = telemetry.histogram(
            "hvd_compression_error",
            "Per-tensor max-abs quantization error (the error-feedback "
            "residual's magnitude)", labelnames=("codec",),
            buckets=_ERROR_BUCKETS)

    # -- submit side -------------------------------------------------------
    def stamp(self, entry):
        """Resolve ``entry.codec`` from the explicit request (a codec
        name string set by ``Compression.int8``-style markers) or the
        env policy, into the ``(name, block)`` tuple the fusion plane
        groups by and the guardian digests. Raises the loud Adasum /
        process-set rejects; called from Coordinator.submit so the
        error surfaces on the submitting thread."""
        explicit = entry.codec
        entry.codec = None
        if self._delegated:
            # The delegated xla-global data plane executes fused NATIVE
            # responses (handles, not names) and applies the env
            # policy's catch-all at execution time instead
            # (policy.simple_wire_policy) — per-entry stamping has
            # nothing to attach to. The pure-TCP plane stamps normally:
            # its backend runs the host-side quantized-allgather path.
            if explicit is not None and not self._warned_native:
                self._warned_native = True
                self._log.warning(
                    "compression: per-tensor codec requests are ignored "
                    "on the delegated xla-global plane — it applies the "
                    "HVDTPU_COMPRESSION catch-all at the data plane "
                    "instead (no error feedback, no name globs; "
                    "docs/compression.md)")
            return
        nelems = sum(int(np.prod(getattr(a, "shape", ()) or (1,)))
                     for a in entry.arrays)
        dtype = (entry.arrays[0].dtype
                 if entry.arrays and hasattr(entry.arrays[0], "dtype")
                 else None)
        if explicit is not None:
            codec = codecs.get_codec(explicit)
            if not codec.wire:
                # Cast compressors run at the user layer (compress /
                # decompress around the collective) — nothing to stamp.
                return
            self._validate_wire(explicit, entry)
            entry.codec = (explicit, self.block)
            return
        name = self.policy.select(
            entry.name, nelems, dtype, entry.op,
            entry.process_set.process_set_id)
        if name is None:
            return
        codec = codecs.CODECS[name]
        entry.codec = (name, self.block if codec.wire else 0)

    def _validate_wire(self, codec_name, entry):
        from ..ops import reduce_ops
        if entry.op not in (None, reduce_ops.Sum, reduce_ops.Average,
                            reduce_ops.Adasum):
            raise ValueError(
                f"compression={codec_name!r} with "
                f"op={reduce_ops.op_name(entry.op)}: quantized "
                "collectives support Sum/Average only — dequantize-"
                "then-accumulate is a linear-reduction identity "
                "(docs/compression.md)")
        if entry.op == reduce_ops.Adasum:
            raise ValueError(
                f"compression={codec_name!r} with op=Adasum: Adasum "
                "needs exact per-rank gradients (quantizing them "
                "silently changes the scale-invariant combination). "
                "Drop the compressor or use Sum/Average "
                "(docs/compression.md).")
        if entry.process_set.process_set_id != 0:
            raise ValueError(
                f"compression={codec_name!r} on process set "
                f"{entry.process_set.process_set_id}: quantized "
                "collectives are only wired for the global process set "
                "(docs/compression.md).")

    # -- dispatch side (coordinator cycle thread) --------------------------
    def residuals_in(self, bucket):
        """Flat residual list aligned with the bucket's flat array list
        (zeros where none is stored or the shape moved), or None when
        error feedback is off."""
        if not self.error_feedback:
            return None
        import jax.numpy as jnp
        out = []
        for e in bucket:
            stored = self.residuals.get(e.name) if e.name else None
            if (stored is None or len(stored) != len(e.arrays)
                    or any(r.shape != a.shape
                           for r, a in zip(stored, e.arrays))):
                stored = [jnp.zeros(a.shape, jnp.float32)
                          for a in e.arrays]
            out.extend(stored)
        return out

    def store_residuals(self, bucket, flat_residuals):
        i = 0
        for e in bucket:
            k = len(e.arrays)
            if e.name:
                self.residuals.put(e.name, flat_residuals[i:i + k])
            i += k

    def warn_fallback(self, backend_name):
        if not self._warned_fallback:
            self._warned_fallback = True
            self._log.warning(
                "compression: backend %r has no quantized-collective "
                "pipeline; compressed buckets fall back to the plain "
                "allreduce (lossless, but no bandwidth win)",
                backend_name)

    def record(self, codec_name, bucket, flat_arrays, flat_residuals):
        """Telemetry for one executed bucket: ratio gauge, bytes-saved
        counter, and (when residuals exist) the per-tensor max-abs
        quantization error histogram. No-op with metrics off."""
        if not self._metrics_on:
            return
        codec = codecs.CODECS[codec_name]
        orig = wire = 0
        for a in flat_arrays:
            n = int(np.prod(a.shape))
            orig += n * a.dtype.itemsize
            wire += codec.wire_bytes(n, self.block, a.dtype.itemsize)
        if orig:
            self._m_ratio.labels(codec=codec_name).set(wire / orig)
            self._m_saved.labels(codec=codec_name).inc(max(0, orig - wire))
        if flat_residuals is not None:
            self._err_buckets += 1
            if (self._err_buckets - 1) % 16:
                return
            i = 0
            for e in bucket:
                k = len(e.arrays)
                err = max(float(np.max(np.abs(np.asarray(r))))
                          for r in flat_residuals[i:i + k])
                self._m_err.labels(codec=codec_name).observe(err)
                i += k


def make_plane(runtime=None, force=False):
    """CompressionPlane when ``HVDTPU_COMPRESSION`` is set (or
    ``force``, for explicit per-call codec markers with the env unset);
    None otherwise — the disabled-mode contract."""
    spec = envparse.get_str(envparse.COMPRESSION, "")
    if not spec and not force:
        return None
    delegated = bool(runtime is not None
                     and getattr(getattr(runtime, "backend", None),
                                 "delegate_data_ops", False))
    return CompressionPlane(policy.CompressionPolicy.from_env(),
                            delegated=delegated)
