"""Codec layer: one interface over wire quantization and dtype casts.

The EQuARX result (PAPERS.md: *Efficient Quantized AllReduce in XLA*,
arXiv:2506.17615) is that block-wise quantization pays for itself when
it is fused INTO the collective: quantize → reduce-scatter the narrow
blocks → dequantize-accumulate in a wide dtype → requantize → allgather
→ final dequantize. Accumulation never happens in the narrow dtype, so
the error stays bounded by the per-block quantization step instead of
growing with the cohort size.

Two codec families behind one :class:`Codec` interface:

- **Block codecs** (``int8``, ``fp8``): ``encode`` splits the last axis
  into fixed-size blocks and emits a narrow-dtype payload plus one f32
  scale per block (scale = blockwise max-abs / qmax). These are *wire*
  codecs: the collective itself must run the quantized pipeline
  (summing raw int8 payloads would be garbage), so the dispatch layer
  routes them to ``allreduce_quantized`` instead of wrapping a plain
  allreduce.
- **Cast codecs** (``none``, ``fp16``, ``bf16``): ``encode`` is an
  astype, scales are None, and a plain allreduce carries the narrow
  payload (the reference's ``horovod/tensorflow/compression.py``
  semantics).

Everything here is jit-traceable (shapes static under trace): the
backends call these helpers from inside compiled shard_map bodies, and
:func:`quantized_allreduce_axis` is the in-jit spelling for user train
steps (DistributedOptimizer's axis path).
"""

import jax.numpy as jnp
from jax import lax

DEFAULT_BLOCK = 256

_INT8_QMAX = 127.0
_FP8_DTYPE = getattr(jnp, "float8_e4m3fn", None)


def fp8_supported():
    """True when this jax build ships float8_e4m3fn (the fp8 codec is
    registered either way; selecting it without support is a loud
    error at dispatch, not a silent fp32 fallback)."""
    return _FP8_DTYPE is not None


class Codec:
    """One compression scheme for collective payloads.

    ``wire=True`` marks block codecs whose payload cannot ride a plain
    reduction (the collective must dequantize before accumulating);
    ``wire=False`` marks casts a plain allreduce can carry directly.
    """

    name = "abstract"
    wire = False
    lossy = False

    def encode(self, x, block):
        """(payload, scales) — scales is None for cast codecs."""
        raise NotImplementedError

    def decode(self, payload, scales, block, dtype=jnp.float32):
        raise NotImplementedError

    def wire_bytes(self, nelems, block, orig_itemsize):
        """Payload + scale bytes this codec puts on the wire for
        ``nelems`` values of an ``orig_itemsize``-wide input."""
        raise NotImplementedError


def _block_view(x, block):
    """Reshape the last axis into (nblocks, block); the caller pads to a
    multiple of ``block`` first (dispatch does)."""
    if x.shape[-1] % block:
        raise ValueError(
            f"codec input last axis {x.shape[-1]} is not a multiple of "
            f"block size {block} (the dispatch layer pads first)")
    return x.reshape(x.shape[:-1] + (x.shape[-1] // block, block))


class _BlockCodec(Codec):
    """Shared block-wise scheme: per-block scale = max-abs / qmax."""

    wire = True
    lossy = True
    qmax = None          # largest representable magnitude of the payload
    payload_np = None    # numpy-spellable wire dtype of the payload
    payload_itemsize = 1

    def _to_payload(self, v):
        raise NotImplementedError

    def _from_payload(self, q):
        raise NotImplementedError

    def encode(self, x, block):
        xb = _block_view(x.astype(jnp.float32), block)
        maxabs = jnp.max(jnp.abs(xb), axis=-1, keepdims=True)
        scale = maxabs / self.qmax
        # All-zero blocks: scale 0 would divide to nan; payload is all
        # zeros either way, so any nonzero divisor is correct.
        safe = jnp.where(scale > 0.0, scale, 1.0)
        q = self._to_payload(xb / safe)
        return (q.reshape(x.shape),
                jnp.squeeze(scale, axis=-1).astype(jnp.float32))

    def decode(self, payload, scales, block, dtype=jnp.float32):
        qb = self._from_payload(_block_view(payload, block))
        return (qb * scales[..., None].astype(jnp.float32)).reshape(
            payload.shape).astype(dtype)

    def wire_bytes(self, nelems, block, orig_itemsize):
        nblocks = -(-nelems // block)
        return nelems * self.payload_itemsize + nblocks * 4


class Int8BlockCodec(_BlockCodec):
    """Symmetric per-block int8: q = round(x * 127 / max|block|).
    Round-trip error is bounded by scale/2 = max|block| / 254."""

    name = "int8"
    qmax = _INT8_QMAX
    payload_np = "int8"

    def _to_payload(self, v):
        return jnp.clip(jnp.round(v), -_INT8_QMAX, _INT8_QMAX).astype(
            jnp.int8)

    def _from_payload(self, q):
        return q.astype(jnp.float32)


class FP8BlockCodec(_BlockCodec):
    """Per-block-scaled float8_e4m3fn: the block max maps to the fp8
    max-finite (448), keeping 3 mantissa bits of relative precision
    across the block's dynamic range. Payloads ride collectives as
    bitcast uint8 (not every backend reduces/permutes fp8 natively)."""

    name = "fp8"
    qmax = 448.0
    payload_np = "uint8"  # fp8 bits ride collectives bitcast to uint8

    def _to_payload(self, v):
        if _FP8_DTYPE is None:
            raise NotImplementedError(
                "the fp8 codec needs a jax build with "
                "jnp.float8_e4m3fn; use HVDTPU_COMPRESSION=int8")
        return lax.bitcast_convert_type(v.astype(_FP8_DTYPE), jnp.uint8)

    def _from_payload(self, q):
        if _FP8_DTYPE is None:
            raise NotImplementedError(
                "the fp8 codec needs a jax build with "
                "jnp.float8_e4m3fn; use HVDTPU_COMPRESSION=int8")
        return lax.bitcast_convert_type(q, _FP8_DTYPE).astype(jnp.float32)


class _CastCodec(Codec):
    """astype-on-the-wire codecs (reference compression semantics): a
    plain allreduce carries the narrow payload, accumulation happens in
    the narrow dtype — cheap, and fine for fp16/bf16."""

    lossy = True
    cast_dtype = None
    cast_itemsize = 2

    def encode(self, x, block):
        del block
        return x.astype(self.cast_dtype), None

    def decode(self, payload, scales, block, dtype=jnp.float32):
        del scales, block
        return payload.astype(dtype)

    def wire_bytes(self, nelems, block, orig_itemsize):
        del block
        return nelems * self.cast_itemsize


class FP16CastCodec(_CastCodec):
    name = "fp16"
    cast_dtype = jnp.float16


class BF16CastCodec(_CastCodec):
    name = "bf16"
    cast_dtype = jnp.bfloat16


class NoneCodec(Codec):
    name = "none"

    def encode(self, x, block):
        del block
        return x, None

    def decode(self, payload, scales, block, dtype=jnp.float32):
        del scales, block
        return payload.astype(dtype)

    def wire_bytes(self, nelems, block, orig_itemsize):
        del block
        return nelems * orig_itemsize


CODECS = {c.name: c for c in (NoneCodec(), FP16CastCodec(),
                              BF16CastCodec(), Int8BlockCodec(),
                              FP8BlockCodec())}


def get_codec(name):
    codec = CODECS.get(name)
    if codec is None:
        raise ValueError(
            f"unknown compression codec {name!r}; available: "
            f"{', '.join(sorted(CODECS))}")
    if name == "fp8" and not fp8_supported():
        raise ValueError(
            "codec 'fp8' selected but this jax build has no "
            "jnp.float8_e4m3fn; use 'int8' (or upgrade jax)")
    return codec


def padded_len(nelems, nranks, block):
    """Smallest length >= nelems divisible by nranks * block (every rank
    owns an equal whole number of blocks after the reduce-scatter)."""
    unit = nranks * block
    return -(-nelems // unit) * unit


def quantized_allreduce_axis(x, axis_name, codec="int8",
                             block=DEFAULT_BLOCK, average=True):
    """In-jit EQuARX allreduce over a shard_map axis.

    ``x`` is this replica's (un-reduced) array; returns the cross-replica
    sum (or mean) with both collective legs carried in the codec's wire
    format: quantize → all_to_all (the reduce-scatter leg) → dequantized
    f32 accumulation → requantize → all_gather → dequantize. Stateless —
    error feedback lives on the eager dispatch plane (ResidualStore),
    not inside jit (docs/compression.md, "Convergence caveats").
    """
    c = get_codec(codec) if isinstance(codec, str) else codec
    if not c.wire:
        raise ValueError(
            f"quantized_allreduce_axis needs a wire codec, got {c.name!r}")
    from ..utils.jax_compat import axis_size
    n = axis_size(axis_name)
    orig_shape = x.shape
    orig_dtype = x.dtype
    flat = x.reshape(-1).astype(jnp.float32)
    nelems = flat.shape[0]
    padded = padded_len(nelems, n, block)
    if padded != nelems:
        flat = jnp.pad(flat, (0, padded - nelems))
    rows = flat.reshape(n, padded // n)
    q, s = c.encode(rows, block)
    # Reduce-scatter leg: rank r keeps every rank's quantized copy of
    # chunk r, accumulates in f32.
    q = lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0,
                       tiled=True)
    s = lax.all_to_all(s, axis_name, split_axis=0, concat_axis=0,
                       tiled=True)
    red = jnp.sum(c.decode(q, s, block), axis=0)
    if average:
        red = red / n
    # Allgather leg: requantized shard back out to every rank.
    q2, s2 = c.encode(red, block)
    qg = lax.all_gather(q2, axis_name, tiled=True)
    sg = lax.all_gather(s2, axis_name, tiled=True)
    out = c.decode(qg, sg, block)
    if padded != nelems:
        out = out[:nelems]
    return out.reshape(orig_shape).astype(orig_dtype)
