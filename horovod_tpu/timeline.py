"""Chrome-tracing timeline (reference: horovod/common/timeline.cc/.h).

The reference feeds a lock-free SPSC queue drained by a writer thread
(reference: timeline.h:48-100); events move through NEGOTIATING → TOP_LEVEL →
ACTIVITY states. Here the coordinator emits begin/end activity events into a
thread-safe queue and a writer thread streams Chrome ``trace_event`` JSON.
Runtime start/stop mirrors hvd.start_timeline/stop_timeline
(reference: horovod/common/basics.py:156, operations.cc:1032-1064).
"""

import json
import queue
import threading
import time


class Timeline:
    def __init__(self, path, jax_profiler_dir=None, mark_cycles=False):
        self.path = path
        # When set, the coordinator drops an instant event per negotiation
        # cycle (reference: --timeline-mark-cycles / MarkCycle events).
        self.mark_cycles = bool(mark_cycles)
        self._queue = queue.Queue()
        self._thread = None
        self._running = False
        self._file = None
        self._first = True
        self._pids = {}
        # Optional device-side story: a jax.profiler trace alongside the
        # host timeline (the SURVEY-stated TPU equivalent of NVTX ranges,
        # reference: nvtx_op_range.cc — on TPU the profiler's TraceMe/xplane
        # capture is the per-op device view).
        self._jax_profiler_dir = jax_profiler_dir
        self._jax_profiling = False

    # -- producer side (coordinator) --------------------------------------
    def begin(self, names, activity):
        if self._running:
            self._queue.put(("B", tuple(names), activity,
                             time.perf_counter_ns() // 1000))

    def end(self, names, activity):
        if self._running:
            self._queue.put(("E", tuple(names), activity,
                             time.perf_counter_ns() // 1000))

    def marker(self, name, ts_us=None):
        """Instant event; ``ts_us`` lets a caller stamp a time captured
        earlier (the native cycle marker records the cycle's START but is
        emitted after the cycle ran, once it knows work happened)."""
        if self._running:
            self._queue.put(("I", (name,), name,
                             ts_us if ts_us is not None
                             else time.perf_counter_ns() // 1000))

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        if self._running:
            return
        self._file = open(self.path, "w")
        self._file.write("[\n")
        self._first = True
        self._running = True
        self._thread = threading.Thread(target=self._writer,
                                        name="hvd-tpu-timeline", daemon=True)
        self._thread.start()
        if self._jax_profiler_dir:
            try:
                import jax
                jax.profiler.start_trace(self._jax_profiler_dir)
                self._jax_profiling = True
            except Exception:  # noqa: BLE001 — host timeline still works
                self._jax_profiling = False

    def stop(self):
        if not self._running:
            return
        self._running = False
        if self._jax_profiling:
            try:
                import jax
                jax.profiler.stop_trace()
            except Exception:  # noqa: BLE001
                pass
            self._jax_profiling = False
        self._queue.put(None)
        self._thread.join(timeout=5)
        try:
            self._file.write("\n]\n")
            self._file.close()
        except (OSError, ValueError):
            pass

    # -- writer thread -----------------------------------------------------
    def _emit(self, event):
        if not self._first:
            self._file.write(",\n")
        self._first = False
        self._file.write(json.dumps(event))

    def _writer(self):
        while True:
            item = self._queue.get()
            if item is None:
                break
            phase, names, activity, ts_us = item
            for name in names:
                tid = self._pids.setdefault(name, len(self._pids) + 1)
                if phase == "I":
                    self._emit({"name": activity, "ph": "i", "ts": ts_us,
                                "pid": 0, "tid": tid, "s": "g"})
                else:
                    self._emit({"name": activity, "cat": "hvd",
                                "ph": phase, "ts": ts_us, "pid": 0,
                                "tid": tid, "args": {"tensor": name}})
            self._file.flush()
