"""Chrome-tracing timeline (reference: horovod/common/timeline.cc/.h).

The reference feeds a lock-free SPSC queue drained by a writer thread
(reference: timeline.h:48-100); events move through NEGOTIATING → TOP_LEVEL →
ACTIVITY states. Here the coordinator emits begin/end activity events into a
thread-safe queue and a writer thread streams Chrome ``trace_event`` JSON.
Runtime start/stop mirrors hvd.start_timeline/stop_timeline
(reference: horovod/common/basics.py:156, operations.cc:1032-1064).
"""

import json
import os
import queue
import threading
import time

from .utils import envparse


class Timeline:
    def __init__(self, path, jax_profiler_dir=None, mark_cycles=False):
        self.path = path
        # Actual file of the CURRENT session (path, version-suffixed in
        # elastic runs — see _shard_path); set by start().
        self.shard_path = path
        # When set, the coordinator drops an instant event per negotiation
        # cycle (reference: --timeline-mark-cycles / MarkCycle events).
        self.mark_cycles = bool(mark_cycles)
        self._queue = queue.Queue()
        self._thread = None
        self._running = False
        self._file = None
        # Optional device-side story: a jax.profiler trace alongside the
        # host timeline (the SURVEY-stated TPU equivalent of NVTX ranges,
        # reference: nvtx_op_range.cc — on TPU the profiler's TraceMe/xplane
        # capture is the per-op device view).
        self._jax_profiler_dir = jax_profiler_dir
        self._jax_profiling = False

    # -- producer side (coordinator) --------------------------------------
    def begin(self, names, activity):
        if self._running:
            self._queue.put(("B", tuple(names), activity,
                             time.perf_counter_ns() // 1000))

    def end(self, names, activity):
        if self._running:
            self._queue.put(("E", tuple(names), activity,
                             time.perf_counter_ns() // 1000))

    def marker(self, name, ts_us=None):
        """Instant event; ``ts_us`` lets a caller stamp a time captured
        earlier (the native cycle marker records the cycle's START but is
        emitted after the cycle ran, once it knows work happened)."""
        if self._running:
            self._queue.put(("I", (name,), name,
                             ts_us if ts_us is not None
                             else time.perf_counter_ns() // 1000))

    def _shard_path(self):
        """Elastic runs restart the timeline after every reset with the
        SAME configured path (basics.init reads one env knob), which
        used to truncate the pre-reset trace. Suffix the shard with the
        membership version joined (``trace.json`` → ``trace.v3.json``)
        so each cohort's timeline survives; non-elastic runs keep the
        plain path."""
        ver = envparse.get_env(envparse.ELASTIC_VERSION)
        if ver is None:
            return self.path
        root, ext = os.path.splitext(self.path)
        return f"{root}.v{ver}{ext or '.json'}"

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        if self._running:
            return
        self.shard_path = self._shard_path()
        self._file = open(self.shard_path, "w")
        self._file.write("[\n")
        # Fresh queue per session, and the writer gets its file
        # explicitly: a start() after a stop() whose join timed out must
        # not let the OLD writer steal this session's events/sentinel or
        # race its close against the NEW file (the straggler finishes
        # draining its own queue into its own file and exits).
        self._queue = queue.Queue()
        self._running = True
        self._thread = threading.Thread(target=self._writer,
                                        args=(self._file, self._queue),
                                        name="hvd-tpu-timeline", daemon=True)
        self._thread.start()
        if self._jax_profiler_dir:
            try:
                import jax
                jax.profiler.start_trace(self._jax_profiler_dir)
                self._jax_profiling = True
            except Exception:  # noqa: BLE001 — host timeline still works
                self._jax_profiling = False

    def stop(self):
        if not self._running:
            return
        self._running = False
        if self._jax_profiling:
            try:
                import jax
                jax.profiler.stop_trace()
            except Exception:  # noqa: BLE001
                pass
            self._jax_profiling = False
        self._queue.put(None)
        # The WRITER owns closing the file: if this join times out the
        # thread is still draining, and closing here would race its
        # writes (ValueError on a closed file). It closes after the
        # sentinel whether or not we are still waiting.
        self._thread.join(timeout=5)

    # -- writer thread -----------------------------------------------------
    # ``first`` is a writer-local [bool] (is the next event the file's
    # first?) and ``pids`` a writer-local name->tid map — NOT instance
    # state: a straggler writer from a previous session draining its
    # own queue must not corrupt this session's JSON comma placement,
    # and two writers sharing one tid dict would race its inserts
    # (the HVD301-shaped handoff bug this file used to have).
    def _emit(self, file, event, first):
        if not first[0]:
            file.write(",\n")
        first[0] = False
        file.write(json.dumps(event))

    def _emit_item(self, file, item, first, pids):
        phase, names, activity, ts_us = item
        for name in names:
            tid = pids.setdefault(name, len(pids) + 1)
            if phase == "I":
                self._emit(file, {"name": activity, "ph": "i",
                                  "ts": ts_us, "pid": 0, "tid": tid,
                                  "s": "g"}, first)
            else:
                self._emit(file, {"name": activity, "cat": "hvd",
                                  "ph": phase, "ts": ts_us, "pid": 0,
                                  "tid": tid, "args": {"tensor": name}},
                           first)

    def _writer(self, file, q):
        """Drain-then-flush loop: one blocking get, then everything the
        producers queued meanwhile, then ONE flush for the whole drain —
        a busy cycle emitting hundreds of events pays one syscall, not
        one per event. Ends (and closes the file) at the stop sentinel.
        Everything mutable here (file, queue, first, pids) is owned by
        THIS writer: start() hands the new writer its own file+queue,
        so a timed-out predecessor can finish without sharing state."""
        first = [True]
        pids = {}
        try:
            stop = False
            while not stop:
                item = q.get()
                if item is None:
                    break
                self._emit_item(file, item, first, pids)
                while True:
                    try:
                        item = q.get_nowait()
                    except queue.Empty:
                        break
                    if item is None:
                        stop = True
                        break
                    self._emit_item(file, item, first, pids)
                file.flush()
        finally:
            try:
                file.write("\n]\n")
                file.close()
            except (OSError, ValueError):
                pass
