"""Ray integration: actor-based horovod_tpu jobs (reference:
horovod/ray/runner.py:168 ``RayExecutor``).

Thin by design: Ray provides placement (actors); rendezvous and topology
ride the shared cluster core (runner/cluster.py). Requires ray (not
bundled in TPU images — the adapter gates with a clear error).

    from horovod_tpu.ray import RayExecutor
    ex = RayExecutor(num_workers=4)
    ex.start()
    results = ex.run(train_fn, args=(lr,))
    ex.shutdown()
"""

from ..runner.cluster import ClusterJob, cluster_task_bootstrap


def _ray():
    try:
        import ray
        return ray
    except ImportError as e:
        raise ImportError(
            "horovod_tpu.ray requires ray, which is not installed in this "
            "environment (TPU images ship without Ray). `pip install ray` "
            "on a Ray cluster to use this integration.") from e


class RayExecutor:
    """Reference API shape: start() places workers, run() executes the
    training function on all of them, shutdown() tears down."""

    def __init__(self, num_workers=1, cpus_per_worker=1,
                 resources_per_worker=None, start_timeout=120,
                 extra_env=None):
        self.num_workers = num_workers
        self.cpus_per_worker = cpus_per_worker
        self.resources_per_worker = resources_per_worker or {}
        self.start_timeout = start_timeout
        self.extra_env = dict(extra_env or {})
        self._workers = None
        self._job = None

    def start(self):
        ray = _ray()

        @ray.remote
        class _Worker:
            def bootstrap(self, rank, task_args, extra_env):
                import os

                from horovod_tpu.utils import envparse
                os.environ.update(extra_env)
                n, addr, port, token, timeout = task_args
                cluster_task_bootstrap(rank, n, addr, port, token, timeout)
                return envparse.get_str(envparse.RANK)

            def execute(self, fn, args, kwargs):
                return fn(*args, **kwargs)

        self._job = ClusterJob(self.num_workers,
                               start_timeout=self.start_timeout)
        worker_cls = _Worker.options(num_cpus=self.cpus_per_worker,
                                     resources=self.resources_per_worker)
        self._workers = [worker_cls.remote()
                         for _ in range(self.num_workers)]
        ray.get([w.bootstrap.remote(i, self._job.task_args(),
                                    self.extra_env)
                 for i, w in enumerate(self._workers)])

    def run(self, fn, args=(), kwargs=None):
        """Execute fn on every worker; per-rank results ordered by rank."""
        ray = _ray()
        if self._workers is None:
            raise RuntimeError("call start() before run()")
        return ray.get([w.execute.remote(fn, args, kwargs or {})
                        for w in self._workers])

    def execute_single(self, fn, args=(), kwargs=None, rank=0):
        ray = _ray()
        if self._workers is None:
            raise RuntimeError("call start() before run()")
        return ray.get(self._workers[rank].execute.remote(
            fn, args, kwargs or {}))

    def shutdown(self):
        ray = _ray()
        if self._workers:
            for w in self._workers:
                ray.kill(w)
            self._workers = None
        if self._job is not None:
            self._job.shutdown()
            self._job = None


def __getattr__(name):
    # Lazy: the elastic executor and strategies import ray only on use.
    if name in ("ElasticRayExecutor", "RayHostDiscovery"):
        from . import elastic
        return getattr(elastic, name)
    if name in ("PlacementStrategy", "ColocatedStrategy", "SpreadStrategy",
                "strategy_for"):
        from . import strategy
        return getattr(strategy, name)
    raise AttributeError(name)


__all__ = ["RayExecutor", "ElasticRayExecutor", "RayHostDiscovery",
           "ClusterJob", "cluster_task_bootstrap", "ColocatedStrategy",
           "SpreadStrategy", "strategy_for"]
