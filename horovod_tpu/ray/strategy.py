"""Placement strategies for Ray workers (reference:
horovod/ray/strategy.py:139 ``ColocatedStrategy``/``PGStrategy``).

A strategy turns (num_workers, per-worker resources) into a Ray
placement-group request: the bundle list plus the Ray scheduling strategy
string. Bundle math is pure Python (tested without ray); only
``create_placement_group`` touches the ray API, through the adapter's
lazy import.

TPU note: on TPU-VM pods each host owns its chips, so colocation bundles
("pack") map one bundle per host with all that host's workers inside —
the layout that keeps the jax.distributed mesh's intra-host ICI traffic
off the data-center network.
"""


class PlacementStrategy:
    """Base: subclasses define the bundle layout."""

    def __init__(self, num_workers, cpus_per_worker=1, gpus_per_worker=0,
                 resources_per_worker=None):
        self.num_workers = num_workers
        self.cpus_per_worker = cpus_per_worker
        self.gpus_per_worker = gpus_per_worker
        self.resources_per_worker = dict(resources_per_worker or {})

    def _worker_resources(self):
        res = {"CPU": self.cpus_per_worker}
        if self.gpus_per_worker:
            res["GPU"] = self.gpus_per_worker
        res.update(self.resources_per_worker)
        return res

    def bundles(self):
        raise NotImplementedError

    def ray_strategy(self):
        raise NotImplementedError

    def bundle_index_for_worker(self, worker_index):
        """Which bundle a given worker rank is scheduled into."""
        raise NotImplementedError

    def create_placement_group(self, timeout=100):
        """Reserve the group; returns the ray PlacementGroup handle."""
        import ray
        pg = ray.util.placement_group(self.bundles(),
                                      strategy=self.ray_strategy())
        ray.get(pg.ready(), timeout=timeout)
        return pg


class ColocatedStrategy(PlacementStrategy):
    """One bundle per host holding that host's workers' combined
    resources; STRICT_PACK keeps each bundle on one node (reference:
    strategy.py ColocatedStrategy — equal-distribution layout).

    ``workers_by_host`` allows uneven layouts (e.g. 7 workers on 2
    hosts as 4+3); by default workers spread evenly."""

    def __init__(self, num_hosts, workers_per_host=None, cpus_per_worker=1,
                 gpus_per_worker=0, resources_per_worker=None,
                 workers_by_host=None):
        if workers_by_host is None:
            workers_by_host = [workers_per_host] * num_hosts
        super().__init__(sum(workers_by_host), cpus_per_worker,
                         gpus_per_worker, resources_per_worker)
        self.num_hosts = num_hosts
        self.workers_per_host = workers_per_host
        self.workers_by_host = list(workers_by_host)

    def bundles(self):
        per = self._worker_resources()
        return [{k: v * count for k, v in per.items()}
                for count in self.workers_by_host]

    def ray_strategy(self):
        return "STRICT_PACK" if self.num_hosts == 1 else "PACK"

    def bundle_index_for_worker(self, worker_index):
        for i, count in enumerate(self.workers_by_host):
            if worker_index < count:
                return i
            worker_index -= count
        raise IndexError("worker_index beyond num_workers")


class SpreadStrategy(PlacementStrategy):
    """One bundle per worker, SPREAD across the cluster — maximizes
    host-failure independence at the cost of cross-host traffic
    (reference: strategy.py PGStrategy/pack=False)."""

    def bundles(self):
        return [self._worker_resources()
                for _ in range(self.num_workers)]

    def ray_strategy(self):
        return "SPREAD"

    def bundle_index_for_worker(self, worker_index):
        return worker_index


def strategy_for(pack, num_workers, num_hosts=None, cpus_per_worker=1,
                 gpus_per_worker=0, resources_per_worker=None):
    """Reference-flag adapter: ``use_current_placement_group``/``pack``
    style booleans to a strategy object. Pack layouts split uneven
    worker counts as evenly as possible (ceil on the first remainder
    hosts) — elastic jobs have dynamic host counts, so divisibility
    must not be a startup requirement."""
    if pack:
        hosts = min(num_hosts or 1, num_workers)
        base, rem = divmod(num_workers, hosts)
        by_host = [base + (1 if i < rem else 0) for i in range(hosts)]
        return ColocatedStrategy(hosts, cpus_per_worker=cpus_per_worker,
                                 gpus_per_worker=gpus_per_worker,
                                 resources_per_worker=resources_per_worker,
                                 workers_by_host=by_host)
    return SpreadStrategy(num_workers, cpus_per_worker, gpus_per_worker,
                          resources_per_worker)


__all__ = ["PlacementStrategy", "ColocatedStrategy", "SpreadStrategy",
           "strategy_for"]
