"""Elastic Ray executor: actor-based fault-tolerant jobs (reference:
horovod/ray/elastic.py:149 ``ElasticRayExecutor`` + elastic_v2.py).

Design: the subprocess elastic driver (runner/elastic_driver.py) already
owns the hard parts — versioned re-rendezvous, stable rank order,
blacklist, quorum, straggler reaping — and was kill-tested in round 2.
This module reuses that exact state machine and swaps the two Ray-shaped
pieces in:

- **membership** comes from the Ray cluster (``RayHostDiscovery`` polls
  ``ray.nodes()`` instead of running a discovery script), and
- **workers** are Ray actors (``_ActorProcess`` adapts an actor + its
  running ObjectRef to the SlotProcess poll/wait/terminate/kill surface
  the driver manages).

A worker actor sets the elastic HVDTPU_* env (same contract as a spawned
process: worker id, rendezvous addr/port/token) and calls the user
function; inside it, ``horovod_tpu.elastic.run``-wrapped state works
unchanged. Per-worker results of the succeeding cohort come back from
``run()`` ordered by final rank.
"""

import time
from types import SimpleNamespace

from . import _ray
from .strategy import strategy_for
from ..runner.elastic_driver import ElasticDriver, ElasticSettings
from ..runner.hosts import HostInfo
from ..utils.logging_util import get_logger


class RayHostDiscovery:
    """Cluster membership from ray.nodes() (reference: elastic.py:44
    RayHostDiscovery): alive nodes with enough resources become
    ``host:slots`` entries; a dead/preempted node simply drops out, which
    is the signal the elastic driver reacts to."""

    def __init__(self, cpus_per_worker=1, gpus_per_worker=0,
                 use_gpu=False, max_np=None):
        self.cpus_per_worker = cpus_per_worker
        self.gpus_per_worker = gpus_per_worker or (1 if use_gpu else 0)
        self.max_np = max_np

    def find_available_hosts(self):
        # No max_np capping HERE: the elastic driver caps at max_np
        # AFTER blacklist filtering (_discover_targets) — a discovery-
        # side budget would let a blacklisted host starve healthy
        # replacements of slots. ``max_np`` is kept only as metadata.
        ray = _ray()
        hosts = []
        for node in ray.nodes():
            if not node.get("Alive"):
                continue
            res = node.get("Resources", {})
            slots = int(res.get("CPU", 0) // self.cpus_per_worker)
            if self.gpus_per_worker:
                slots = min(slots, int(res.get("GPU", 0)
                                       // self.gpus_per_worker))
            if slots <= 0:
                continue
            hosts.append(HostInfo(node["NodeManagerAddress"], slots))
        return hosts


def _make_worker_cls(ray):
    @ray.remote
    class ElasticWorker:
        """One rank: applies the elastic env contract, runs the user fn."""

        def run(self, fn, env, args, kwargs):
            import os
            os.environ.update(env)
            return fn(*(args or ()), **(kwargs or {}))

    return ElasticWorker


class _ActorProcess:
    """Adapt (actor, in-flight ObjectRef) to the SlotProcess surface
    ElasticDriver drives: poll() -> rc|None, wait(), terminate(), kill().
    Success/failure maps to rc 0/1; the result value is kept for
    ElasticRayExecutor.run()."""

    def __init__(self, actor, ref):
        self.actor = actor
        self.ref = ref
        self._rc = None
        self.result = None
        self.error = None

    def poll(self):
        if self._rc is not None:
            return self._rc
        ray = _ray()
        done, _ = ray.wait([self.ref], timeout=0)
        if not done:
            return None
        try:
            self.result = ray.get(self.ref)
            self._rc = 0
        except Exception as e:  # noqa: BLE001 — actor death/user error
            self.error = e
            self._rc = 1
        return self._rc

    def wait(self, timeout=None):
        deadline = None if timeout is None else time.monotonic() + timeout
        while self.poll() is None:
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError("actor still running")
            time.sleep(0.05)
        return self._rc

    def terminate(self):
        self.kill()

    def kill(self):
        if self._rc is None:
            try:
                _ray().kill(self.actor)
            except Exception:  # noqa: BLE001 — already dead
                pass


class _RayElasticDriver(ElasticDriver):
    """ElasticDriver whose workers are Ray actors."""

    def __init__(self, elastic, fn, fn_args, fn_kwargs, discovery,
                 worker_env, placement=None):
        super().__init__(elastic, command=None, discovery=discovery)
        self._fn = fn
        self._fn_args = fn_args
        self._fn_kwargs = fn_kwargs
        self._worker_env = worker_env
        self._placement = placement
        self._worker_cls = None
        self.results = {}          # worker_id -> return value
        self.final_rank = {}       # worker_id -> rank at completion

    def _spawn(self, worker_id, host, slot_index):
        ray = _ray()
        if self._worker_cls is None:
            self._worker_cls = _make_worker_cls(ray)
        env = dict(self._worker_env)
        env.update({
            "HVDTPU_ELASTIC": "1",
            "HVDTPU_WORKER_ID": worker_id,
            "HVDTPU_RENDEZVOUS_ADDR": self.addr,
            "HVDTPU_RENDEZVOUS_PORT": str(self.port),
            "HVDTPU_JOB_TOKEN": self.token,
            "HVDTPU_START_TIMEOUT": str(self.elastic.base.start_timeout),
        })
        opts = {"num_cpus": self.elastic.base.cpus_per_worker}
        if getattr(self.elastic.base, "gpus_per_worker", 0):
            opts["num_gpus"] = self.elastic.base.gpus_per_worker
        if self._placement is not None:
            opts["placement_group"] = self._placement
        # Soft host affinity: prefer the discovered node so slot math
        # (local/cross ranks) reflects physical placement.
        try:
            opts["resources"] = {f"node:{host}": 0.001}
        except Exception:  # noqa: BLE001
            pass
        actor = self._worker_cls.options(**opts).remote()
        ref = actor.run.remote(self._fn, env, self._fn_args,
                               self._fn_kwargs)
        proc = _ActorProcess(actor, ref)
        from ..runner.elastic_driver import _Worker
        self.workers[worker_id] = _Worker(worker_id, host, slot_index,
                                          proc)

    def _sweep_exits(self):
        # Capture results AND final ranks of workers finishing this sweep
        # (the base class pops successes from both self.workers and
        # self.rank_order, so snapshot the order first).
        before = {wid: w.proc for wid, w in self.workers.items()}
        order_before = list(self.rank_order)
        changed = super()._sweep_exits()
        for wid in self.succeeded:
            proc = before.get(wid)
            if proc is not None and wid not in self.results:
                self.results[wid] = proc.result
                if wid in order_before:
                    self.final_rank[wid] = order_before.index(wid)
        return changed


class ElasticRayExecutor:
    """Reference API shape (horovod/ray/elastic.py:149): construct with
    elastic bounds, ``start()``, ``run(fn)`` retries/rescales through
    membership changes, results come from the cohort that finished.

        ex = ElasticRayExecutor(min_np=2, max_np=8, cpus_per_worker=1)
        ex.start()
        results = ex.run(train_fn)
        ex.shutdown()
    """

    def __init__(self, min_np=1, max_np=None, cpus_per_worker=1,
                 gpus_per_worker=0, use_gpu=False, env_vars=None,
                 override_discovery=None, reset_limit=None,
                 host_fail_limit=3, discovery_interval=1.0,
                 start_timeout=120, pack=False, use_placement_group=False,
                 verbose=False):
        base = SimpleNamespace(
            env={}, verbose=verbose, start_timeout=start_timeout,
            prefix_output=False, output_filename=None,
            rendezvous_addr=None, cpus_per_worker=cpus_per_worker,
            gpus_per_worker=gpus_per_worker or (1 if use_gpu else 0),
            resolve_hosts=lambda: [])
        self.elastic = ElasticSettings(
            base, discovery_script=None, min_np=min_np, max_np=max_np,
            reset_limit=reset_limit, host_fail_limit=host_fail_limit,
            discovery_interval=discovery_interval)
        self.discovery = override_discovery or RayHostDiscovery(
            cpus_per_worker=cpus_per_worker,
            gpus_per_worker=base.gpus_per_worker, max_np=max_np)
        self.env_vars = dict(env_vars or {})
        self.pack = pack
        self.use_placement_group = use_placement_group
        self._pg = None
        self._started = False
        self.log = get_logger()

    def start(self):
        """Validate the cluster is reachable and (optionally) reserve a
        placement group sized for max_np."""
        ray = _ray()
        if not ray.is_initialized():
            raise RuntimeError(
                "ray.init() must be called before ElasticRayExecutor."
                "start()")
        if self.use_placement_group:
            n = self.elastic.max_np or self.elastic.min_np
            hosts = len(self.discovery.find_available_hosts()) or 1
            # Uneven pack splits are handled by strategy_for (elastic
            # host counts are dynamic; divisibility is not required).
            strat = strategy_for(
                self.pack, n, num_hosts=hosts,
                cpus_per_worker=self.elastic.base.cpus_per_worker,
                gpus_per_worker=self.elastic.base.gpus_per_worker)
            self._pg = strat.create_placement_group(
                timeout=self.elastic.base.start_timeout)
        self._started = True

    def run(self, fn, args=None, kwargs=None):
        """Drive the elastic loop until a cohort finishes; returns the
        succeeded workers' results in final rank order."""
        if not self._started:
            raise RuntimeError("call start() before run()")
        driver = _RayElasticDriver(
            self.elastic, fn, args, kwargs, self.discovery,
            worker_env=self.env_vars, placement=self._pg)
        rc = driver.run()
        if rc != 0:
            raise RuntimeError(
                "elastic ray job failed (no worker cohort succeeded)")
        # Final rank order as recorded at each worker's completion (the
        # driver removes finished workers from its live rank_order, so
        # the order must come from the completion-time snapshot).
        ordered = sorted(driver.results,
                         key=lambda w: driver.final_rank.get(w, 1 << 30))
        return [driver.results[wid] for wid in ordered]

    def shutdown(self):
        if self._pg is not None:
            try:
                _ray().util.remove_placement_group(self._pg)
            except Exception:  # noqa: BLE001
                pass
            self._pg = None
        self._started = False


__all__ = ["ElasticRayExecutor", "RayHostDiscovery"]
