"""``plan_redistribution(src, dst, tree_meta) -> Program``.

The planner turns two :class:`~horovod_tpu.resharding.spec.Spec`\\ s
into a deterministic sequence of bounded-size collective steps. The
synthesis is pure interval arithmetic: for every destination interval
(dst ownership) pick a source holder (src ownership), emit the element
copies, then chunk the copy list into steps none of whose per-rank
payload exceeds ``HVDTPU_RESHARD_BUCKET_BYTES`` — the memory bound of
arXiv:2112.01075: a full replica of a leaf is never staged, peak
scratch stays within shard + 2×bucket.

Two candidate chunkings are priced with the PR-16 α–β cost model
(``analysis.costmodel.collective_time``) and the cheaper one wins:

- ``exchange`` — minimal bytes: each destination rank receives exactly
  the elements it lacks (all-to-all-shaped legs; legs whose payload is
  identical across receivers classify as all-gather; copies whose
  source IS the destination rank on the same mesh become zero-comm
  ``slice`` legs).
- ``gather`` — windowed all-gather of the source space: fewer, more
  uniform legs but every rank receives every window (wins only when
  the α·steps saving beats the β·bytes overshoot).

When the source spec carries ``pending_sum`` the values are unreduced
partial contributions and every leg becomes a reduce-scatter (the
executor sums per-holder windows into the destination).

Every Program carries a :meth:`~Program.signature` (cross-rank
identity, like ``ZeroPlan``), guardian leg digests +
:meth:`~Program.verify_consistency` (board-published, compared with
``guardian.compare_digests``), and :meth:`~Program.prove` — the
program lowered to hvd-sim's lockstep matcher
(``analysis.simulate._lockstep``) so deadlock-freedom (HVD501) and
digest agreement (HVD502) are proven per plan, not assumed.
"""

import hashlib
import json

import numpy as np

from .spec import Spec  # noqa: F401  (re-exported surface)
from ..utils import envparse

#: ``HVDTPU_RESHARD_BUCKET_BYTES`` default: 4 MiB windows — small
#: enough that scratch is negligible next to a shard, large enough
#: that the α term doesn't dominate a transition.
DEFAULT_RESHARD_BUCKET_BYTES = 4 * 1024 * 1024


class PlanError(ValueError):
    """A destination element no source rank holds (incompatible
    specs), or specs that disagree with the tree."""


class Copy:
    """``length`` elements from ``src_rank``'s buffer ``src_buf`` at
    ``src_off`` into ``dst_rank``'s ``dst_buf`` at ``dst_off``
    (``leaf`` = tree leaf index, for dtype and per-leaf grouping)."""

    __slots__ = ("leaf", "src_rank", "src_buf", "src_off",
                 "dst_rank", "dst_buf", "dst_off", "length")

    def __init__(self, leaf, src_rank, src_buf, src_off, dst_rank,
                 dst_buf, dst_off, length):
        self.leaf = leaf
        self.src_rank = src_rank
        self.src_buf = src_buf
        self.src_off = src_off
        self.dst_rank = dst_rank
        self.dst_buf = dst_buf
        self.dst_off = dst_off
        self.length = length

    def __repr__(self):
        return (f"Copy(leaf={self.leaf} r{self.src_rank}"
                f"{self.src_buf}[{self.src_off}:"
                f"{self.src_off + self.length}] -> r{self.dst_rank}"
                f"{self.dst_buf}[{self.dst_off}])")


class Step:
    """One collective leg: ``kind`` in slice / allgather / alltoall /
    reducescatter, ``nbytes`` = the largest per-rank payload (what the
    α–β model prices), ``total_bytes`` = sum over copies."""

    __slots__ = ("index", "kind", "op", "name", "nbytes",
                 "total_bytes", "copies")

    def __init__(self, index, kind, op, nbytes, total_bytes, copies):
        self.index = index
        self.kind = kind
        self.op = op
        self.name = None  # assigned once the program signature exists
        self.nbytes = int(nbytes)
        self.total_bytes = int(total_bytes)
        self.copies = copies

    def __repr__(self):
        return (f"Step({self.index}: {self.kind} "
                f"{len(self.copies)} copies, {self.nbytes}B/rank)")


class _ProgramEvent:
    """A Step viewed through hvd-sim's SimEvent duck type: ``slice``
    legs are local (``pset != 'global'`` completes immediately in the
    lockstep matcher); comm legs negotiate on the step name."""

    __slots__ = ("kind", "name", "pattern", "pset", "op", "file",
                 "line")

    def __init__(self, step):
        self.kind = step.kind
        self.name = step.name
        self.pattern = None
        self.pset = "local" if step.kind == "slice" else "global"
        self.op = step.op
        self.file = "<reshard-program>"
        self.line = step.index

    def slot(self):
        if self.name is not None:
            return ("n", self.name)
        return ("u", self.kind)

    def describe(self):
        out = f"`{self.kind}`"
        if self.name is not None:
            out += f"(name={self.name!r})"
        if self.op is not None:
            out += f" op={self.op}"
        return out


class Program:
    """A deterministic redistribution program. Identical on every rank
    that agrees on (src spec, dst spec, tree meta, bucket budget) —
    the cross-rank contract ``signature()`` pins and
    ``verify_consistency`` enforces through the guardian board."""

    __slots__ = ("src", "dst", "tree_meta", "bucket_bytes", "strategy",
                 "predicted_s", "steps", "sig8", "candidates")

    def __init__(self, src, dst, tree_meta, bucket_bytes, strategy,
                 predicted_s, steps, candidates):
        self.src = src
        self.dst = dst
        self.tree_meta = tree_meta
        self.bucket_bytes = int(bucket_bytes)
        self.strategy = strategy
        self.predicted_s = float(predicted_s)
        self.steps = steps
        self.candidates = candidates  # {strategy: predicted_s}
        self.sig8 = hashlib.sha1(
            json.dumps(self.signature(), sort_keys=True,
                       separators=(",", ":")).encode()
        ).hexdigest()[:8]
        for s in steps:
            s.name = f"reshard.{self.sig8}.{s.index:03d}"

    # -- identity ----------------------------------------------------------
    def signature(self):
        return {
            "version": 1,
            "src": self.src.signature(),
            "dst": self.dst.signature(),
            "meta": [[list(shape), dtype]
                     for shape, dtype in self.tree_meta],
            "bucket_bytes": self.bucket_bytes,
            "strategy": self.strategy,
            "steps": [{"kind": s.kind, "op": s.op,
                       "nbytes": s.nbytes,
                       "total_bytes": s.total_bytes,
                       "ncopies": len(s.copies)}
                      for s in self.steps],
        }

    def bytes_moved(self):
        """Wire bytes (non-slice legs only)."""
        return sum(s.total_bytes for s in self.steps
                   if s.kind != "slice")

    def comm_steps(self):
        return sum(1 for s in self.steps if s.kind != "slice")

    # -- guardian ----------------------------------------------------------
    def leg_digests(self, rank):
        """Guardian digests aggregated per leg kind — same field set
        as ``ZeroRuntime.leg_digests`` so ``guardian.compare_digests``
        applies unchanged."""
        digests = {}
        for kind in sorted({s.kind for s in self.steps}):
            ss = [s for s in self.steps if s.kind == kind]
            ops = sorted({s.op for s in ss if s.op is not None})
            dtypes = sorted({self.tree_meta[c.leaf][1]
                             for s in ss for c in s.copies})
            digests[f"reshard_{kind}"] = {
                "kind": f"reshard_{kind}",
                "op": ops[0] if ops else None,
                "dtype": ",".join(dtypes),
                "shapes": [[s.total_bytes] for s in ss],
                "process_set": 0,
                "prescale": None,
                "postscale": None,
                "root_rank": None,
                "codec": self.sig8,
                "shard_index": rank,
                "shard_shape": [[s.nbytes] for s in ss],
            }
        return digests

    def verify_consistency(self, board=None, rank=None, size=None,
                           timeout_s=None):
        """Cross-rank program check through the guardian board (multi-
        process cohorts with HVDTPU_CONSISTENCY_CHECK on): publish this
        rank's leg digests, compare every peer's — a rank that derived
        a different program would exchange mismatched windows and
        corrupt the tree silently. Mirrors
        ``ZeroRuntime.verify_plan_consistency``."""
        from .. import guardian
        if board is None:
            if not envparse.get_int(envparse.CONSISTENCY_CHECK, 0):
                return
            from .. import basics
            rt = basics.runtime()
            if rt.topology.size <= 1:
                return
            board = guardian.make_cross_process_board()
            if board is None:
                return
            rank, size = rt.topology.rank, rt.topology.size
        import time
        if timeout_s is None:
            timeout_s = envparse.get_float(
                envparse.CONSISTENCY_TIMEOUT, 10.0)
        mine = self.leg_digests(rank)
        for leg, digest in mine.items():
            board.put(f"reshard.plan.{leg}.{rank}",
                      guardian.render_digest(digest))
        for leg, digest in mine.items():
            deadline = time.monotonic() + timeout_s
            theirs_by_rank = {}
            waiting = set(range(size)) - {rank}
            while waiting:
                for r in sorted(waiting):
                    raw = board.get(f"reshard.plan.{leg}.{r}")
                    if raw is not None:
                        theirs_by_rank[r] = json.loads(raw)
                        waiting.discard(r)
                if not waiting or time.monotonic() > deadline:
                    break
                time.sleep(0.01)
            divergences = guardian.compare_digests(digest,
                                                   theirs_by_rank)
            if divergences:
                from ..exceptions import CollectiveMismatchError
                lines = [f"  rank {r}: {field} = {theirs!r} (rank "
                         f"{rank} derived {ours!r})"
                         for r, field, theirs, ours in divergences]
                raise CollectiveMismatchError(
                    f"redistribution program {leg} diverges across "
                    "ranks:\n" + "\n".join(lines) +
                    "\nEvery rank must derive the identical program — "
                    "check the specs/tree/HVDTPU_RESHARD_BUCKET_BYTES "
                    "agree on all ranks.", divergences=divergences)

    # -- hvd-sim proof -----------------------------------------------------
    def sim_stream(self):
        """This program as one rank's hvd-sim event stream."""
        return [_ProgramEvent(s) for s in self.steps]

    def prove(self, world=None):
        """Run the program through hvd-sim's lockstep matcher
        (``analysis.simulate._lockstep``) at ``world`` symbolic ranks:
        returns ``[]`` when deadlock-freedom (HVD501) and digest
        agreement (HVD502) hold, else the proven Diagnostics."""
        if world is None:
            world = max(self.src.world, self.dst.world)
        world = max(2, int(world))
        streams = {r: self.sim_stream() for r in range(world)}
        return check_streams(streams)


def check_streams(streams):
    """Lockstep-match per-rank event streams; returns HVD501/HVD502
    Diagnostics (the same rules the schedule simulator proves) or
    ``[]``. Exposed separately so tests can corrupt a stream and watch
    the checker catch it."""
    from ..analysis.diagnostics import Diagnostic
    from ..analysis.simulate import _lockstep
    ranks = sorted(streams)
    result = _lockstep(streams, ranks)
    if result is None:
        return []
    blocked = {r: ev.describe() for r, ev in result["blocked"].items()}
    if result["type"] == "deadlock":
        return [Diagnostic.make(
            "HVD501",
            "redistribution program deadlocks: per-rank step "
            f"sequences diverge at {blocked}",
            file="<reshard-program>",
            trace={"blocked": blocked})]
    return [Diagnostic.make(
        "HVD502",
        f"redistribution program digest mismatch on "
        f"{result['field']}: {blocked}",
        file="<reshard-program>",
        trace={"blocked": blocked, "field": result["field"]})]


# ==========================================================================
# Synthesis
# ==========================================================================

def _source_cover(src, tree_meta, leaf):
    """Sorted coverage list ``(g0, g1, rank, buf, b0)`` of every src
    rank's holdings of ``leaf``."""
    cov = []
    for r in range(src.world):
        for iv in src.ownership(tree_meta, r)[leaf]:
            cov.append((iv.g0, iv.g0 + iv.length, r, iv.buf, iv.b0))
    cov.sort(key=lambda c: (c[0], c[2]))
    return cov


def _raw_copies(src, dst, tree_meta, same_mesh):
    """The minimal copy list: every destination interval filled from a
    deterministic source choice — the destination rank itself when the
    meshes coincide and it already holds the bytes (zero comm), else
    the lowest-numbered holder. With ``pending_sum`` EVERY holder
    contributes (the executor sums)."""
    copies = []
    for i in range(len(tree_meta)):
        cov = _source_cover(src, tree_meta, i)
        if not cov:
            # leaf has no source elements (size 0) — nothing to move.
            continue
        for dr in range(dst.world):
            for div in dst.ownership(tree_meta, dr)[i]:
                p, end = div.g0, div.g0 + div.length
                while p < end:
                    cands = [c for c in cov if c[0] <= p < c[1]]
                    if not cands:
                        raise PlanError(
                            f"leaf {i} element {p} is not held by any "
                            "source rank — specs are incompatible "
                            "with the tree")
                    if src.pending_sum:
                        take = min(end, min(c[1] for c in cands)) - p
                        chosen = cands
                    else:
                        chosen = None
                        if same_mesh:
                            for c in cands:
                                if c[2] == dr:
                                    chosen = c
                                    break
                        if chosen is None:
                            chosen = cands[0]
                        take = min(end, chosen[1]) - p
                        chosen = [chosen]
                    for g0, _, r, buf, b0 in chosen:
                        copies.append(Copy(
                            i, r, buf, b0 + (p - g0), dr, div.buf,
                            div.b0 + (p - div.g0), take))
                    p += take
    return copies


def _itemsize(tree_meta, leaf):
    return np.dtype(tree_meta[leaf][1]).itemsize


def _split_large(copies, tree_meta, bucket_bytes):
    out = []
    for c in copies:
        isz = _itemsize(tree_meta, c.leaf)
        max_elems = max(1, bucket_bytes // isz)
        off = 0
        while off < c.length:
            take = min(c.length - off, max_elems)
            out.append(Copy(c.leaf, c.src_rank, c.src_buf,
                            c.src_off + off, c.dst_rank, c.dst_buf,
                            c.dst_off + off, take))
            off += take
    return out


def _copy_key(c):
    return (c.leaf, c.dst_rank, c.dst_buf, c.dst_off, c.src_rank)


def _classify(copies, op):
    """Leg kind of one sealed chunk of remote copies."""
    if op == "sum":
        return "reducescatter"
    by_dst = {}
    for c in copies:
        by_dst.setdefault(c.dst_rank, set()).add(
            (c.src_rank, c.src_buf, c.src_off, c.length))
    payloads = list(by_dst.values())
    if len(payloads) > 1 and all(p == payloads[0]
                                 for p in payloads[1:]):
        return "allgather"
    return "alltoall"


def _chunk_bytes(copies, tree_meta):
    per_rank = {}
    total = 0
    for c in copies:
        b = c.length * _itemsize(tree_meta, c.leaf)
        per_rank[c.dst_rank] = per_rank.get(c.dst_rank, 0) + b
        total += b
    return (max(per_rank.values()) if per_rank else 0), total


def _chunk_exchange(local, remote, tree_meta, bucket_bytes, op):
    """Exchange chunking: seal a step when any destination rank's
    received payload would exceed the bucket budget."""
    steps = []

    def seal(chunk, kind):
        if not chunk:
            return
        nbytes, total = _chunk_bytes(chunk, tree_meta)
        steps.append(Step(len(steps), kind, op if kind != "slice"
                          else None, nbytes, total, chunk))

    for group, forced_kind in ((remote, None), (local, "slice")):
        chunk, per_rank = [], {}
        for c in sorted(group, key=_copy_key):
            b = c.length * _itemsize(tree_meta, c.leaf)
            if chunk and per_rank.get(c.dst_rank, 0) + b \
                    > bucket_bytes:
                seal(chunk, forced_kind or _classify(chunk, op))
                chunk, per_rank = [], {}
            chunk.append(c)
            per_rank[c.dst_rank] = per_rank.get(c.dst_rank, 0) + b
        seal(chunk, forced_kind or _classify(chunk, op))
    return steps


def _chunk_gather(local, remote, tree_meta, bucket_bytes, op):
    """Gather chunking: windows walk the UNIQUE source bytes; every
    window is an all-gather (each destination receives the whole
    window). More bytes than exchange, fewer / more uniform legs."""
    steps = []
    order = sorted(remote, key=lambda c: (c.leaf, c.src_rank,
                                          c.src_buf, c.src_off))
    window_of, cum = {}, 0
    for c in order:
        key = (c.src_rank, c.src_buf, c.src_off, c.length)
        if key not in window_of:
            window_of[key] = cum // bucket_bytes
            cum += c.length * _itemsize(tree_meta, c.leaf)
    windows = {}
    for c in order:
        windows.setdefault(
            window_of[(c.src_rank, c.src_buf, c.src_off, c.length)],
            []).append(c)
    for w in sorted(windows):
        chunk = windows[w]
        uniq = {}
        for c in chunk:
            uniq[(c.src_rank, c.src_buf, c.src_off, c.length)] = \
                c.length * _itemsize(tree_meta, c.leaf)
        nbytes = sum(uniq.values())
        steps.append(Step(
            len(steps),
            "reducescatter" if op == "sum" else "allgather", op,
            nbytes, nbytes, chunk))
    if local:
        nbytes, total = _chunk_bytes(local, tree_meta)
        steps.append(Step(len(steps), "slice", None, nbytes, total,
                          local))
    return steps


def _price(steps, world, table):
    from ..analysis import costmodel
    return sum(costmodel.collective_time(s.kind, s.nbytes, world,
                                         table)
               for s in steps if s.kind != "slice")


def plan_redistribution(src_spec, dst_spec, tree_meta,
                        bucket_bytes=None, table=None):
    """Plan the (mesh, layout) → (mesh, layout) move of a tree whose
    leaves are ``tree_meta = [(shape, dtype), ...]`` (see
    :func:`~horovod_tpu.resharding.spec.tree_meta_of`). Returns the
    cheapest legal :class:`Program` under the α–β cost model."""
    tree_meta = [(tuple(int(d) for d in shape), str(dtype))
                 for shape, dtype in tree_meta]
    src_spec.validate(tree_meta)
    dst_spec.validate(tree_meta)
    if bucket_bytes is None:
        bucket_bytes = envparse.get_int(
            envparse.RESHARD_BUCKET_BYTES,
            DEFAULT_RESHARD_BUCKET_BYTES)
    bucket_bytes = max(int(bucket_bytes), 1)
    same_mesh = src_spec.mesh_signature() == dst_spec.mesh_signature()
    op = "sum" if src_spec.pending_sum else None
    copies = _split_large(
        _raw_copies(src_spec, dst_spec, tree_meta, same_mesh),
        tree_meta, bucket_bytes)
    local = [c for c in copies
             if same_mesh and c.src_rank == c.dst_rank
             and not src_spec.pending_sum]
    remote = [c for c in copies
              if not (same_mesh and c.src_rank == c.dst_rank)
              or src_spec.pending_sum]
    world = max(src_spec.world, dst_spec.world)
    candidates = {}
    exchange = _chunk_exchange(local, remote, tree_meta, bucket_bytes,
                               op)
    candidates["exchange"] = (_price(exchange, world, table), exchange)
    if remote:
        gather = _chunk_gather(local, remote, tree_meta, bucket_bytes,
                               op)
        candidates["gather"] = (_price(gather, world, table), gather)
    strategy = min(sorted(candidates),
                   key=lambda k: candidates[k][0])
    if not remote and all(s.kind == "slice" for s in exchange):
        candidates["local"] = candidates.pop("exchange")
        strategy = "local"
    predicted_s, steps = candidates[strategy]
    for idx, s in enumerate(steps):
        s.index = idx
    return Program(src_spec, dst_spec, tree_meta, bucket_bytes,
                   strategy, predicted_s,
                   steps, {k: v[0] for k, v in candidates.items()})
