"""(mesh, layout) specs for the redistribution planner.

A :class:`Spec` names WHERE every element of a pytree lives: an ordered
set of mesh axes, a per-leaf tensor layout (:class:`Replicated` or
:class:`Sharded` along one tensor dimension over one mesh axis), and an
optional tree-wide :class:`ZeroFlat` stage — the ZeRO-1 pad-and-split
flat-bucket layout of ``ops.zero.ZeroPlan`` — over another mesh axis.
The two stages compose: a rank's ZeRO shard is a window of the packed
buckets built from its TENSOR-LOCAL leaf slices, which is exactly the
2D (data × tensor) geometry ``parallel/twod.py`` trains in.

The planner consumes specs through one question — *which global flat
elements of leaf ``i`` does rank ``r`` hold, and at what offset of
which local buffer?* — answered by :meth:`Spec.ownership` as a list of
:class:`Interval` runs. Everything else (program synthesis, chunking,
cost ranking) is interval arithmetic over those runs, the portable-
collectives formulation of arXiv:2112.01075.

Local buffers are keyed ``("leaf", i)`` (the rank's possibly-sliced
leaf, flattened) or ``("bucket", k)`` (the rank's ``shard_len`` window
of padded fusion bucket ``k``) — the same buffer identities the ZeRO
checkpoint form and the serving range programs already speak.
"""

import numpy as np


class Replicated:
    """Every rank on the mesh holds the full leaf."""

    __slots__ = ()

    def signature(self):
        return {"kind": "replicated"}

    def __repr__(self):
        return "Replicated()"


class Sharded:
    """Leaf split along tensor dimension ``dim`` over mesh axis
    ``axis``. ``even=True`` (the jit/GSPMD contract of
    ``parallel.sharding._spec_fits``) requires the dimension to divide
    the axis size; ``even=False`` uses the serving plane's near-even
    contiguous ranges (``serving.state.row_slice``). Scalars and
    leaves whose rank does not reach ``dim`` degrade to replicated —
    the same rule the serving ROWS layout applies."""

    __slots__ = ("axis", "dim", "even")

    def __init__(self, axis, dim=0, even=True):
        self.axis = axis
        self.dim = int(dim)
        self.even = bool(even)

    def signature(self):
        return {"kind": "sharded", "axis": self.axis, "dim": self.dim,
                "even": self.even}

    def __repr__(self):
        return (f"Sharded(axis={self.axis!r}, dim={self.dim}, "
                f"even={self.even})")


class ZeroFlat:
    """Tree-wide ZeRO-1 flat-dense stage: the leaves (after the tensor
    stage) pack into ``plan``'s padded fusion buckets and mesh axis
    ``axis`` owns contiguous ``shard_len`` windows — the exact
    ``ops.zero.ZeroPlan`` geometry, so checkpointed train shards ARE
    this layout's local buffers."""

    __slots__ = ("axis", "plan")

    def __init__(self, axis, plan):
        self.axis = axis
        self.plan = plan

    def signature(self):
        return {"kind": "zero", "axis": self.axis,
                "plan": self.plan.signature()}

    def __repr__(self):
        return f"ZeroFlat(axis={self.axis!r}, n={self.plan.n})"


class Interval:
    """``length`` elements of a leaf's global flat space starting at
    ``g0``, held by some rank at offset ``b0`` of local buffer
    ``buf`` (``("leaf", i)`` or ``("bucket", k)``)."""

    __slots__ = ("g0", "length", "buf", "b0")

    def __init__(self, g0, length, buf, b0):
        self.g0 = g0
        self.length = length
        self.buf = buf
        self.b0 = b0

    def __repr__(self):
        return (f"Interval([{self.g0}:{self.g0 + self.length}) "
                f"@ {self.buf}+{self.b0})")


def _prod(xs):
    out = 1
    for x in xs:
        out *= int(x)
    return out


def leaf_offsets(plan):
    """leaf index -> (bucket index, flat offset inside the packed
    bucket buffer); packing order is the bucket's ``indices`` order
    (``ops.bucketing._pack``)."""
    out = {}
    for k, b in enumerate(plan.buckets):
        off = 0
        for i in b.indices:
            out[i] = (k, off)
            off += _prod(plan.leaf_shapes[i])
    return out


class Spec:
    """One side of a redistribution: mesh axes (ordered name -> size,
    ranks enumerate row-major over that order), per-leaf tensor
    layouts, and an optional tree-wide :class:`ZeroFlat` stage.
    ``pending_sum=True`` marks the held values as unreduced partial
    contributions — every holder of an element must be summed (the
    gradient case), which forces reduce-scatter legs in the planner.
    """

    __slots__ = ("mesh_axes", "leaves", "zero", "pending_sum")

    def __init__(self, mesh_axes, leaves, zero=None, pending_sum=False):
        self.mesh_axes = {str(k): int(v) for k, v in
                          dict(mesh_axes).items()}
        if any(v < 1 for v in self.mesh_axes.values()):
            raise ValueError(f"mesh axis sizes must be >= 1: "
                             f"{self.mesh_axes}")
        self.leaves = list(leaves)
        self.zero = zero
        self.pending_sum = bool(pending_sum)
        if zero is not None and zero.axis not in self.mesh_axes:
            raise ValueError(f"zero stage axis {zero.axis!r} not in "
                             f"mesh axes {list(self.mesh_axes)}")
        for lay in self.leaves:
            if isinstance(lay, Sharded) \
                    and lay.axis not in self.mesh_axes:
                raise ValueError(f"sharded axis {lay.axis!r} not in "
                                 f"mesh axes {list(self.mesh_axes)}")

    # -- rank geometry -----------------------------------------------------
    @property
    def world(self):
        return _prod(self.mesh_axes.values())

    def coords(self, rank):
        """Row-major coordinates of ``rank`` over the axis order."""
        out, rem = {}, int(rank)
        for name in reversed(list(self.mesh_axes)):
            size = self.mesh_axes[name]
            out[name] = rem % size
            rem //= size
        return out

    def mesh_signature(self):
        return [[name, size] for name, size in self.mesh_axes.items()]

    def signature(self):
        return {
            "mesh": self.mesh_signature(),
            "leaves": [lay.signature() for lay in self.leaves],
            "zero": None if self.zero is None
            else self.zero.signature(),
            "pending_sum": self.pending_sum,
        }

    # -- validation --------------------------------------------------------
    def validate(self, tree_meta):
        if len(self.leaves) != len(tree_meta):
            raise ValueError(
                f"spec has {len(self.leaves)} leaf layouts for "
                f"{len(tree_meta)} tree leaves")
        for i, (shape, _) in enumerate(tree_meta):
            lay = self.leaves[i]
            if isinstance(lay, Sharded) and lay.even \
                    and lay.dim < len(shape) and shape[lay.dim] >= 1:
                nt = self.mesh_axes[lay.axis]
                if shape[lay.dim] % nt:
                    raise ValueError(
                        f"leaf {i} shape {shape} dim {lay.dim} does "
                        f"not divide mesh axis {lay.axis!r}={nt} "
                        f"(even sharding); use even=False for "
                        "near-even ranges")
        if self.zero is not None:
            plan = self.zero.plan
            if plan.n != self.mesh_axes[self.zero.axis]:
                raise ValueError(
                    f"zero plan n={plan.n} != mesh axis "
                    f"{self.zero.axis!r}="
                    f"{self.mesh_axes[self.zero.axis]}")
            local = [self.local_shape(i, shape, 0)
                     for i, (shape, _) in enumerate(tree_meta)]
            if [tuple(s) for s in local] \
                    != [tuple(s) for s in plan.leaf_shapes]:
                raise ValueError(
                    "zero plan leaf shapes do not match the spec's "
                    f"tensor-local shapes: plan={plan.leaf_shapes} "
                    f"vs local={local}")

    # -- tensor stage ------------------------------------------------------
    def _dim_slice(self, lay, extent, rank):
        nt = self.mesh_axes[lay.axis]
        t = self.coords(rank)[lay.axis]
        if lay.even:
            step = extent // nt
            return t * step, (t + 1) * step
        return (extent * t) // nt, (extent * (t + 1)) // nt

    def local_shape(self, i, shape, rank):
        """The rank's tensor-local leaf shape (what the zero stage
        packs; equal across ranks for even sharding)."""
        lay = self.leaves[i]
        if not isinstance(lay, Sharded) or lay.dim >= len(shape) \
                or shape[lay.dim] < 1:
            return tuple(shape)
        lo, hi = self._dim_slice(lay, shape[lay.dim], rank)
        out = list(shape)
        out[lay.dim] = hi - lo
        return tuple(out)

    def _tensor_runs(self, i, shape, rank):
        """Merged runs ``(g0, l0, length)`` mapping the rank's tensor-
        local flat space (offset ``l0``) onto the leaf's global flat
        space (offset ``g0``)."""
        size = _prod(shape)
        if size == 0:
            return []
        lay = self.leaves[i]
        if not isinstance(lay, Sharded) or lay.dim >= len(shape) \
                or shape[lay.dim] < 1:
            return [(0, 0, size)]
        lo, hi = self._dim_slice(lay, shape[lay.dim], rank)
        if hi <= lo:
            return []
        if (lo, hi) == (0, shape[lay.dim]):
            return [(0, 0, size)]
        inner = _prod(shape[lay.dim + 1:])
        outer = _prod(shape[:lay.dim])
        run = (hi - lo) * inner
        stride = shape[lay.dim] * inner
        return [(o * stride + lo * inner, o * run, run)
                for o in range(outer)]

    # -- ownership ---------------------------------------------------------
    def ownership(self, tree_meta, rank):
        """Per leaf: the :class:`Interval` runs rank ``rank`` holds."""
        out = []
        if self.zero is None:
            for i, (shape, _) in enumerate(tree_meta):
                out.append([Interval(g0, ln, ("leaf", i), l0)
                            for g0, l0, ln
                            in self._tensor_runs(i, shape, rank)])
            return out
        plan = self.zero.plan
        offsets = leaf_offsets(plan)
        d = self.coords(rank)[self.zero.axis]
        for i, (shape, _) in enumerate(tree_meta):
            runs = self._tensor_runs(i, shape, rank)
            k, off = offsets[i]
            sl = plan.shards[k].shard_len
            lo_sh, hi_sh = d * sl, (d + 1) * sl
            local_size = sum(r[2] for r in runs)
            a, b = max(off, lo_sh), min(off + local_size, hi_sh)
            ivs = []
            if a < b:
                tl_a, tl_b = a - off, b - off
                for g0, l0, ln in runs:
                    s, e = max(tl_a, l0), min(tl_b, l0 + ln)
                    if s < e:
                        ivs.append(Interval(
                            g0 + (s - l0), e - s, ("bucket", k),
                            off + s - lo_sh))
            out.append(ivs)
        return out

    def local_buffers(self, tree_meta, rank):
        """Ordered ``buf_key -> (n_elements, dtype_str)`` of the
        rank's local buffers under this spec."""
        out = {}
        if self.zero is not None:
            plan = self.zero.plan
            for k, (b, s) in enumerate(zip(plan.buckets, plan.shards)):
                out[("bucket", k)] = (s.shard_len, str(b.dtype))
            return out
        for i, (shape, dtype) in enumerate(tree_meta):
            n = sum(r[2] for r in self._tensor_runs(i, shape, rank))
            if n:
                out[("leaf", i)] = (n, str(dtype))
        return out


def tree_meta_of(tree):
    """``[(shape, dtype), ...]`` for a pytree of arrays or
    ShapeDtypeStructs — the planner's view of the tree."""
    import jax
    return [(tuple(leaf.shape), str(leaf.dtype))
            for leaf in jax.tree.leaves(tree)]


def zero_flat_spec(plan, axis="hvd", extra_axes=None):
    """The ZeRO-1 train layout as a Spec: flat bucket shards of
    ``plan`` over ``axis`` (tensor stage replicated)."""
    mesh = dict(extra_axes or {})
    mesh[axis] = plan.n
    return Spec(mesh, [Replicated() for _ in plan.leaf_shapes],
                zero=ZeroFlat(axis, plan))


def replicated_spec(nleaves, mesh_axes):
    """Fully-replicated layout over ``mesh_axes``."""
    return Spec(mesh_axes, [Replicated() for _ in range(nleaves)])
