"""Memory-bounded (mesh, layout) → (mesh, layout) redistribution.

The planner (docs/resharding.md) unifies the three hand-rolled
transitions — ZeRO elastic reshard (``ops.zero.reshard_state``),
train→serve range programs (``serving.state``), and 2D data × tensor
composition (``parallel.twod``) — into one algebra: describe both
sides as :class:`Spec`, call :func:`plan_redistribution`, execute the
resulting :class:`Program` host-side (:func:`execute_host`) or in-jit
(:func:`make_jit_executor`). Programs are chunked to
``HVDTPU_RESHARD_BUCKET_BYTES``, priced by the α–β cost model, carry
guardian digests, and prove themselves deadlock-free under hvd-sim
(``Program.prove``).
"""

from .spec import (Interval, Replicated, Sharded, Spec, ZeroFlat,
                   leaf_offsets, replicated_spec, tree_meta_of,
                   zero_flat_spec)
from .planner import (Copy, DEFAULT_RESHARD_BUCKET_BYTES, PlanError,
                      Program, Step, check_streams,
                      plan_redistribution)
from .execute import (MemoryLedger, buffers_of_tree, execute_host,
                      make_jit_executor, reader_for_buffers)

__all__ = [
    "Interval", "Replicated", "Sharded", "Spec", "ZeroFlat",
    "leaf_offsets", "replicated_spec", "tree_meta_of",
    "zero_flat_spec", "Copy", "DEFAULT_RESHARD_BUCKET_BYTES",
    "PlanError", "Program", "Step", "check_streams",
    "plan_redistribution", "MemoryLedger", "buffers_of_tree",
    "execute_host", "make_jit_executor", "reader_for_buffers",
]
