"""Program executors: host-side windowed copies and in-jit shard_map.

The host executor is the elastic / train→serve path: it assembles each
destination rank's local buffers by reading bounded windows of the
source shards — ``read_window(src_rank, buf_key, start, length)`` is
the only way source data enters, so a fully-replicated leaf is never
materialized. Peak live bytes per destination rank stay within its
local buffers (≤ one shard) plus the in-flight window (≤ the bucket
budget) plus one source-side staging window — the shard + 2×bucket
bound the property tests pin through :class:`MemoryLedger`.

The in-jit executor lowers a same-mesh single-axis program into a
shard_map body: per step, each rank gathers its send window through
``lax.all_gather`` / exchanges per-destination rows through
``lax.all_to_all``, then scatters the received elements into its
destination buffers via precomputed index maps. Scratch per step is
world × window.

Telemetry: ``hvd_reshard_bytes_total{leg}``, ``hvd_reshard_seconds``,
``hvd_reshard_peak_bytes`` (docs/metrics.md).
"""

import numpy as np


def _m_bytes():
    from ..telemetry import core as telemetry
    return telemetry.counter(
        "hvd_reshard_bytes_total",
        "Bytes moved by redistribution programs, per leg kind",
        ("leg",))


def _m_seconds():
    from ..telemetry import core as telemetry
    return telemetry.histogram(
        "hvd_reshard_seconds",
        "Wall time of one redistribution program execution")


def _m_peak():
    from ..telemetry import core as telemetry
    return telemetry.gauge(
        "hvd_reshard_peak_bytes",
        "Peak live scratch+destination bytes of the last program "
        "execution (bounded by shard + 2x HVDTPU_RESHARD_BUCKET_BYTES)")


class MemoryLedger:
    """Counting allocator shim: every buffer the host executor holds
    is accounted here, so tests assert the memory bound instead of
    trusting it."""

    __slots__ = ("live", "peak")

    def __init__(self):
        self.live = 0
        self.peak = 0

    def alloc(self, nbytes):
        self.live += int(nbytes)
        if self.live > self.peak:
            self.peak = self.live

    def free(self, nbytes):
        self.live -= int(nbytes)


def execute_host(program, read_window, ranks=None, dtype_override=None,
                 ledger=None):
    """Run ``program`` host-side for the given destination ranks
    (default: all). Returns ``(results, report)`` where ``results``
    maps ``dst_rank -> {buf_key: 1-D np.ndarray}`` and ``report``
    carries ``peak_bytes`` (max over ranks of buffers + in-flight
    windows), per-leg byte counts, and the program's predicted cost.

    ``read_window(src_rank, buf_key, start, length)`` must return the
    1-D window of that source buffer — and must itself stay windowed
    (read a shard, slice a bucket) for the memory bound to hold
    end-to-end. ``dtype_override`` reinterprets every destination
    buffer's dtype (the optimizer-moment path reuses one geometry for
    f32 moment slots over non-f32 params)."""
    from ..telemetry import span as tele_span
    ledger = ledger if ledger is not None else MemoryLedger()
    dst, meta = program.dst, program.tree_meta
    if ranks is None:
        ranks = range(dst.world)
    results, peak_overall = {}, 0
    bytes_by_leg = {}
    with tele_span(["resharding"], "RESHARD_EXECUTE",
                   histogram=_m_seconds()):
        for rank in ranks:
            base = ledger.live
            rank_peak = 0
            bufs = {}
            for key, (n, dt) in dst.local_buffers(meta, rank).items():
                dt = np.dtype(dtype_override or dt)
                bufs[key] = np.zeros(n, dt)
                ledger.alloc(bufs[key].nbytes)
            rank_peak = max(rank_peak, ledger.live - base)
            for step in program.steps:
                moved = 0
                for c in step.copies:
                    if c.dst_rank != rank:
                        continue
                    win = np.asarray(read_window(
                        c.src_rank, c.src_buf, c.src_off, c.length))
                    win = win.reshape(-1)
                    ledger.alloc(win.nbytes)
                    rank_peak = max(rank_peak, ledger.live - base)
                    out = bufs[c.dst_buf]
                    sl = slice(c.dst_off, c.dst_off + c.length)
                    if step.op == "sum":
                        out[sl] += win.astype(out.dtype)
                    else:
                        out[sl] = win.astype(out.dtype)
                    ledger.free(win.nbytes)
                    moved += win.nbytes
                if moved:
                    bytes_by_leg[step.kind] = \
                        bytes_by_leg.get(step.kind, 0) + moved
                    _m_bytes().labels(leg=step.kind).inc(moved)
            results[rank] = bufs
            peak_overall = max(peak_overall, rank_peak)
            # Hand the rank's buffers to the caller: they leave the
            # executor's accounting (the bound is per-rank scratch,
            # not the caller's aggregate).
            for arr in bufs.values():
                ledger.free(arr.nbytes)
    _m_peak().set(peak_overall)
    report = {
        "strategy": program.strategy,
        "predicted_s": program.predicted_s,
        "peak_bytes": peak_overall,
        "bytes_by_leg": bytes_by_leg,
        "wire_bytes": program.bytes_moved(),
    }
    return results, report


def buffers_of_tree(spec, tree_meta, leaves, rank):
    """Materialize ``rank``'s local buffers under ``spec`` from full
    (host) leaf arrays — the test/bench helper for seeding a source
    side. Uses ownership intervals, so it works for any layout."""
    own = spec.ownership(tree_meta, rank)
    bufs = {key: np.zeros(n, np.dtype(dt))
            for key, (n, dt) in
            spec.local_buffers(tree_meta, rank).items()}
    for i, ivs in enumerate(own):
        flat = np.asarray(leaves[i]).reshape(-1)
        for iv in ivs:
            bufs[iv.buf][iv.b0:iv.b0 + iv.length] = \
                flat[iv.g0:iv.g0 + iv.length]
    return bufs


def reader_for_buffers(buffers):
    """``read_window`` over ``{rank: {buf_key: array}}`` that slices —
    never copies whole buffers beyond the requested window."""
    def read_window(rank, buf, start, length):
        return buffers[rank][buf][start:start + length]
    return read_window


# ==========================================================================
# In-jit execution (same mesh, single axis)
# ==========================================================================

def _index_maps(program, axis_size):
    """Per step: host-precomputed gather/scatter index maps over each
    rank's CONCATENATED local in/out buffers (-1 = padding)."""
    src_layout = _flat_layout(program.src, program.tree_meta)
    dst_layout = _flat_layout(program.dst, program.tree_meta)
    maps = []
    n = axis_size
    for step in program.steps:
        if step.kind == "slice":
            nloc = max((sum(c.length for c in step.copies
                            if c.dst_rank == r) for r in range(n)),
                       default=0)
            gidx = np.full((n, nloc), -1, np.int32)
            sidx = np.full((n, nloc), -1, np.int32)
            fill = np.zeros(n, np.int64)
            for c in step.copies:
                r = c.dst_rank
                a = int(fill[r])
                gidx[r, a:a + c.length] = np.arange(
                    src_layout[c.src_buf] + c.src_off,
                    src_layout[c.src_buf] + c.src_off + c.length)
                sidx[r, a:a + c.length] = np.arange(
                    dst_layout[c.dst_buf] + c.dst_off,
                    dst_layout[c.dst_buf] + c.dst_off + c.length)
                fill[r] += c.length
            maps.append(("slice", gidx, sidx))
            continue
        # comm step: rows keyed (src, dst); window = max pair payload
        win = 0
        for s in range(n):
            for d in range(n):
                b = sum(c.length for c in step.copies
                        if c.src_rank == s and c.dst_rank == d)
                win = max(win, b)
        send = np.full((n, n, win), -1, np.int32)   # [src, dst, :]
        recv = np.full((n, n, win), -1, np.int32)   # [dst, src, :]
        fill = np.zeros((n, n), np.int64)
        for c in sorted(step.copies,
                        key=lambda c: (c.src_rank, c.dst_rank,
                                       c.dst_buf, c.dst_off)):
            s, d = c.src_rank, c.dst_rank
            a = int(fill[s, d])
            send[s, d, a:a + c.length] = np.arange(
                src_layout[c.src_buf] + c.src_off,
                src_layout[c.src_buf] + c.src_off + c.length)
            recv[d, s, a:a + c.length] = np.arange(
                dst_layout[c.dst_buf] + c.dst_off,
                dst_layout[c.dst_buf] + c.dst_off + c.length)
            fill[s, d] += c.length
        maps.append((step.kind, send, recv))
    return maps


def _flat_layout(spec, tree_meta):
    """buf_key -> offset in the rank's concatenated local flat buffer
    (uniform across ranks — required for the SPMD body)."""
    sizes = {}
    for r in range(spec.world):
        bufs = spec.local_buffers(tree_meta, r)
        for key, (nelem, _) in bufs.items():
            if key in sizes and sizes[key] != nelem:
                raise NotImplementedError(
                    "in-jit execution requires uniform per-rank "
                    f"buffer sizes; {key} varies across ranks "
                    "(near-even sharding) — use execute_host")
            sizes[key] = nelem
    layout, off = {}, 0
    for key in sorted(sizes):
        layout[key] = off
        off += sizes[key]
    return layout


def make_jit_executor(program, mesh, axis_name):
    """Compile ``program`` (same single-axis mesh on both sides, no
    pending-sum legs) into a jitted ``fn(in_bufs) -> out_bufs`` over
    GLOBAL flat buffers sharded ``P(axis_name)``: ``in_bufs`` /
    ``out_bufs`` are dicts keyed like the spec's local buffers, each a
    ``(world * len,)`` array whose rank-r block is that rank's local
    buffer."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P
    from ..utils.jax_compat import shard_map as _shard_map

    n = int(mesh.shape[axis_name])
    for side, name in ((program.src, "src"), (program.dst, "dst")):
        if side.mesh_signature() != [[axis_name, n]]:
            raise NotImplementedError(
                f"in-jit execution supports single-axis same-mesh "
                f"programs; {name} mesh is {side.mesh_signature()}, "
                f"executor axis is [[{axis_name!r}, {n}]]")
    if any(s.op == "sum" for s in program.steps):
        raise NotImplementedError(
            "pending-sum (reduce-scatter) programs are host-path "
            "only for now")
    meta = program.tree_meta
    if len({dt for _, dt in meta}) > 1:
        raise NotImplementedError(
            "in-jit execution requires a uniform leaf dtype (the "
            "buffers ride one concatenated flat vector); mixed-dtype "
            "trees take execute_host")
    src_layout = _flat_layout(program.src, meta)
    dst_layout = _flat_layout(program.dst, meta)
    src_keys = sorted(src_layout)
    dst_keys = sorted(dst_layout)
    src_sizes = {k: program.src.local_buffers(meta, 0)[k][0]
                 for k in src_keys}
    dst_bufs0 = program.dst.local_buffers(meta, 0)
    total_out = sum(dst_bufs0[k][0] for k in dst_keys)
    maps = _index_maps(program, n)
    out_dtype = np.result_type(*[np.dtype(dt)
                                 for _, dt in meta]) if meta else \
        np.float32

    def body(*in_flat):
        r = lax.axis_index(axis_name)
        flat_in = jnp.concatenate(
            [b.reshape(-1) for b in in_flat]) if in_flat else \
            jnp.zeros((0,), out_dtype)
        # one dump slot at the end absorbs -1 padding scatters
        flat_out = jnp.zeros((total_out + 1,), flat_in.dtype)

        def scatter(flat_out, idx_rows, values):
            idx = jnp.where(idx_rows >= 0, idx_rows, total_out)
            return flat_out.at[idx.reshape(-1)].set(
                values.reshape(-1), mode="drop")

        for kind, a, b in maps:
            if kind == "slice":
                rows = jnp.take(jnp.asarray(a), r, axis=0)
                vals = jnp.take(flat_in, jnp.clip(rows, 0),
                                mode="clip")
                flat_out = scatter(
                    flat_out, jnp.take(jnp.asarray(b), r, axis=0),
                    vals)
            elif kind == "allgather":
                send = jnp.take(jnp.asarray(a), r, axis=0)  # (n, win)
                payload = jnp.where(
                    send >= 0,
                    jnp.take(flat_in, jnp.clip(send, 0), mode="clip"),
                    0).astype(flat_in.dtype)
                # every rank contributes its full per-destination rows;
                # gather then pick the rows addressed to me.
                gathered = lax.all_gather(payload, axis_name)
                # gathered[s, d, :] = payload rank s built for dst d;
                # keep the rows addressed to me.
                mine = jnp.take(gathered, r, axis=1)
                recv_rows = jnp.take(jnp.asarray(b), r, axis=0)
                flat_out = scatter(flat_out, recv_rows, mine)
            else:  # alltoall
                send = jnp.take(jnp.asarray(a), r, axis=0)  # (n, win)
                payload = jnp.where(
                    send >= 0,
                    jnp.take(flat_in, jnp.clip(send, 0), mode="clip"),
                    0).astype(flat_in.dtype)
                recv = lax.all_to_all(payload, axis_name,
                                      split_axis=0, concat_axis=0,
                                      tiled=True)
                recv_rows = jnp.take(jnp.asarray(b), r, axis=0)
                flat_out = scatter(flat_out, recv_rows, recv)
        flat_out = flat_out[:total_out]
        outs, off = [], 0
        for k in dst_keys:
            nelem = dst_bufs0[k][0]
            outs.append(flat_out[off:off + nelem])
            off += nelem
        return tuple(outs)

    in_specs = tuple(P(axis_name) for _ in src_keys)
    out_specs = tuple(P(axis_name) for _ in dst_keys)
    mapped = jax.jit(_shard_map(
        body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_vma=False))

    def run(in_bufs):
        args = [jnp.asarray(in_bufs[k]).reshape(
            n * src_sizes[k]) for k in src_keys]
        outs = mapped(*args)
        return {k: v for k, v in zip(dst_keys, outs)}

    return run
