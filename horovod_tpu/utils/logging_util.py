"""Rank-prefixed leveled logging (analog of reference horovod/common/logging.cc).

Controlled by HVDTPU_LOG_LEVEL / HOROVOD_LOG_LEVEL: trace/debug/info/warning/error.
"""

import logging
import sys

from . import envparse

_LEVELS = {
    "trace": logging.DEBUG - 5,
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "fatal": logging.CRITICAL,
}

logging.addLevelName(logging.DEBUG - 5, "TRACE")

_logger = None


def get_logger():
    global _logger
    if _logger is None:
        _logger = logging.getLogger("horovod_tpu")
        level_name = envparse.get_str(envparse.LOG_LEVEL, "warning").lower()
        _logger.setLevel(_LEVELS.get(level_name, logging.WARNING))
        if not _logger.handlers:
            handler = logging.StreamHandler(sys.stderr)
            handler.setFormatter(logging.Formatter(
                "[%(asctime)s] [%(levelname)s] [hvd-tpu] %(message)s"))
            _logger.addHandler(handler)
        _logger.propagate = False
    return _logger
