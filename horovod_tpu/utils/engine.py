"""Engine auto-selection for the example/bench scripts.

The reference's scripts always run on the accelerator because its
collectives live inside the framework's device kernels. Here the
examples have two engines — host-framework eager math with host-plane
collectives ('tf'/'torch'), or model math compiled onto the chip
('tpu') — and an unmodified user on a TPU-VM must land on the fast one
by default (round-4 review: on-chip must not be opt-in).
"""

import os


def resolve_engine(requested="auto", host_engine="tf",
                   env="HVDTPU_ENGINE"):
    """Resolve an example's --engine flag.

    'auto' (the default) picks 'tpu' iff the JAX runtime actually has a
    TPU, else ``host_engine``; the HVDTPU_ENGINE env var overrides auto
    (explicit opt-out without editing the command line). An explicit
    non-auto request always wins.
    """
    valid = {"tpu", host_engine}
    if requested != "auto":
        return requested
    forced = os.environ.get(env, "").strip().lower()
    if forced and forced != "auto":
        if forced not in valid:
            raise ValueError(
                f"{env}={forced!r} is not a valid engine; expected "
                f"one of {sorted(valid)} or 'auto'")
        return forced
    import jax
    return "tpu" if jax.default_backend() == "tpu" else host_engine


def default_keras_backend_to_jax():
    """Export KERAS_BACKEND=jax when a TPU is present and the user has
    not chosen a backend — keras model.fit then compiles onto the chip
    (set_data_parallel). Call BEFORE the first keras import."""
    if os.environ.get("KERAS_BACKEND"):
        return os.environ["KERAS_BACKEND"]
    import jax
    if jax.default_backend() == "tpu":
        os.environ["KERAS_BACKEND"] = "jax"
        return "jax"
    return None
