"""JAX API compatibility shims shared across modules."""

from jax import lax


def shard_map(f, mesh=None, in_specs=None, out_specs=None,
              check_vma=True):
    """``jax.shard_map`` across the API transition: newer releases
    export it at top level with ``check_vma=``; older ones live in
    ``jax.experimental.shard_map`` and spell the flag ``check_rep=``."""
    import jax
    top = getattr(jax, "shard_map", None)
    if top is not None:
        try:
            return top(f, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs, check_vma=check_vma)
        except TypeError:
            return top(f, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs, check_rep=check_vma)
    from jax.experimental.shard_map import shard_map as _sm
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check_vma)


def axis_size(axis_name):
    """Static size of a named mesh axis from inside shard_map/pmap.
    ``lax.axis_size`` only exists on newer jax; older releases expose
    the same number through the core axis-env frame."""
    fn = getattr(lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    import jax.core
    frame = jax.core.axis_frame(axis_name)
    # Older cores return the frame object, newer ones the bare size.
    return getattr(frame, "size", frame)


def pvary(x, axis_name):
    """Mark a value device-varying along ``axis_name`` (no-op if it
    already is). Papers over the lax.pcast / lax.pvary API transition."""
    try:
        return lax.pcast(x, axis_name, to="varying")
    except ValueError:
        return x  # already device-varying along axis_name
    except (AttributeError, TypeError):
        try:
            return lax.pvary(x, axis_name)
        except ValueError:
            return x
        except AttributeError:
            # Pre-varying-types jax (<= 0.4.x): no pcast/pvary and no
            # vma tracking to appease — identity is exactly right.
            return x
