"""JAX API compatibility shims shared across modules."""

import functools

import jax
from jax import lax


def shard_map(f, mesh=None, in_specs=None, out_specs=None,
              check_vma=True):
    """``jax.shard_map`` across the API transition: newer releases
    export it at top level with ``check_vma=``; older ones live in
    ``jax.experimental.shard_map`` and spell the flag ``check_rep=``."""
    import jax
    top = getattr(jax, "shard_map", None)
    if top is not None:
        try:
            return top(f, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs, check_vma=check_vma)
        except TypeError:
            return top(f, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs, check_rep=check_vma)
    from jax.experimental.shard_map import shard_map as _sm
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check_vma)


def tpu_compiler_params(dimension_semantics):
    """Mosaic compiler params across the ``pltpu.TPUCompilerParams`` →
    ``pltpu.CompilerParams`` rename; None when neither spelling exists
    (pallas_call accepts compiler_params=None)."""
    from jax.experimental.pallas import tpu as pltpu
    for name in ("CompilerParams", "TPUCompilerParams"):
        cls = getattr(pltpu, name, None)
        if cls is not None:
            try:
                return cls(dimension_semantics=dimension_semantics)
            except TypeError:
                continue
    return None


def axis_size(axis_name):
    """Static size of a named mesh axis from inside shard_map/pmap.
    ``lax.axis_size`` only exists on newer jax; older releases expose
    the same number through the core axis-env frame."""
    fn = getattr(lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    import jax.core
    frame = jax.core.axis_frame(axis_name)
    # Older cores return the frame object, newer ones the bare size.
    return getattr(frame, "size", frame)


def _vary_ladder(x, axis_name, pre_vma):
    """The pcast → pvary API ladder shared by :func:`pvary` and
    :func:`vary_replicated`; ``pre_vma`` supplies the behavior on
    releases that predate varying types entirely."""
    try:
        return lax.pcast(x, axis_name, to="varying")
    except ValueError:
        return x  # already device-varying along axis_name
    except (AttributeError, TypeError):
        pass
    try:
        return lax.pvary(x, axis_name)
    except ValueError:
        return x
    except AttributeError:
        return pre_vma(x, axis_name)


def vary_replicated(x, axis_name):
    """Declare a replicated shard_map input before differentiating a
    loss that uses it, so its cotangent is correctly reduced across
    ``axis_name``.

    On varying-types jax this is exactly ``pvary`` (the op the type
    system would auto-insert; transpose = psum). Pre-vma jax inserts
    nothing — ``jax.grad`` inside a shard_map body silently returns one
    shard's partial gradient for replicated inputs — so there this is a
    custom-vjp identity whose backward is ``lax.pmean``: on those
    releases psum/pmean themselves transpose to a psum of the
    replicated cotangent (an extra factor of the axis size), and the
    mean here cancels it, making the end-to-end gradient exact for any
    loss that crosses the reduction once (verified against dense
    oracles in tests/test_parallel.py and tests/test_long_context.py)."""
    return _vary_ladder(x, axis_name, _pre_vma_vary)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _pre_vma_vary(x, axis_name):
    return x


def _pre_vma_vary_fwd(x, axis_name):
    return x, None


def _pre_vma_vary_bwd(axis_name, _, g):
    return (lax.pmean(g, axis_name),)


_pre_vma_vary.defvjp(_pre_vma_vary_fwd, _pre_vma_vary_bwd)


def concrete_or_none(x):
    """The concrete value behind ``x``, or None when it is genuinely
    abstract. Unwraps bookkeeping tracers that carry their payload in
    ``.val`` — notably the check_rep RewriteTracer of older shard_map,
    which wraps even constants evaluated under
    ``jax.ensure_compile_time_eval()`` inside a shard_map body."""
    for _ in range(8):
        if not isinstance(x, jax.core.Tracer):
            return x
        x = getattr(x, "val", None)
        if x is None:
            return None
    return None


def inside_named_axis():
    """True when tracing under any named mesh axis (shard_map/pmap
    body). Newer jax exposes this through value types (``jax.typeof(x)
    .vma``); pre-varying-types releases only record it in the core axis
    env, which this reads."""
    try:
        from jax._src import core as _core
        return bool(_core.unsafe_get_axis_names())
    except (ImportError, AttributeError):
        return False


def pvary(x, axis_name):
    """Mark a value device-varying along ``axis_name`` (no-op if it
    already is). Papers over the lax.pcast / lax.pvary API transition.
    On pre-varying-types jax (<= 0.4.x) there is no vma tracking to
    appease, so identity is exactly right — callers who need the
    gradient contract of the auto-inserted pvary (psum'd cotangents for
    replicated inputs) use :func:`vary_replicated` instead."""
    return _vary_ladder(x, axis_name, lambda v, _axis: v)
