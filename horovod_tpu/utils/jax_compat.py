"""JAX API compatibility shims shared across modules."""

from jax import lax


def pvary(x, axis_name):
    """Mark a value device-varying along ``axis_name`` (no-op if it
    already is). Papers over the lax.pcast / lax.pvary API transition."""
    try:
        return lax.pcast(x, axis_name, to="varying")
    except ValueError:
        return x  # already device-varying along axis_name
    except (AttributeError, TypeError):
        try:
            return lax.pvary(x, axis_name)
        except ValueError:
            return x
