"""Locate the user frame that invoked a horovod_tpu API.

Used by the deterministic auto-namer (ops/collectives.py) and the
coordinator's submission diagnostics: both need "where in the *user's*
program did this collective come from", skipping every frame inside the
package itself. Kept allocation-light (``sys._getframe`` walk, no
traceback objects) so it is safe on the eager submission hot path.
"""

import os
import sys

# horovod_tpu/ package root; frames under it are framework internals.
_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def user_frame(skip=1):
    """First stack frame outside the horovod_tpu package.

    Returns ``(filename, lineno, qualname)``. Falls back to the
    outermost examined frame when the whole stack is internal (e.g. a
    framework-owned background thread).
    """
    f = sys._getframe(skip)
    last = f
    while f is not None:
        filename = f.f_code.co_filename
        if not filename.startswith(_PKG_ROOT):
            break
        last = f
        f = f.f_back
    frame = f if f is not None else last
    code = frame.f_code
    qualname = getattr(code, "co_qualname", code.co_name)
    return code.co_filename, frame.f_lineno, qualname


def format_user_frame(skip=2):
    """``file.py:lineno (qualname)`` for the calling user frame."""
    filename, lineno, qualname = user_frame(skip=skip)
    return f"{filename}:{lineno} ({qualname})"
