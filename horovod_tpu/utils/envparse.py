"""Environment-variable driven configuration.

The reference framework is configured exclusively through environment
variables (reference: horovod/common/common.h:107-141, utils/env_parser.cc).
We keep the same model: every runtime knob has an ``HVDTPU_*`` name and, for
drop-in compatibility with scripts written for the reference, the matching
``HOROVOD_*`` name is accepted as a fallback.
"""

import os

# HOROVOD_TPU_ sits between the native spelling and the reference
# fallback: it is the documented prefix for the TPU-only correctness
# knobs (HOROVOD_TPU_ORDER_CHECK, HOROVOD_TPU_STALL_CHECK_TIME) that
# have no reference analog.
_PREFIXES = ("HVDTPU_", "HOROVOD_TPU_", "HOROVOD_")


def get_env(name, default=None):
    """Look up knob ``name`` (without prefix) under HVDTPU_ then HOROVOD_."""
    for prefix in _PREFIXES:
        val = os.environ.get(prefix + name)
        if val is not None:
            return val
    return default


def _warn_malformed(name, val, default):
    import warnings
    warnings.warn(
        f"Environment knob {name}={val!r} is not a valid number; using "
        f"default {default!r}", stacklevel=3)


def get_int(name, default=0):
    val = get_env(name)
    if val is None or val == "":
        return default
    try:
        return int(val)
    except ValueError:
        _warn_malformed(name, val, default)
        return default


def get_float(name, default=0.0):
    val = get_env(name)
    if val is None or val == "":
        return default
    try:
        return float(val)
    except ValueError:
        _warn_malformed(name, val, default)
        return default


def get_bool(name, default=False):
    val = get_env(name)
    if val is None:
        return default
    return val.strip().lower() in ("1", "true", "yes", "on")


def get_str(name, default=""):
    val = get_env(name)
    return default if val is None else val


# --------------------------------------------------------------------------
# Knob registry
#
# Every *user-facing* configuration knob is declared through register()
# so the registry and docs/knobs.md can be cross-checked mechanically
# (hvd-lint --check-knobs / --self, rule HVD306): a knob added here
# without a docs row — or a docs row naming a knob nobody registered —
# is a finding. Raw `os.environ` reads of HVDTPU_*/HOROVOD_* names
# elsewhere in the package are a finding too (rule HVD304): they bypass
# both the prefix fallback above and this registry.
# --------------------------------------------------------------------------

#: name (without prefix) -> {"default": str, "doc": str}
KNOBS = {}


def register(name, default, doc):
    """Declare a user-facing knob; returns ``name`` so declarations
    double as the module-level constants call sites import."""
    KNOBS[name] = {"default": default, "doc": doc}
    return name


# -- runtime / coordination (subset of reference common.h:107-141) ---------
FUSION_THRESHOLD = register(
    "FUSION_THRESHOLD", "128 MiB",
    "Max bytes fused into one collective bucket (tensor fusion)")
CYCLE_TIME = register(
    "CYCLE_TIME", "1.0 ms", "Coordinator cycle period")
CACHE_CAPACITY = register(
    "CACHE_CAPACITY", "1024", "Native response-cache entries")
HIERARCHICAL_THRESHOLD = register(
    "HIERARCHICAL_THRESHOLD", "1 MiB",
    "Min buffer bytes before multi-host collectives take the two-level "
    "intra-host/cross-host path; 0 disables")
MIN_BUCKET = register(
    "MIN_BUCKET", "256",
    "Delegated (XLA) plane: floor for collective bucket sizes, elements")
CPU_OPERATIONS = register(
    "CPU_OPERATIONS", "tcp", "SPMD data plane: 'tcp' | 'xla'")
LOG_LEVEL = register(
    "LOG_LEVEL", "warning", "trace/debug/info/warning/error")
TIMELINE = register(
    "TIMELINE", "", "Write a chrome-trace JSON to this path")
TIMELINE_MARK_CYCLES = register(
    "TIMELINE_MARK_CYCLES", "off",
    "Instant event per negotiation cycle")

# -- stall / failure detection ---------------------------------------------
STALL_CHECK_DISABLE = register(
    "STALL_CHECK_DISABLE", "0", "Disable the stall inspector")
STALL_CHECK_TIME_SECONDS = register(
    "STALL_CHECK_TIME_SECONDS", "60",
    "Native-plane stall warning threshold (SPMD negotiation stalls)")
STALL_SHUTDOWN_TIME_SECONDS = register(
    "STALL_SHUTDOWN_TIME_SECONDS", "0",
    "Escalate a native-plane stall to job shutdown")
# Short spelling for the coordinator's stall warning (documented as
# HOROVOD_TPU_STALL_CHECK_TIME); falls back to STALL_CHECK_TIME_SECONDS.
STALL_CHECK_TIME = register(
    "STALL_CHECK_TIME", "60",
    "Coordinator stall warning: one periodic summary when submitted "
    "collectives stay in flight this long")

# -- correctness checking (hvd-lint; docs/lint.md) -------------------------
ORDER_CHECK = register(
    "ORDER_CHECK", "0",
    "Submission-order guard: hash the tensor-name submission stream, "
    "cross-check across ranks in SPMD mode (analysis/order_guard.py)")
ORDER_CHECK_RECORD = register(
    "ORDER_CHECK_RECORD", "",
    "Dump the recorded submission sequence as JSON on shutdown")
ORDER_CHECK_INTERVAL = register(
    "ORDER_CHECK_INTERVAL", "5", "Seconds between SPMD digest checks")
LEGACY_AUTO_NAMES = register(
    "LEGACY_AUTO_NAMES", "0",
    "Restore the process-global auto-name counter (<kind>.noname.<n>)")
SANITIZE = register(
    "SANITIZE", "0",
    "hvd-sanitize runtime layer: lock-order deadlock detection, "
    "blocking-call tripwire on collective-critical threads, shutdown "
    "thread-leak audit (analysis/sanitizer.py)")
LINT_BASELINE = register(
    "LINT_BASELINE", "",
    "Default --baseline file for hvd-lint: runs fail only on findings "
    "not recorded there (analysis/baseline.py; keys are rule x file x "
    "content-hash, so rebases don't resurface accepted findings)")

# -- autotune ---------------------------------------------------------------
AUTOTUNE = register(
    "AUTOTUNE", "0", "Enable the successive-halving parameter sweep")
AUTOTUNE_LOG = register(
    "AUTOTUNE_LOG", "", "CSV of per-round candidate scores")
AUTOTUNE_FUSION_CANDIDATES_MIB = register(
    "AUTOTUNE_FUSION_CANDIDATES_MIB", "0..128", "Fusion-threshold grid")
AUTOTUNE_CYCLE_CANDIDATES_MS = register(
    "AUTOTUNE_CYCLE_CANDIDATES_MS", "0.1..10", "Cycle-time grid")
AUTOTUNE_BUCKET_CANDIDATES = register(
    "AUTOTUNE_BUCKET_CANDIDATES", "256,4096,65536",
    "Delegated-plane bucket floors")
AUTOTUNE_WARMUP_CYCLES = register(
    "AUTOTUNE_WARMUP_CYCLES", "10", "Active cycles before scoring")
AUTOTUNE_CYCLES_PER_CANDIDATE = register(
    "AUTOTUNE_CYCLES_PER_CANDIDATE", "20",
    "Scoring budget of the final halving round")
AUTOTUNE_CACHE = register(
    "AUTOTUNE_CACHE", "",
    "Persistent warm-start store (JSON): converged winners per "
    "(model-signature, world-size, codec-availability) key, applied "
    "before the first scored window on repeat runs; inspect with "
    "hvd-autotune")
AUTOTUNE_SIGNATURE = register(
    "AUTOTUNE_SIGNATURE", "",
    "Explicit model-signature half of the warm-start key (default: "
    "hash of the collective names observed during warmup)")
AUTOTUNE_SCORE = register(
    "AUTOTUNE_SCORE", "auto",
    "Candidate score source: auto (trace-derived steps/sec when the "
    "flight ring shows step structure, bytes/sec otherwise), steps, "
    "or bytes")
AUTOTUNE_CONFIRM_CYCLES = register(
    "AUTOTUNE_CONFIRM_CYCLES", "10",
    "Scoring window of the warm-start re-validation after an "
    "elastic-version bump (baseline window + warm window)")
AUTOTUNE_BUCKET_BYTES_CANDIDATES_MIB = register(
    "AUTOTUNE_BUCKET_BYTES_CANDIDATES_MIB", "1,4,16,64",
    "Overlap-plane bucket-bytes grid (the overlap arm; only when "
    "HVDTPU_OVERLAP is on)")
AUTOTUNE_COMPRESSION_CANDIDATES = register(
    "AUTOTUNE_COMPRESSION_CANDIDATES", "",
    "Compression-codec grid for the compression arm (default: the "
    "current catch-all codec, none, int8, bf16 — availability-"
    "filtered; only when a pure catch-all policy is active)")
AUTOTUNE_COMPRESSION_THRESHOLD_CANDIDATES = register(
    "AUTOTUNE_COMPRESSION_THRESHOLD_CANDIDATES", "",
    "Compression element-threshold grid for the compression arm "
    "(default: the current threshold only)")
AUTOTUNE_ZERO_BUCKET_CANDIDATES_MIB = register(
    "AUTOTUNE_ZERO_BUCKET_CANDIDATES_MIB", "4,16,64",
    "ZeRO-leg bucket-bytes grid (the zero arm; single-controller "
    "mode with HVDTPU_ZERO on)")

# -- metrics plane (docs/metrics.md) ---------------------------------------
METRICS = register(
    "METRICS", "0", "Enable the telemetry registry + instrumentation")
METRICS_PUSH_INTERVAL = register(
    "METRICS_PUSH_INTERVAL", "5",
    "Seconds between per-rank snapshot pushes to the driver KV store")
METRICS_SNAPSHOT = register(
    "METRICS_SNAPSHOT", "BENCH_metrics.json",
    "Path where bench.py archives the run's telemetry snapshot")
METRICS_DUMP = register(
    "METRICS_DUMP", "", "Final JSON snapshot path written at shutdown")

# -- fault tolerance / chaos (docs/fault_tolerance.md) ---------------------
ELASTIC = register(
    "ELASTIC", "0",
    "Elastic worker mode: ranks come from the driver's rendezvous "
    "store, not launcher env (set by hvdrun --min-np/--max-np)")
ELASTIC_CHECK_INTERVAL = register(
    "ELASTIC_CHECK_INTERVAL", "0.2",
    "Seconds between elastic host-update checks at commit boundaries")
START_TIMEOUT = register(
    "START_TIMEOUT", "120",
    "Seconds workers wait at rendezvous for the full cohort "
    "(hvdrun --start-timeout)")
CHAOS = register(
    "CHAOS", "",
    "Fault-injection spec (point:action[:param]*; validate: hvd-chaos)")
CHAOS_LOG = register(
    "CHAOS_LOG", "", "Append one line per chaos firing to this file")
KV_RETRIES = register(
    "KV_RETRIES", "8", "KV client: max retries per call")
KV_BACKOFF = register(
    "KV_BACKOFF", "0.05", "KV client: initial backoff seconds")
KV_DEADLINE = register(
    "KV_DEADLINE", "30", "KV client: overall per-call deadline seconds")
HEARTBEAT_INTERVAL = register(
    "HEARTBEAT_INTERVAL", "2",
    "Worker: seconds between heartbeat lease renewals")
HEARTBEAT_TIMEOUT = register(
    "HEARTBEAT_TIMEOUT", "30",
    "Driver: fail a worker whose lease stops changing for this long")
SIGKILL_DEADLINE = register(
    "SIGKILL_DEADLINE", "10",
    "Driver: seconds between SIGTERM and SIGKILL on worker stop")
CONSISTENCY_CHECK = register(
    "CONSISTENCY_CHECK", "0",
    "Data-plane guardian: cross-rank metadata digest check "
    "(0 off, 1 every named collective, N>1 sampled)")
CONSISTENCY_TIMEOUT = register(
    "CONSISTENCY_TIMEOUT", "10",
    "Seconds the pre-dispatch check waits for peer digests")
COLLECTIVE_TIMEOUT = register(
    "COLLECTIVE_TIMEOUT", "0",
    "Stuck-collective watchdog: coordinated abort past this age; 0 off")
CHECKPOINT_KEEP = register(
    "CHECKPOINT_KEEP", "0",
    "Keep only the newest N step_<N> checkpoints; 0 keeps everything")

# -- control-plane HA (docs/fault_tolerance.md "Control-plane HA") ---------
DRIVER_JOURNAL = register(
    "DRIVER_JOURNAL", "",
    "Directory for the driver's append-only fsync'd control-plane "
    "journal (membership, blacklist, durable KV scopes) + periodic "
    "snapshot; enables the /journal standby-sync route. Unset: no "
    "journal I/O at all")
DRIVER_JOURNAL_SNAPSHOT_EVERY = register(
    "DRIVER_JOURNAL_SNAPSHOT_EVERY", "256",
    "Journal entries between full-state snapshots (journal rotation)")
DRIVER_STANDBY_ADDRS = register(
    "DRIVER_STANDBY_ADDRS", "",
    "Primary driver: comma-separated host:port standby endpoints, "
    "exported to workers as HVDTPU_RENDEZVOUS_ADDRS (primary first) "
    "so their KV client can fail over")
DRIVER_LEASE_INTERVAL = register(
    "DRIVER_LEASE_INTERVAL", "1",
    "Standby: seconds between /journal polls against the primary "
    "(each successful poll renews the primary's lease)")
DRIVER_LEASE_TIMEOUT = register(
    "DRIVER_LEASE_TIMEOUT", "10",
    "Standby: promote to primary after the primary has been "
    "unreachable this long (term bump + takeover)")
DRIVER_PORT = register(
    "DRIVER_PORT", "0",
    "Fixed KV-store listen port for the driver/standby (0 = "
    "ephemeral; standbys need a port workers can be told in advance)")

# -- gradient compression (docs/compression.md) ----------------------------
COMPRESSION = register(
    "COMPRESSION", "",
    "Gradient-compression policy: a codec (none/fp16/bf16/int8/fp8) or "
    "';'-separated '<name-glob>=<codec>' rules, first match wins")
COMPRESSION_THRESHOLD = register(
    "COMPRESSION_THRESHOLD", "1024",
    "Min elements before the compression policy applies to a tensor")
COMPRESSION_BLOCK = register(
    "COMPRESSION_BLOCK", "256",
    "Quantization block size: one f32 scale per this many values")
COMPRESSION_ERROR_FEEDBACK = register(
    "COMPRESSION_ERROR_FEEDBACK", "1",
    "Carry per-tensor quantization error into the next step's "
    "gradient (eager/fusion plane only)")

# -- sparse/embedding gradient plane (docs/sparse.md) ----------------------
SPARSE = register(
    "SPARSE", "",
    "Sparse-gradient path policy: auto/gather/dense or ';'-separated "
    "'<name-glob>=<mode>' rules, first match wins; auto picks "
    "allgather-of-slices vs densify-then-allreduce per tensor from the "
    "EMA-smoothed measured row density against a world-scaled "
    "crossover. Unset: every sparse gradient densifies (pre-plane "
    "behavior)")
SPARSE_THRESHOLD = register(
    "SPARSE_THRESHOLD", "1.0",
    "Scales the auto-mode crossover density "
    "(theta * 2*row_bytes / ((n-1)*(row_bytes+index_bytes)))")
SPARSE_EMA = register(
    "SPARSE_EMA", "0.8",
    "History weight of the per-name density EMA the auto policy "
    "smooths path decisions with (0 = instantaneous)")

# -- comm/compute overlap (docs/performance.md) ----------------------------
OVERLAP = register(
    "OVERLAP", "0",
    "Bucketed comm/compute overlap: per-bucket gradient collectives "
    "the scheduler can run under remaining backprop (in-jit axis "
    "path), priority-ordered async bucket dispatch (eager plane)")
BUCKET_BYTES = register(
    "BUCKET_BYTES", "16 MiB",
    "Payload bytes per gradient bucket on the overlap path")

# -- ZeRO-1 sharded weight update (docs/performance.md) ---------------------
ZERO = register(
    "ZERO", "0",
    "ZeRO-1 cross-replica sharded weight update: gradients "
    "reduce-scatter per bucket, each replica steps 1/n of a sharded "
    "optimizer state, updated shards allgather back (ops/zero.py)")
ZERO_BUCKET_BYTES = register(
    "ZERO_BUCKET_BYTES", "16 MiB",
    "Payload bytes per ZeRO fusion bucket (reduce-scatter/allgather "
    "legs); defaults to the overlap plane's bucket budget")
RESHARD_BUCKET_BYTES = register(
    "RESHARD_BUCKET_BYTES", "4 MiB",
    "Window budget of redistribution-planner collective steps "
    "(horovod_tpu/resharding/): no step stages more than this many "
    "bytes per rank, so an elastic reshard or train->serve transform "
    "never materializes a fully-replicated leaf")

# -- cross-rank tracing (docs/tracing.md) ----------------------------------
TRACE = register(
    "TRACE", "0",
    "Cross-rank trace plane: write a per-rank JSONL trace shard with "
    "correlated collective spans (name x occurrence x elastic version) "
    "and push it to the driver KV store for hvd-trace merge/report")
TRACE_DIR = register(
    "TRACE_DIR", "hvd_traces",
    "Directory for trace shards and flight-recorder postmortem dumps")
FLIGHT_RECORDER = register(
    "FLIGHT_RECORDER", "1",
    "Always-on bounded ring of recent span/negotiation events; dumped "
    "to a postmortem bundle on collective abort/mismatch (0 disables)")
FLIGHT_RECORDER_EVENTS = register(
    "FLIGHT_RECORDER_EVENTS", "4096",
    "Flight-recorder ring capacity, events per rank")

# -- static performance model (docs/lint.md HVD6xx) -------------------------
COSTMODEL = register(
    "COSTMODEL", "0",
    "Calibrated α–β cost model as an autotuner warm-start prior: the "
    "sweep probes candidates in the model's predicted order (pure "
    "prior — measured scores still decide; analysis/costmodel.py)")
COSTMODEL_TABLE = register(
    "COSTMODEL_TABLE", "",
    "Path to a calibrated cost-model table JSON (hvd-lint perf "
    "--calibrate --write-table); unset falls back to the built-in "
    "default table")
PERF_TARGET_RANKS = register(
    "PERF_TARGET_RANKS", "8,64,256,1024",
    "Cohort sizes hvd-lint perf probes for predicted scaling curves "
    "and the HVD603 scale-cliff rule")

# -- serving plane (docs/serving.md) ---------------------------------------
SERVING = register(
    "SERVING", "0",
    "Enable the serving plane: continuous-batching workers + router "
    "routes on the runner HTTP server (horovod_tpu/serving/)")
SERVING_MAX_BATCH_TOKENS = register(
    "SERVING_MAX_BATCH_TOKENS", "256",
    "Per-step scheduler budget: prefill tokens admitted plus one slot "
    "per running sequence may not exceed this")
SERVING_KV_PAGE_SIZE = register(
    "SERVING_KV_PAGE_SIZE", "16",
    "Token slots per KV-cache page")
SERVING_KV_PAGES = register(
    "SERVING_KV_PAGES", "256",
    "KV-cache pages in the per-host pool; admission keeps 1/16 of "
    "them free (the watermark reserve)")
SERVING_QUEUE_LIMIT = register(
    "SERVING_QUEUE_LIMIT", "64",
    "Bound of the per-host admission queue; past it submissions are "
    "rejected 429 + Retry-After (backpressure, never buffering)")
SERVING_SCALE_UP_DEPTH = register(
    "SERVING_SCALE_UP_DEPTH", "32",
    "Autoscaler: total cohort pressure (queued + running) that, "
    "sustained, triggers a serving scale-up")
SERVING_DRAIN_TIMEOUT = register(
    "SERVING_DRAIN_TIMEOUT", "30",
    "Seconds a draining cohort may take to finish in-flight "
    "sequences before scale-down proceeds anyway")
SERVING_SLO_P99 = register(
    "SERVING_SLO_P99", "0",
    "Serving p99 end-to-end latency SLO in seconds; a window-smoothed "
    "breach counts as scale-up pressure even with a shallow queue "
    "(0 = latency trigger off, depth-only autoscaling)")
SERVING_MIGRATE_RETRIES = register(
    "SERVING_MIGRATE_RETRIES", "3",
    "Retry attempts per KV-cache migration chunk POST before the "
    "transfer falls back to recompute")
SERVING_MIGRATE_DEADLINE = register(
    "SERVING_MIGRATE_DEADLINE", "5",
    "Seconds each migration chunk may spend retrying before the "
    "transfer falls back to recompute")
SERVING_MIGRATE_MAX_BYTES = register(
    "SERVING_MIGRATE_MAX_BYTES", "4194304",
    "Upper bound on one migrate_in POST body; a sequence's pages are "
    "chunked to stay under it (bounds target staging memory too)")

# -- fleet arbitration (docs/fault_tolerance.md "Fleet arbitration") -------
FLEET = register(
    "FLEET", "0",
    "Enable the chip-budget arbiter: one fixed slot budget split "
    "between the training and serving cohorts, rebalanced by "
    "journaled lease transfers (horovod_tpu/fleet/)")
FLEET_MIN_TRAIN_SLOTS = register(
    "FLEET_MIN_TRAIN_SLOTS", "1",
    "Floor the arbiter never shrinks the training cohort below")
FLEET_MIN_SERVE_SLOTS = register(
    "FLEET_MIN_SERVE_SLOTS", "1",
    "Floor the arbiter never shrinks the serving cohort below")
FLEET_WINDOW = register(
    "FLEET_WINDOW", "3",
    "Consecutive pressured observations before the arbiter proposes "
    "a train->serve lease transfer (smoothing against blips)")
FLEET_COOLDOWN = register(
    "FLEET_COOLDOWN", "30",
    "Seconds between arbiter transfers in either direction; bounds "
    "reshard churn from an oscillating load")
FLEET_EBB_IDLE_S = register(
    "FLEET_EBB_IDLE_S", "60",
    "Seconds the serving plane must stay unpressured before leased "
    "slots ebb back to training (drain-first, never dropping an "
    "accepted request)")
FLEET_TICK_S = register(
    "FLEET_TICK_S", "1",
    "Arbiter control-loop period when running threaded (FleetArbiter"
    ".start); each tick reads stats, steps leases, actuates")

# -- kernels ----------------------------------------------------------------
BRIDGE_FLASH = register(
    "BRIDGE_FLASH", "auto",
    "Route torch/TF bridge attention through the flash kernel: "
    "auto (TPU only) | always | never")
FLASH_DROPOUT = register(
    "FLASH_DROPOUT", "auto",
    "Flash-attention dropout strategy: auto | mask | prng")
FLASH_DROPOUT_MASK_LIMIT = register(
    "FLASH_DROPOUT_MASK_LIMIT", "128 MiB",
    "Max bernoulli keep-mask bytes before auto falls back to the "
    "on-chip prng path")

# --------------------------------------------------------------------------
# Launcher-set variables (analog of HOROVOD_RANK/SIZE/...; reference:
# horovod/runner/gloo_run.py:65-77). NOT registered: they are outputs
# the launcher exports for its workers, not knobs a user tunes — the
# registry/docs cross-check covers knobs only.
# --------------------------------------------------------------------------
RANK = "RANK"
SIZE = "SIZE"
LOCAL_RANK = "LOCAL_RANK"
LOCAL_SIZE = "LOCAL_SIZE"
CROSS_RANK = "CROSS_RANK"
CROSS_SIZE = "CROSS_SIZE"
PEERS = "PEERS"                                # "host:port,..." one per rank
RENDEZVOUS_ADDR = "RENDEZVOUS_ADDR"            # analog of HOROVOD_GLOO_RENDEZVOUS_ADDR
RENDEZVOUS_PORT = "RENDEZVOUS_PORT"
RENDEZVOUS_ADDRS = "RENDEZVOUS_ADDRS"          # ordered host:port failover list (HA)
CONTROLLER = "CONTROLLER"                      # 'tcp' | 'loopback'
WORKER_ID = "WORKER_ID"                        # elastic slot identity
ELASTIC_VERSION = "ELASTIC_VERSION"            # membership version joined
JOB_TOKEN = "JOB_TOKEN"                        # KV-store auth token
XLA_COORD = "XLA_COORD"                        # jax.distributed coordinator
