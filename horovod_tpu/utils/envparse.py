"""Environment-variable driven configuration.

The reference framework is configured exclusively through environment
variables (reference: horovod/common/common.h:107-141, utils/env_parser.cc).
We keep the same model: every runtime knob has an ``HVDTPU_*`` name and, for
drop-in compatibility with scripts written for the reference, the matching
``HOROVOD_*`` name is accepted as a fallback.
"""

import os

# HOROVOD_TPU_ sits between the native spelling and the reference
# fallback: it is the documented prefix for the TPU-only correctness
# knobs (HOROVOD_TPU_ORDER_CHECK, HOROVOD_TPU_STALL_CHECK_TIME) that
# have no reference analog.
_PREFIXES = ("HVDTPU_", "HOROVOD_TPU_", "HOROVOD_")


def get_env(name, default=None):
    """Look up knob ``name`` (without prefix) under HVDTPU_ then HOROVOD_."""
    for prefix in _PREFIXES:
        val = os.environ.get(prefix + name)
        if val is not None:
            return val
    return default


def _warn_malformed(name, val, default):
    import warnings
    warnings.warn(
        f"Environment knob {name}={val!r} is not a valid number; using "
        f"default {default!r}", stacklevel=3)


def get_int(name, default=0):
    val = get_env(name)
    if val is None or val == "":
        return default
    try:
        return int(val)
    except ValueError:
        _warn_malformed(name, val, default)
        return default


def get_float(name, default=0.0):
    val = get_env(name)
    if val is None or val == "":
        return default
    try:
        return float(val)
    except ValueError:
        _warn_malformed(name, val, default)
        return default


def get_bool(name, default=False):
    val = get_env(name)
    if val is None:
        return default
    return val.strip().lower() in ("1", "true", "yes", "on")


def get_str(name, default=""):
    val = get_env(name)
    return default if val is None else val


# Canonical knob names (subset of reference common.h:107-141, plus TPU-native ones)
FUSION_THRESHOLD = "FUSION_THRESHOLD"          # bytes, default 128 MiB
CYCLE_TIME = "CYCLE_TIME"                      # ms, default 1.0
CACHE_CAPACITY = "CACHE_CAPACITY"              # default 1024
TIMELINE = "TIMELINE"                          # path to chrome-trace json
TIMELINE_MARK_CYCLES = "TIMELINE_MARK_CYCLES"  # instant event per cycle
LOG_LEVEL = "LOG_LEVEL"
STALL_CHECK_DISABLE = "STALL_CHECK_DISABLE"
STALL_CHECK_TIME_SECONDS = "STALL_CHECK_TIME_SECONDS"
STALL_SHUTDOWN_TIME_SECONDS = "STALL_SHUTDOWN_TIME_SECONDS"
# Short spelling for the coordinator's stall warning (documented as
# HOROVOD_TPU_STALL_CHECK_TIME); falls back to STALL_CHECK_TIME_SECONDS.
STALL_CHECK_TIME = "STALL_CHECK_TIME"
# Submission-order guard (documented as HOROVOD_TPU_ORDER_CHECK): hash
# the per-cycle tensor-name submission sequence, cross-check across
# ranks in SPMD mode, record it otherwise (analysis/order_guard.py).
ORDER_CHECK = "ORDER_CHECK"
ORDER_CHECK_RECORD = "ORDER_CHECK_RECORD"      # JSON dump path for sequences
ORDER_CHECK_INTERVAL = "ORDER_CHECK_INTERVAL"  # seconds between cross-checks
# Restore the pre-lint process-global auto-name counter
# ("<kind>.noname.<n>"), which can diverge across ranks when submission
# interleaving differs (see ops/collectives.py _auto_name).
LEGACY_AUTO_NAMES = "LEGACY_AUTO_NAMES"
AUTOTUNE = "AUTOTUNE"
AUTOTUNE_LOG = "AUTOTUNE_LOG"
# Metrics plane (documented as HOROVOD_TPU_METRICS*): enable the
# telemetry registry + hot-path instrumentation; push per-rank snapshots
# to the driver KV store every PUSH_INTERVAL seconds; write a final JSON
# snapshot to DUMP on shutdown (see docs/metrics.md).
METRICS = "METRICS"
METRICS_PUSH_INTERVAL = "METRICS_PUSH_INTERVAL"
METRICS_DUMP = "METRICS_DUMP"
# Min buffer bytes before allreduce takes the two-level intra-host/
# cross-host path on multi-host jobs; 0 disables (reference knob analog:
# HOROVOD_HIERARCHICAL_ALLREDUCE).
HIERARCHICAL_THRESHOLD = "HIERARCHICAL_THRESHOLD"
ELASTIC = "ELASTIC"
# Fault injection + control-plane hardening (docs/fault_tolerance.md):
# chaos spec grammar in chaos/spec.py; KV client retry/backoff knobs;
# worker heartbeat lease + driver liveness timeout; SIGTERM->SIGKILL
# escalation deadline for workers that ignore a stop request.
CHAOS = "CHAOS"
CHAOS_LOG = "CHAOS_LOG"
KV_RETRIES = "KV_RETRIES"
KV_BACKOFF = "KV_BACKOFF"
KV_DEADLINE = "KV_DEADLINE"
HEARTBEAT_INTERVAL = "HEARTBEAT_INTERVAL"
HEARTBEAT_TIMEOUT = "HEARTBEAT_TIMEOUT"
SIGKILL_DEADLINE = "SIGKILL_DEADLINE"
# Data-plane guardian (guardian.py; docs/fault_tolerance.md):
# cross-rank metadata digests before dispatch (0 off, 1 every named
# collective, N>1 sampled every Nth submission), peer-digest wait
# deadline, and the stuck-collective watchdog's abort timeout
# (0 disables the abort; the stall warning alone remains).
CONSISTENCY_CHECK = "CONSISTENCY_CHECK"
CONSISTENCY_TIMEOUT = "CONSISTENCY_TIMEOUT"
COLLECTIVE_TIMEOUT = "COLLECTIVE_TIMEOUT"
# Crash-safe checkpoints (checkpoint.py): keep only the newest N
# step_<N> checkpoints after each save_step (0 = keep everything).
CHECKPOINT_KEEP = "CHECKPOINT_KEEP"

# Launcher-set topology env (analog of HOROVOD_RANK/SIZE/...; reference:
# horovod/runner/gloo_run.py:65-77)
RANK = "RANK"
SIZE = "SIZE"
LOCAL_RANK = "LOCAL_RANK"
LOCAL_SIZE = "LOCAL_SIZE"
CROSS_RANK = "CROSS_RANK"
CROSS_SIZE = "CROSS_SIZE"
PEERS = "PEERS"                                # "host:port,..." one per rank
RENDEZVOUS_ADDR = "RENDEZVOUS_ADDR"            # analog of HOROVOD_GLOO_RENDEZVOUS_ADDR
RENDEZVOUS_PORT = "RENDEZVOUS_PORT"
CONTROLLER = "CONTROLLER"                      # 'tcp' | 'loopback'
CPU_OPERATIONS = "CPU_OPERATIONS"              # 'tcp' | 'xla'
