"""horovod_tpu.tracing: the cross-rank trace plane (docs/tracing.md).

The per-rank Chrome timeline (timeline.py) cannot answer cluster
questions — which rank made this collective late, where the step's
critical path ran, what every rank was doing when the watchdog fired.
This package closes that gap:

- recorder.py — per-rank JSONL shards with correlated collective spans
  (name × occurrence × elastic version) + the always-on flight-recorder
  ring the guardian/chaos paths dump to a postmortem bundle;
- clock.py — NTP-style offset sampling against the driver's ``/clock``
  route, so cross-rank skew does not fabricate stragglers;
- merge.py — driver-side merge into ONE Perfetto/Chrome trace: a track
  per rank, flow arrows joining each collective's per-rank spans;
- analyze.py — per-step critical path, per-collective straggler
  attribution (feeding ``hvd_straggler_delay_seconds{rank}``), comm
  breakdown reconciled against ``hvd_overlap_fraction``;
- cli.py — the ``hvd-trace`` console entry (collect/merge/report/
  postmortem).

Cost contract: with ``HVDTPU_TRACE`` unset and
``HVDTPU_FLIGHT_RECORDER=0``, :func:`make_tracer` returns ``None`` and
instrumented sites pay one ``None`` check; :func:`trace_event` (the
module-level hook for code with no coordinator reference) is one global
read + ``None`` check. The flight recorder is ON by default — a bounded
deque append per collective — so every abort leaves forensics even in
jobs that never asked for tracing.
"""

import os

from ..utils import envparse
from ..utils.logging_util import get_logger
from .recorder import (  # noqa: F401  (re-exported API)
    DEFAULT_FLIGHT_EVENTS, FlightRecorder, ShardWriter, TRACE_SCOPE,
    Tracer, trace_scope,
)

# The process-active tracer: backends/guardian/chaos/elastic record
# through trace_event() without holding a coordinator reference.
_ACTIVE = None


def active():
    """The process-active Tracer, or None when tracing AND the flight
    recorder are both off."""
    return _ACTIVE


def trace_event(cat, name, **fields):
    """Record a generic event on the active tracer; one global read +
    None check when the plane is off."""
    tr = _ACTIVE
    if tr is not None:
        tr.event(cat, name, **fields)


def _set_active(tracer):
    """Test hook / factory internal."""
    global _ACTIVE
    _ACTIVE = tracer


def make_tracer(runtime):
    """Build the rank's Tracer from the env knobs, or None when both
    ``HVDTPU_TRACE`` and ``HVDTPU_FLIGHT_RECORDER`` are off (the
    coordinator then pays one attribute check per submit and nothing
    else). Registers the tracer as the process-active one."""
    trace_on = envparse.get_bool(envparse.TRACE)
    flight_n = (envparse.get_int(envparse.FLIGHT_RECORDER_EVENTS,
                                 DEFAULT_FLIGHT_EVENTS)
                if envparse.get_bool(envparse.FLIGHT_RECORDER, True)
                else 0)
    if not trace_on and flight_n <= 0:
        _set_active(None)
        return None

    rank = runtime.topology.rank
    # Unit-test runtime stubs may carry only a topology; the real
    # Runtime.size property resolves device count in single mode.
    size = getattr(runtime, "size", None)
    if size is None:
        size = getattr(runtime.topology, "size", 1)
    version = envparse.get_int(envparse.ELASTIC_VERSION, 0)
    flight = FlightRecorder(flight_n) if flight_n > 0 else None
    trace_dir = envparse.get_str(envparse.TRACE_DIR, "hvd_traces")

    # Clock alignment is sampled in BOTH modes when a rendezvous
    # exists: flight-only postmortems merge cross-rank too, and an
    # unaligned bundle reorders the forensics by exactly the skew.
    from ..runner import rendezvous as rdv
    push_cfg = rdv.rendezvous_config()
    off, rtt = 0.0, None
    if push_cfg is not None:
        from . import clock
        addr, port, token = push_cfg
        off, rtt = clock.estimate_offset(addr, port, token=token)

    writer = None
    if trace_on:
        try:
            os.makedirs(trace_dir, exist_ok=True)
            path = os.path.join(
                trace_dir,
                f"shard.r{rank}.p{os.getpid()}.v{version}.jsonl")
            import socket
            import time
            meta = {"e": "meta", "kind": "shard", "rank": rank,
                    "size": size, "ver": version, "pid": os.getpid(),
                    "off": off, "rtt": rtt,
                    "host": socket.gethostname(), "t": time.time()}
            writer = ShardWriter(path, meta)
        except OSError as exc:
            get_logger().warning(
                "tracing: cannot open trace shard under %s (%s); "
                "shard tracing disabled, flight recorder stays on",
                trace_dir, exc)
            writer = None

    tracer = Tracer(rank, size, version, shard_writer=writer,
                    flight=flight, trace_dir=trace_dir,
                    push_cfg=push_cfg, clock=(off, rtt))
    _set_active(tracer)
    return tracer
