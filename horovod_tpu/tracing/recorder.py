"""Per-rank trace recording: JSONL shard writer + flight-recorder ring.

The timeline (timeline.py) is strictly per-rank Chrome JSON — useless
for the questions that matter at scale (*which rank made this
collective late*, *what was every rank doing when the watchdog fired*).
This module records the cross-rank half of the answer on each rank:

- **Shard writer** (``HVDTPU_TRACE=1``): every collective submission and
  completion — plus negotiation/guardian/chaos/elastic events — as one
  compact JSON object per line, stamped with wall-clock time and a
  *correlation key* (tensor name × occurrence × elastic version) that is
  identical on every rank of a correct program. The driver-side merger
  (merge.py) joins shards on that key; clock skew is corrected with the
  offset sampled against the driver's ``/clock`` route (clock.py).
- **Flight recorder** (``HVDTPU_FLIGHT_RECORDER``, on by default): the
  same records into a bounded ring (``collections.deque(maxlen=N)``) —
  an append costs ~1 µs, so it stays on even when shard tracing is off.
  Guardian abort/mismatch paths dump the ring to a postmortem shard, so
  every aborted run leaves a mergeable "last N events, all ranks" trace.

Cost contract (telemetry-style): with ``HVDTPU_TRACE`` unset and
``HVDTPU_FLIGHT_RECORDER=0``, :func:`make_tracer` returns ``None`` and
every instrumented site pays one ``None`` check (guard-tested). With
only the flight recorder on, no file is opened and nothing is pushed.
"""

import collections
import json
import os
import queue
import socket
import threading
import time

from ..analysis import sanitizer
from ..telemetry import core as telemetry
from ..utils import envparse
from ..utils.logging_util import get_logger

DEFAULT_FLIGHT_EVENTS = 4096
# Shard/postmortem bytes pushed to the driver KV store are capped: the
# store is an in-memory dict in the launcher process, and one chatty
# rank must not evict the job's control plane. Truncation keeps the
# meta header + the newest lines (the tail is what postmortems need).
PUSH_CAP_BYTES = 4 * 1024 * 1024
#: KV scope prefix for pushed shards: trace.<elastic_version>
TRACE_SCOPE = "trace"


def trace_scope(version):
    return f"{TRACE_SCOPE}.{version}"


def _payload_bytes(entry):
    """Total payload bytes of a submission's arrays, or 0 when the
    shapes are unavailable (host objects, barrier entries). Feeds the
    ``b`` field the α–β cost-model calibration fits bandwidth from
    (analysis/costmodel.py); best-effort by design — a weird array
    type must never break the submit path."""
    try:
        import math
        total = 0
        for a in getattr(entry, "arrays", None) or ():
            total += int(math.prod(a.shape)) * int(a.dtype.itemsize)
        return total
    except Exception:  # noqa: BLE001 — tracing is never load-bearing
        return 0


class FlightRecorder:
    """Bounded ring of recent trace records. Append-only from the hot
    path; ``snapshot()`` copies under the GIL (deque iteration is
    atomic enough for a postmortem — a torn read loses one event, not
    the bundle)."""

    __slots__ = ("_ring",)

    def __init__(self, capacity):
        self._ring = collections.deque(maxlen=int(capacity))

    def append(self, rec):
        self._ring.append(rec)

    def snapshot(self):
        return list(self._ring)

    def __len__(self):
        return len(self._ring)


class ShardWriter:
    """Append-only JSONL writer for one rank's trace shard.

    Serialization + file I/O run on a dedicated writer thread (the
    timeline.py pattern): producers — framework threads submitting
    collectives, the coordinator cycle thread completing them — pay one
    ``queue.put`` and never touch the file, so trace writes cannot
    stall the data plane. The writer drains in batches and flushes once
    per drain; ``close()`` sends the sentinel and the WRITER closes the
    file (a timed-out join must not race its last writes)."""

    def __init__(self, path, meta):
        self.path = path
        self._queue = queue.Queue()
        self._queue.put(meta)
        self._thread = threading.Thread(
            target=self._writer, args=(open(path, "w"), self._queue),
            name="hvd-tpu-trace-writer", daemon=True)
        self._thread.start()

    def write(self, rec):
        self._queue.put(rec)

    @staticmethod
    def _writer(file, q):
        """Drain-then-flush loop, owned state only (file + queue):
        one blocking get, then everything queued meanwhile, one flush
        per drain. Ends (and closes the file) at the None sentinel."""
        try:
            stop = False
            while not stop:
                rec = q.get()
                if rec is None:
                    break
                lines = [json.dumps(rec, separators=(",", ":"),
                                    default=str)]
                while True:
                    try:
                        rec = q.get_nowait()
                    except queue.Empty:
                        break
                    if rec is None:
                        stop = True
                        break
                    lines.append(json.dumps(rec, separators=(",", ":"),
                                            default=str))
                file.write("\n".join(lines) + "\n")
                file.flush()
        finally:
            try:
                file.close()
            except OSError:
                pass

    def close(self):
        self._queue.put(None)
        self._thread.join(timeout=5)


class Tracer:
    """Facade the coordinator (and, via the module-level hook in
    ``tracing/__init__.py``, the backends/guardian/chaos/elastic) record
    through. Owns the occurrence counters that make correlation keys
    line up across ranks: each rank counts its own submissions per
    tensor name, which advance identically on every rank of a correct
    program (the same invariant the guardian's sampled slots rely on)."""

    def __init__(self, rank, size, version, shard_writer=None,
                 flight=None, trace_dir=None, push_cfg=None,
                 clock=(0.0, None)):
        self.rank = rank
        self.size = size
        self.version = version
        self.trace_dir = trace_dir
        # (offset_s, rtt_s) to the driver's clock (clock.py) — stamped
        # into EVERY meta header this tracer writes, postmortem dumps
        # included: an unaligned postmortem would reorder cross-rank
        # forensics by exactly the skew the plane exists to remove.
        self.clock_off, self.clock_rtt = clock
        self._writer = shard_writer
        self._flight = flight
        self._push_cfg = push_cfg  # (addr, port, token) or None
        self._occ = {}
        self._lock = sanitizer.make_lock("tracing.occ")
        self._log = get_logger()
        self._m_events = telemetry.counter(
            "hvd_trace_events_total",
            "Trace records emitted (shard and/or flight ring)")
        self._m_dumps = telemetry.counter(
            "hvd_flight_dumps_total",
            "Flight-recorder postmortem dumps")

    # -- hot path ----------------------------------------------------------
    def on_submit(self, entry):
        """Stamp ``entry.corr`` with this name's occurrence number and
        record the submission. Called from framework threads (the lock
        covers the counter only)."""
        name = entry.name or entry.kind
        with self._lock:
            occ = self._occ.get(name, 0) + 1
            self._occ[name] = occ
        entry.corr = occ
        rec = {"e": "sub", "t": time.time(), "n": name,
               "k": entry.kind, "o": occ}
        nbytes = _payload_bytes(entry)
        if nbytes:
            rec["b"] = nbytes
        self._emit(rec)

    def on_complete(self, entry, ok=True):
        name = entry.name or entry.kind
        rec = {"e": "fin", "t": time.time(), "n": name,
               "o": getattr(entry, "corr", None) or 0}
        if not ok:
            rec["err"] = 1
        self._emit(rec)

    def event(self, cat, name, **fields):
        """Generic record (negotiation, guardian, chaos, elastic...)."""
        rec = {"e": "ev", "t": time.time(), "cat": cat, "n": name}
        rec.update(fields)
        self._emit(rec)

    def _emit(self, rec):
        fl = self._flight
        if fl is not None:
            fl.append(rec)
        w = self._writer
        if w is not None:
            w.write(rec)
        self._m_events.inc()

    # -- postmortem / lifecycle --------------------------------------------
    def _meta(self, kind, **extra):
        meta = {"e": "meta", "t": time.time(), "kind": kind,
                "rank": self.rank, "size": self.size,
                "ver": self.version, "pid": os.getpid(),
                "host": socket.gethostname(),
                "off": self.clock_off, "rtt": self.clock_rtt}
        meta.update(extra)
        return meta

    def dump_postmortem(self, reason):
        """Write the flight ring to a postmortem shard next to the trace
        shards and push it to the driver KV store — called from the
        guardian abort/mismatch paths, so it must never raise."""
        if self._flight is None:
            return None
        try:
            events = self._flight.snapshot()
            d = self.trace_dir or "."
            os.makedirs(d, exist_ok=True)
            path = os.path.join(
                d, f"postmortem.r{self.rank}.p{os.getpid()}"
                   f".v{self.version}.jsonl")
            meta = self._meta("postmortem", reason=str(reason)[:500],
                              events=len(events))
            with open(path, "w") as f:
                f.write(json.dumps(meta, separators=(",", ":")) + "\n")
                for rec in events:
                    f.write(json.dumps(rec, separators=(",", ":"),
                                       default=str) + "\n")
            self._m_dumps.inc()
            self._push_file(path, f"postmortem.{self.rank}")
            self._log.warning(
                "tracing: flight-recorder postmortem (%d events, "
                "reason: %s) written to %s", len(events),
                str(reason)[:80], path)
            return path
        except Exception as exc:  # noqa: BLE001 — forensics, never fatal
            self._log.warning("tracing: postmortem dump failed: %s", exc)
            return None

    def _push_file(self, path, key):
        """Best-effort bounded push of a shard file to the driver KV
        store so ``hvd-trace collect`` works without shared storage."""
        if self._push_cfg is None:
            return
        try:
            with open(path, "rb") as f:
                data = f.read()
            if len(data) > PUSH_CAP_BYTES:
                # Keep the meta header line + the newest tail lines.
                head, _, rest = data.partition(b"\n")
                tail = rest[-PUSH_CAP_BYTES:]
                tail = tail[tail.index(b"\n") + 1:] if b"\n" in tail \
                    else tail
                data = head + b"\n" + tail
            from ..runner import http_client
            addr, port, token = self._push_cfg
            with sanitizer.allowed("trace shard push (bounded)"):
                http_client.put_kv(addr, port, trace_scope(self.version),
                                   key, data, token=token,
                                   retries=2, deadline=5.0)
        except Exception as exc:  # noqa: BLE001 — advisory plane
            self._log.warning("tracing: shard push %s failed: %s", key,
                              exc)

    def close(self):
        """Flush + close the shard and push it to the driver KV store
        (shutdown path; idempotent)."""
        w = self._writer
        if w is not None:
            w.close()
            self._writer = None
            self._push_file(w.path, f"shard.{self.rank}")
