"""``hvd-trace``: collect / merge / report / postmortem.

    hvd-trace collect --url http://driver:port --token T --out DIR
    hvd-trace merge DIR [shard...] --out trace.json
    hvd-trace report DIR [--json] [--metrics BENCH_metrics.json]
    hvd-trace postmortem DIR [--out bundle.json]

``collect`` pulls the shards every rank pushed to the launcher KV store
(``trace.<version>/shard.<rank>`` + ``postmortem.<rank>``); ``merge``
emits one Perfetto/Chrome-loadable trace with a track per rank and flow
arrows joining each collective's per-rank spans; ``report`` prints the
analyzer summary (per-step critical path, straggler attribution, comm
breakdown); ``postmortem`` merges only the flight-recorder dumps of an
aborted run and summarizes the final events. Full walkthrough:
docs/tracing.md.
"""

import argparse
import json
import sys
import urllib.parse

from . import analyze as analyze_mod
from . import merge as merge_mod


def _build_parser():
    parser = argparse.ArgumentParser(
        prog="hvd-trace",
        description="Cross-rank trace tooling: collect shards, merge "
                    "into one Perfetto trace, analyze stragglers and "
                    "critical paths, bundle postmortems.")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("collect", help="fetch pushed shards from the "
                                       "driver KV store")
    p.add_argument("--url", required=True,
                   help="driver KV store, e.g. http://10.0.0.2:41325")
    p.add_argument("--token", default="", help="job token")
    p.add_argument("--version", default="0",
                   help="elastic membership version (default 0)")
    p.add_argument("--out", default="hvd_traces",
                   help="output directory (default hvd_traces)")
    p.add_argument("--max-ranks", type=int, default=64)

    for name, hlp in (("merge", "merge shards into one Chrome trace"),
                      ("report", "print the analyzer summary"),
                      ("postmortem", "merge + summarize flight-"
                                     "recorder dumps")):
        p = sub.add_parser(name, help=hlp)
        p.add_argument("paths", nargs="+",
                       help="shard files and/or directories")
        p.add_argument("--no-align", action="store_true",
                       help="skip clock-offset alignment")
        if name == "merge":
            p.add_argument("--out", default="hvd_trace_merged.json")
        if name == "report":
            p.add_argument("--json", action="store_true",
                           help="emit the raw report dict")
            p.add_argument("--metrics", default="",
                           help="metrics snapshot JSON to reconcile "
                                "(hvd_overlap_fraction)")
        if name == "postmortem":
            p.add_argument("--out", default="",
                           help="also write the merged postmortem "
                                "trace JSON here")
    return parser


def _load(paths, kinds):
    shards = merge_mod.load_paths(paths, kinds=kinds)
    if not shards:
        print("hvd-trace: no shards found under "
              + ", ".join(paths), file=sys.stderr)
    return shards


def main(argv=None):
    args = _build_parser().parse_args(argv)

    if args.cmd == "collect":
        parsed = urllib.parse.urlparse(args.url)
        addr, port = parsed.hostname, parsed.port
        if not addr or not port:
            print(f"hvd-trace: bad --url {args.url!r} (expected "
                  "http://host:port)", file=sys.stderr)
            return 2
        written = merge_mod.collect_shards(
            addr, port, args.token, args.version, args.out,
            max_ranks=args.max_ranks)
        for path in written:
            print(path)
        print(f"hvd-trace: collected {len(written)} shard(s) into "
              f"{args.out}", file=sys.stderr)
        return 0 if written else 1

    align = not args.no_align
    kinds = ((merge_mod.POSTMORTEM_PREFIX,)
             if args.cmd == "postmortem"
             else (merge_mod.SHARD_PREFIX, merge_mod.POSTMORTEM_PREFIX)
             if args.cmd == "merge"
             else (merge_mod.SHARD_PREFIX,))
    shards = _load(args.paths, kinds)
    if not shards:
        return 1

    if args.cmd == "merge":
        trace = merge_mod.merge_shards(shards, align=align)
        with open(args.out, "w") as f:
            json.dump(trace, f)
        print(f"hvd-trace: wrote {len(trace['traceEvents'])} events "
              f"({len(shards)} shard(s)) to {args.out}",
              file=sys.stderr)
        print(args.out)
        return 0

    if args.cmd == "report":
        metrics = None
        if args.metrics:
            try:
                with open(args.metrics) as f:
                    metrics = json.load(f)
            except (OSError, ValueError) as exc:
                print(f"hvd-trace: cannot read --metrics: {exc}",
                      file=sys.stderr)
                return 2
        report = analyze_mod.analyze(shards, align=align,
                                     metrics=metrics)
        if args.json:
            print(json.dumps(report, indent=1, default=str))
        else:
            print(analyze_mod.render_report(report))
        return 0

    # postmortem
    report = analyze_mod.analyze(shards, align=align)
    print(f"postmortem bundle: {len(shards)} rank dump(s)")
    for s in shards:
        meta = s["meta"]
        print(f"  rank {meta.get('rank', '?')}: "
              f"{len(s['events'])} event(s), reason: "
              f"{meta.get('reason', '<none>')}")
        for rec in s["events"][-5:]:
            print(f"    {rec.get('t', 0):.6f} "
                  f"{rec.get('e')}/{rec.get('cat', '')} "
                  f"{rec.get('n', '')}")
    print()
    print(analyze_mod.render_report(report))
    if args.out:
        trace = merge_mod.merge_shards(shards, align=align)
        with open(args.out, "w") as f:
            json.dump(trace, f)
        print(f"\nmerged postmortem trace written to {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
