"""Trace analysis: straggler attribution, critical path, comm breakdown.

Works on the loaded shards (merge.load_paths), joining every rank's
span for the same correlation key. Three questions, per the paper
motivation (PAPERS.md 2004.13336: per-step comm/compute attribution;
2506.17615: collective-timing methodology):

- **Straggler attribution** — for each collective, the rank whose LATE
  SUBMIT gated the group (everyone else was parked in negotiation until
  it arrived). The skew is only called a straggler when it clears the
  clock-alignment uncertainty (± max residual RTT/2 across the ranks
  involved): sub-RTT skews are measurement noise, not stragglers.
- **Critical path** — collectives sharing an occurrence number form a
  *step* (a training step submits the same names once each). Within a
  step the critical path is walked backward from the last completion:
  repeatedly pick the collective covering the cursor with the earliest
  start, count uncovered gaps as compute.
- **Comm breakdown** — per rank: union of in-flight collective
  intervals over the shard's span, plus the fraction of collective time
  overlapped with other collectives (reconcilable against the live
  ``hvd_overlap_fraction`` gauge when a metrics snapshot is supplied).

``publish_metrics`` feeds ``hvd_straggler_delay_seconds{rank}`` so the
offline attribution and the telemetry plane tell one story.
"""

from . import merge as merge_mod

# Sub-millisecond skews are below what KV-round-trip alignment can
# resolve even on a quiet localhost; never call them stragglers.
MIN_SKEW_FLOOR_S = 1e-3


def _span_table(shards, align=True):
    """{(ver, name, occ): {rank: {"sub", "fin", "kind"}}} over all
    shards. The elastic version is part of the join key — it is part
    of the correlation key for exactly this reason: occurrence
    counters restart with every cohort, so joining v0's ``grad#1``
    with v1's would overwrite same-rank spans and "discover" a
    straggler delayed by the whole inter-cohort gap."""
    table = {}
    for s in shards:
        rank = s["meta"].get("rank", 0)
        ver = s["meta"].get("ver", 0)
        for (name, occ), sp in \
                merge_mod.collective_spans(s, align).items():
            if sp["sub"] is None:
                continue
            table.setdefault((ver, name, occ), {})[rank] = sp
    return table


def _skew_floor(shards):
    """Alignment uncertainty: half the worst min-RTT across shards (the
    NTP error bound), floored at MIN_SKEW_FLOOR_S."""
    rtts = [s["meta"].get("rtt") for s in shards
            if s["meta"].get("rtt") is not None]
    return max(MIN_SKEW_FLOOR_S, max(rtts) / 2.0 if rtts else 0.0)


def _critical_path(colls):
    """Backward interval walk over one step's collectives. Each item:
    {"name", "occ", "start", "end", ...}. Returns (chain, comm_s,
    gap_s): chain is last-to-first, gaps are uncovered (compute)
    time."""
    items = [c for c in colls if c["end"] is not None]
    if not items:
        return [], 0.0, 0.0
    t0 = min(c["start"] for c in items)
    cursor = max(c["end"] for c in items)
    chain, comm_s, gap_s = [], 0.0, 0.0
    remaining = sorted(items, key=lambda c: c["end"], reverse=True)
    while cursor > t0 + 1e-9 and remaining:
        covering = [c for c in remaining
                    if c["start"] < cursor - 1e-9
                    and c["end"] >= cursor - 1e-6]
        if not covering:
            # Gap: nothing in flight ending at the cursor — compute (or
            # idle) time on the critical path.
            nxt = max((c for c in remaining
                       if c["end"] < cursor - 1e-9),
                      key=lambda c: c["end"], default=None)
            if nxt is None:
                break
            gap_s += cursor - nxt["end"]
            cursor = nxt["end"]
            continue
        pick = min(covering, key=lambda c: c["start"])
        chain.append(pick)
        comm_s += cursor - pick["start"]
        cursor = pick["start"]
        remaining = [c for c in remaining if c is not pick]
    return chain, comm_s, gap_s


def analyze(shards, align=True, metrics=None):
    """Full report dict over loaded shards (see module docstring)."""
    shards = [s for s in shards if s["meta"] or s["events"]]
    table = _span_table(shards, align)
    floor = _skew_floor(shards)
    ranks = sorted({s["meta"].get("rank", 0) for s in shards})

    collectives = []
    straggler = {r: {"delay_s": 0.0, "gated": 0} for r in ranks}
    for (ver, name, occ), by_rank in sorted(
            table.items(),
            key=lambda kv: min(sp["sub"] for sp in kv[1].values())):
        subs = {r: sp["sub"] for r, sp in by_rank.items()}
        first_sub = min(subs.values())
        last_rank = max(subs, key=subs.get)
        skew = subs[last_rank] - first_sub
        fins = [sp["fin"] for sp in by_rank.values()
                if sp["fin"] is not None]
        end = max(fins) if fins else None
        rec = {
            "name": name, "occ": occ, "version": ver,
            "ranks": sorted(by_rank),
            "start": first_sub, "end": end,
            "dur_s": (end - first_sub) if end is not None else None,
            "submit_skew_s": skew,
            "straggler_rank": (last_rank
                               if len(by_rank) > 1 and skew > floor
                               else None),
        }
        collectives.append(rec)
        if rec["straggler_rank"] is not None:
            straggler[last_rank]["delay_s"] += skew
            straggler[last_rank]["gated"] += 1

    # Steps: collectives grouped by (version, occurrence) — a training
    # loop submits the same name set once per step, so occurrence ==
    # step index within a cohort; a loop of per-step-unique names
    # degenerates to one step, which the per-collective table still
    # covers.
    steps = []
    by_step = {}
    for c in collectives:
        by_step.setdefault((c["version"], c["occ"]), []).append(c)
    for (ver, occ) in sorted(by_step):
        colls = by_step[(ver, occ)]
        chain, comm_s, gap_s = _critical_path(colls)
        ends = [c["end"] for c in colls if c["end"] is not None]
        t0 = min(c["start"] for c in colls)
        t1 = max(ends) if ends else None
        crit = chain[0] if chain else None
        steps.append({
            "step": occ,
            "version": ver,
            "collectives": len(colls),
            "duration_s": (t1 - t0) if t1 is not None else None,
            "critical_path": [{"name": c["name"],
                               "straggler_rank": c["straggler_rank"],
                               "submit_skew_s": c["submit_skew_s"]}
                              for c in chain],
            "critical_comm_s": comm_s,
            "critical_gap_s": gap_s,
            "gating_collective": crit["name"] if crit else None,
            "gating_rank": crit["straggler_rank"] if crit else None,
        })

    # Per-rank comm window: union of in-flight intervals, ACCUMULATED
    # across a rank's shards (elastic cohorts are disjoint in time, so
    # their unions add).
    comm = {}
    for s in shards:
        rank = s["meta"].get("rank", 0)
        spans = sorted(
            ((sp["sub"], sp["fin"]) for sp in
             merge_mod.collective_spans(s, align).values()
             if sp["sub"] is not None and sp["fin"] is not None),
            key=lambda iv: iv[0])
        total = sum(b - a for a, b in spans)
        union, cur = 0.0, None
        for a, b in spans:
            if cur is None or a > cur[1]:
                if cur is not None:
                    union += cur[1] - cur[0]
                cur = [a, b]
            else:
                cur[1] = max(cur[1], b)
        if cur is not None:
            union += cur[1] - cur[0]
        ts = [merge_mod.aligned(r.get("t", 0.0), s["meta"], align)
              for r in s["events"]]
        wall = (max(ts) - min(ts)) if len(ts) > 1 else 0.0
        acc = comm.setdefault(rank, {"collective_s": 0.0,
                                     "inflight_union_s": 0.0,
                                     "wall_s": 0.0})
        acc["collective_s"] += total
        acc["inflight_union_s"] += union
        acc["wall_s"] += wall
    for acc in comm.values():
        acc["comm_fraction"] = (acc["inflight_union_s"] / acc["wall_s"]
                                if acc["wall_s"] > 0 else None)
        # Fraction of collective time overlapped with OTHER in-flight
        # collectives — the trace-side view of what the live
        # hvd_overlap_fraction gauge measures.
        acc["overlap_fraction"] = (
            1.0 - acc["inflight_union_s"] / acc["collective_s"]
            if acc["collective_s"] > 0 else None)

    report = {
        "ranks": ranks,
        "collectives": len(collectives),
        "collective_table": collectives,
        "steps": steps,
        "stragglers": straggler,
        "comm": comm,
        "skew_floor_s": floor,
        "clock": [{"rank": s["meta"].get("rank", 0),
                   "ver": s["meta"].get("ver", 0),
                   "off": s["meta"].get("off", 0.0),
                   "rtt": s["meta"].get("rtt")}
                  for s in shards],
    }
    if metrics is not None:
        report["metrics_overlap_fraction"] = _gauge_value(
            metrics, "hvd_overlap_fraction")
    return report


def _gauge_value(snapshot, family):
    fam = (snapshot.get("families") or {}).get(family)
    if not fam:
        return None
    samples = fam.get("samples") or []
    return samples[0].get("value") if samples else None


def publish_metrics(report):
    """Feed the straggler attribution into the telemetry plane
    (``hvd_straggler_delay_seconds{rank}``) — NULL no-op when metrics
    are off."""
    from ..telemetry import core as telemetry
    gauge = telemetry.gauge(
        "hvd_straggler_delay_seconds",
        "Cumulative submit-skew delay attributed to each rank by the "
        "trace analyzer (which rank's late submit gated collectives)",
        labelnames=("rank",))
    for rank, rec in report["stragglers"].items():
        gauge.labels(rank=str(rank)).set(rec["delay_s"])
    return gauge


def render_report(report):
    """Human-readable summary (the ``hvd-trace report`` output)."""
    lines = []
    ranks = report["ranks"]
    lines.append(f"ranks: {ranks}  collectives: "
                 f"{report['collectives']}  "
                 f"skew floor: {report['skew_floor_s'] * 1e3:.2f} ms")
    clock = report.get("clock") or []
    if clock:
        cl = "  ".join(
            f"r{v['rank']}v{v.get('ver', 0)}: "
            f"off={v['off'] * 1e3:+.2f}ms"
            + (f" rtt={v['rtt'] * 1e3:.2f}ms" if v.get("rtt") else "")
            for v in sorted(clock,
                            key=lambda v: (v.get("ver", 0), v["rank"])))
        lines.append(f"clock: {cl}")
    versions = {st.get("version", 0) for st in report["steps"]}
    lines.append("")
    lines.append("per-step critical path:")
    lines.append("  step  colls  duration_ms  comm_ms  compute_ms  "
                 "gating collective (straggler)")
    for st in report["steps"]:
        dur = st["duration_s"]
        gate = st["gating_collective"] or "-"
        if st["gating_rank"] is not None:
            gate += f" (rank {st['gating_rank']})"
        label = (str(st["step"]) if len(versions) <= 1
                 else f"v{st.get('version', 0)}:{st['step']}")
        lines.append(
            f"  {label:>4}  {st['collectives']:>5}  "
            f"{(dur * 1e3 if dur is not None else 0):>11.2f}  "
            f"{st['critical_comm_s'] * 1e3:>7.2f}  "
            f"{st['critical_gap_s'] * 1e3:>10.2f}  {gate}")
    lines.append("")
    lines.append("straggler attribution (submit skew above the floor):")
    lines.append("  rank  gated_collectives  total_delay_ms")
    for rank in ranks:
        rec = report["stragglers"][rank]
        lines.append(f"  {rank:>4}  {rec['gated']:>17}  "
                     f"{rec['delay_s'] * 1e3:>14.2f}")
    lines.append("")
    lines.append("comm breakdown:")
    lines.append("  rank  collective_ms  inflight_ms  comm_frac  "
                 "overlap_frac")
    for rank in ranks:
        c = report["comm"].get(rank)
        if c is None:
            continue

        def fmt(x, scale=1.0):
            return f"{x * scale:.2f}" if x is not None else "-"

        lines.append(
            f"  {rank:>4}  {fmt(c['collective_s'], 1e3):>13}  "
            f"{fmt(c['inflight_union_s'], 1e3):>11}  "
            f"{fmt(c['comm_fraction']):>9}  "
            f"{fmt(c['overlap_fraction']):>12}")
    if report.get("metrics_overlap_fraction") is not None:
        lines.append(f"  live hvd_overlap_fraction gauge: "
                     f"{report['metrics_overlap_fraction']:.3f}")
    return "\n".join(lines)
