"""Clock alignment: estimate this rank's offset to the driver's clock.

Cross-rank trace merging compares wall-clock stamps taken on different
hosts; tens of milliseconds of skew — common even under NTP — would
fabricate stragglers out of thin air (a 50 ms-fast clock makes every
submit look 50 ms late). The classic NTP-style exchange against the
launcher's KV server fixes the frame:

    t0 = local clock            # request leaves
    ts = GET /clock             # server stamps its wall clock
    t1 = local clock            # response arrives

Assuming symmetric network delay, the server stamped at the midpoint,
so ``offset = (t0 + t1) / 2 - ts`` (positive = this rank's clock runs
ahead of the driver's) with uncertainty bounded by the round trip.
Sampling a few times and keeping the **minimum-RTT** sample rejects
queueing noise the way NTP's clock filter does. Every shard records its
offset in the meta header; the merger subtracts it, putting all ranks
on the driver's clock. The residual error (± min-RTT/2) is recorded too
so the analyzer can refuse to call sub-RTT skews "stragglers".
"""

import time

DEFAULT_SAMPLES = 5
_SAMPLE_TIMEOUT_S = 2.0


def server_time(addr, port, token="", timeout=_SAMPLE_TIMEOUT_S):
    """The driver KV server's wall clock (``GET /clock``, token-gated
    like every other route). Raises on transport trouble or an old
    server without the route — callers degrade to offset 0."""
    from ..runner import http_client
    url = f"http://{addr}:{port}/clock"
    with http_client._request("GET", url, token=token,
                              timeout=timeout) as resp:
        return float(resp.read())


def estimate_offset(addr, port, token="", samples=DEFAULT_SAMPLES):
    """``(offset_s, rtt_s)`` of the minimum-RTT sample, or ``(0.0,
    None)`` when the server is unreachable / pre-/clock. ``offset_s``
    is local-minus-server: subtract it from local stamps to land on the
    driver's clock."""
    best = None
    for _ in range(max(1, samples)):
        t0 = time.time()
        try:
            ts = server_time(addr, port, token=token)
        except Exception:  # noqa: BLE001 — alignment is best-effort
            # A transport failure is not transient queueing noise: an
            # unreachable /clock (firewalled driver, pre-route server)
            # would fail all remaining samples too, each burning the
            # full timeout on init's critical path. One strike ends it.
            break
        t1 = time.time()
        rtt = t1 - t0
        offset = (t0 + t1) / 2.0 - ts
        if best is None or rtt < best[1]:
            best = (offset, rtt)
    return best if best is not None else (0.0, None)
