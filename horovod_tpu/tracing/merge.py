"""Driver-side merge: per-rank JSONL shards → one Perfetto/Chrome trace.

Each rank's shard (recorder.py) carries wall-clock stamps and a meta
header with that rank's clock offset to the driver (clock.py). The
merger aligns every stamp onto the driver's clock, gives each rank its
own track (``pid`` = rank, with a ``process_name`` metadata event), and
emits:

- one complete-span (``ph: "X"``) per collective per rank, from submit
  to completion, laid out on greedily-allocated lanes so overlapping
  in-flight collectives render side by side instead of corrupting the
  nesting;
- **flow arrows** (``ph: "s"``/``"f"``) connecting every rank's span for
  the same correlation key (name × occurrence × elastic version) — the
  synthetic "collective" arrows that make cross-rank gating visible in
  the Perfetto UI;
- instant events for everything else (negotiation, guardian, chaos,
  elastic, flight-recorder records).

The output is a standard ``{"traceEvents": [...]}`` JSON object that
chrome://tracing and https://ui.perfetto.dev load directly.
"""

import json
import os
import zlib

SHARD_PREFIX = "shard."
POSTMORTEM_PREFIX = "postmortem."
# A submission with no completion record (aborted run, truncated shard)
# still gets a span: this floor keeps it visible in the UI.
_MIN_DUR_US = 1.0


def corr_id(name, occ, version):
    """The cross-rank correlation key, rendered."""
    return f"{name}#{occ}@v{version}"


def load_shard(path):
    """``{"meta": {...}, "events": [...], "path": ...}``. Malformed
    lines are skipped (a rank killed mid-write leaves a torn tail)."""
    meta, events = None, []
    with open(path, encoding="utf-8", errors="replace") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if rec.get("e") == "meta" and meta is None:
                meta = rec
            else:
                events.append(rec)
    return {"meta": meta or {}, "events": events, "path": path}


def shard_paths(paths, kinds=(SHARD_PREFIX, POSTMORTEM_PREFIX)):
    """Expand files/directories into shard file paths (sorted)."""
    out = []
    for p in paths:
        if os.path.isdir(p):
            for name in sorted(os.listdir(p)):
                if name.startswith(tuple(kinds)) \
                        and name.endswith(".jsonl"):
                    out.append(os.path.join(p, name))
        else:
            out.append(p)
    return out


def load_paths(paths, kinds=(SHARD_PREFIX, POSTMORTEM_PREFIX)):
    """Load every shard under ``paths``. An unreadable file (crashed
    rank holding the handle, permissions, mid-collection truncation to
    a directory...) is warned about and SKIPPED — one bad shard must
    not kill a merge or a cost-model calibration over the survivors;
    torn tails inside a readable shard are already handled line-wise
    by :func:`load_shard`."""
    from ..utils.logging_util import get_logger
    out = []
    for p in shard_paths(paths, kinds):
        try:
            out.append(load_shard(p))
        except OSError as exc:
            get_logger().warning(
                "hvd-trace: skipping unreadable shard %s (%s)", p, exc)
    return out


def bundle_by_rank(shards, version=None):
    """Group loaded shards into one record per rank for forensic
    consumers (``hvd-lint explain``, report tooling): keep only the
    newest elastic ``version`` present (or the explicit one), and when
    a rank left several dumps for that version (respawns share a
    directory), keep the newest by meta timestamp. Returns
    ``(version, {rank: shard})``."""
    if not shards:
        return None, {}
    if version is None:
        version = max(s["meta"].get("ver", 0) or 0 for s in shards)
    by_rank = {}
    for s in shards:
        meta = s["meta"]
        if (meta.get("ver", 0) or 0) != version:
            continue
        rank = meta.get("rank")
        if rank is None:
            continue
        prev = by_rank.get(rank)
        if prev is None or (meta.get("t", 0)
                            > prev["meta"].get("t", 0)):
            by_rank[rank] = s
    return version, by_rank


def aligned(t, meta, align=True):
    """A local stamp moved onto the driver's clock."""
    return t - meta.get("off", 0.0) if align else t


def collective_spans(shard, align=True):
    """Pair sub/fin records: ``{(name, occ): {"sub": t, "fin": t|None,
    "kind": ..., "err": bool}}`` with aligned times."""
    meta = shard["meta"]
    spans = {}
    for rec in shard["events"]:
        e = rec.get("e")
        if e not in ("sub", "fin"):
            continue
        key = (rec.get("n"), rec.get("o", 0))
        t = aligned(rec.get("t", 0.0), meta, align)
        s = spans.setdefault(key, {"sub": None, "fin": None,
                                   "kind": rec.get("k"), "err": False,
                                   "bytes": None})
        if e == "sub":
            s["sub"] = t
            if rec.get("b"):
                s["bytes"] = rec["b"]
        else:
            s["fin"] = t
            s["err"] = bool(rec.get("err"))
    return spans


def _alloc_lane(lanes, start, end):
    """Greedy lane allocation so overlapping spans get distinct tids."""
    for i, busy_until in enumerate(lanes):
        if start >= busy_until - 1e-9:
            lanes[i] = end
            return i
    lanes.append(end)
    return len(lanes) - 1


def merge_shards(shards, align=True):
    """Merge loaded shards into one Chrome/Perfetto trace dict."""
    shards = [s for s in shards if s["events"] or s["meta"]]
    if not shards:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    base = None
    for s in shards:
        for rec in s["events"]:
            t = aligned(rec.get("t", 0.0), s["meta"], align)
            base = t if base is None else min(base, t)
    if base is None:
        base = 0.0

    def us(t):
        return (t - base) * 1e6

    events = []
    # corr -> [(rank, start_us, lane)] for the flow pass.
    flow_sites = {}
    for s in shards:
        meta = s["meta"]
        rank = meta.get("rank", 0)
        ver = meta.get("ver", 0)
        label = f"rank {rank}"
        if meta.get("kind") == "postmortem":
            label += " (postmortem)"
        events.append({"ph": "M", "pid": rank, "tid": 0,
                       "name": "process_name",
                       "args": {"name": label}})
        spans = collective_spans(s, align)
        lanes = []
        last_t = max((aligned(r.get("t", 0.0), meta, align)
                      for r in s["events"]), default=base)
        for (name, occ), sp in sorted(
                spans.items(), key=lambda kv: kv[1]["sub"] or 0.0):
            if sp["sub"] is None:
                continue
            start = us(sp["sub"])
            end = us(sp["fin"] if sp["fin"] is not None else last_t)
            dur = max(end - start, _MIN_DUR_US)
            lane = _alloc_lane(lanes, start, start + dur)
            cid = corr_id(name, occ, ver)
            args = {"corr": cid, "rank": rank,
                    "kind": sp["kind"] or "collective"}
            if sp["fin"] is None:
                args["unfinished"] = True
            if sp["err"]:
                args["error"] = True
            events.append({"ph": "X", "pid": rank, "tid": lane,
                           "ts": round(start, 3), "dur": round(dur, 3),
                           "cat": "collective", "name": name,
                           "args": args})
            flow_sites.setdefault(cid, []).append((rank, start, lane))
        # Non-collective records as instants on a dedicated lane.
        ev_tid = len(lanes) or 1
        for rec in s["events"]:
            if rec.get("e") != "ev":
                continue
            t = us(aligned(rec.get("t", 0.0), meta, align))
            args = {k: v for k, v in rec.items()
                    if k not in ("e", "t", "cat", "n")}
            events.append({"ph": "i", "pid": rank, "tid": ev_tid,
                           "ts": round(t, 3), "s": "t",
                           "cat": rec.get("cat", "event"),
                           "name": f"{rec.get('cat', 'ev')}:"
                                   f"{rec.get('n', '')}",
                           "args": args})

    # Flow arrows: one per correlation key spanning >= 2 ranks, from the
    # earliest-submitting rank to every other — submit-order gating made
    # visible ("which rank's late submit gated the group").
    for cid, sites in sorted(flow_sites.items()):
        if len(sites) < 2:
            continue
        fid = zlib.crc32(cid.encode())
        sites = sorted(sites, key=lambda site: site[1])
        first_rank, first_ts, first_lane = sites[0]
        events.append({"ph": "s", "id": fid, "pid": first_rank,
                       "tid": first_lane, "ts": round(first_ts, 3),
                       "cat": "collective", "name": cid})
        for rank, ts, lane in sites[1:]:
            events.append({"ph": "f", "bp": "e", "id": fid, "pid": rank,
                           "tid": lane, "ts": round(ts, 3),
                           "cat": "collective", "name": cid})
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"tool": "hvd-trace",
                          "ranks": sorted({s["meta"].get("rank", 0)
                                           for s in shards}),
                          "aligned": bool(align)}}


def collect_shards(addr, port, token, version, out_dir, max_ranks=64,
                   kinds=("shard", "postmortem")):
    """Fetch pushed shards from the driver KV store into ``out_dir``;
    returns the written paths. EVERY slot under ``max_ranks`` is
    probed for every kind — shard pushes are explicitly best-effort on
    the worker side, so one rank's failed push must not hide the
    shards of every higher rank. A gap against the world size the
    collected metas declare is warned about, so a partial merge never
    masquerades as full coverage."""
    from ..runner import http_client
    from ..utils.logging_util import get_logger
    os.makedirs(out_dir, exist_ok=True)
    scope = f"trace.{version}"
    written = []
    shard_ranks, declared_size = [], 0
    for kind in kinds:
        for rank in range(max_ranks):
            raw = http_client.get_kv(addr, port, scope,
                                     f"{kind}.{rank}", token=token,
                                     retries=1, deadline=5.0)
            if raw is None:
                continue
            path = os.path.join(out_dir,
                                f"{kind}.r{rank}.v{version}.jsonl")
            with open(path, "wb") as f:
                f.write(raw if isinstance(raw, bytes) else raw.encode())
            written.append(path)
            if kind == "shard":
                shard_ranks.append(rank)
                try:
                    head = raw.split(b"\n", 1)[0]
                    declared_size = max(declared_size,
                                        int(json.loads(head)
                                            .get("size", 0)))
                except (ValueError, AttributeError):
                    pass
    if shard_ranks:
        expected = range(max(declared_size, max(shard_ranks) + 1))
        missing = sorted(set(expected) - set(shard_ranks))
        if missing:
            get_logger().warning(
                "hvd-trace collect: no pushed shard for rank(s) %s "
                "(world size %d per the collected metas) — the merge "
                "will cover a PARTIAL rank set", missing,
                len(expected))
    return written
