"""Eager-collective data-plane backends.

The reference dispatches each collective to a priority-ordered chain of
backends (NCCL/MPI/Gloo/oneCCL, reference: horovod/common/ops/
operation_manager.cc:42-80). On TPU there is one first-class data plane —
XLA collectives over ICI — plus a TCP fallback for CPU-only SPMD jobs (the
gloo analog) and a loopback for world-size-1:

- ``XlaSingleBackend``: single-controller mode; every op is a jitted XLA
  program over the replica mesh (see xla_backend.py).
- ``TcpBackend``: N-process CPU data plane over sockets, backed by the
  native C++ runtime (see tcp_backend.py).
- ``LoopbackBackend``: world size 1.
"""

from abc import ABC, abstractmethod


class Backend(ABC):
    """Interface executed by the coordinator's background cycle.

    Grouped/fused entry points take *lists* of arrays so one call can carry a
    whole fusion bucket (the analog of the reference's fused response,
    reference: horovod/common/controller.cc:808 FuseResponses).
    """

    name = "abstract"

    @abstractmethod
    def allreduce(self, arrays, op, process_set, prescale=None,
                  postscale=None):
        """Reduce each array across ranks. Returns list of results."""

    @abstractmethod
    def allgather(self, arrays, process_set):
        """Concatenate each array across ranks along dim 0."""

    @abstractmethod
    def broadcast(self, arrays, root_rank, process_set):
        """Every rank receives root_rank's value."""

    @abstractmethod
    def alltoall(self, array, splits, process_set):
        """Scatter slices of dim 0 to every rank; returns (output, recv_splits)."""

    @abstractmethod
    def reducescatter(self, arrays, op, process_set):
        """Reduce then scatter dim-0 chunks across ranks."""

    @abstractmethod
    def barrier(self, process_set):
        """Block until every rank arrives (reference: EnqueueBarrier,
        horovod/common/operations.cc:1763)."""

    def register_process_set(self, process_set):
        pass

    def remove_process_set(self, process_set):
        pass

    def abort_inflight(self, exc):
        """Fail every asynchronously in-flight entry with ``exc`` — the
        stuck-collective watchdog's coordinated-abort hook
        (coordinator._abort_inflight). Synchronous backends hold no
        async state, so the default is a no-op; the native planes
        (tcp/xla-global) fail their pending negotiations."""

    def close(self):
        pass


class LoopbackBackend(Backend):
    """World-size-1 SPMD backend: collectives are identities (after scaling)."""

    name = "loopback"

    def allreduce(self, arrays, op, process_set, prescale=None,
                  postscale=None):
        import jax.numpy as jnp
        outs = []
        for a in arrays:
            x = jnp.asarray(a)
            if prescale is not None and prescale != 1.0:
                x = x * jnp.asarray(prescale, dtype=x.dtype)
            if postscale is not None and postscale != 1.0:
                x = x * jnp.asarray(postscale, dtype=x.dtype)
            outs.append(x)
        return outs

    def allgather(self, arrays, process_set):
        import jax.numpy as jnp
        return [jnp.asarray(a) for a in arrays]

    def broadcast(self, arrays, root_rank, process_set):
        import jax.numpy as jnp
        if root_rank != 0:
            raise ValueError(f"root_rank {root_rank} out of range for size 1")
        return [jnp.asarray(a) for a in arrays]

    def alltoall(self, array, splits, process_set):
        import jax.numpy as jnp
        import numpy as np
        x = jnp.asarray(array)
        if splits is None:
            splits = np.array([x.shape[0]], dtype=np.int32)
        return x, np.asarray(splits, dtype=np.int32)

    def reducescatter(self, arrays, op, process_set):
        import jax.numpy as jnp
        return [jnp.asarray(a) for a in arrays]

    def barrier(self, process_set):
        pass


def make_spmd_backend(topology):
    """Pick the SPMD data plane like the reference picks its op chain
    (reference: horovod/common/operations.cc:144-253 CreateOperationManager).
    """
    from ..utils import envparse
    # elastic + xla-global is supported via exit-restart resets: on a
    # membership change the worker persists its commit and exits with
    # elastic.RESTART_EXIT_CODE, the driver respawns the slot fresh, and
    # the new process re-forms jax.distributed at the new world size
    # (jax.distributed cannot re-initialize in-process — see
    # elastic.py "Exit-restart reset").
    cpu_ops = envparse.get_str(envparse.CPU_OPERATIONS, "").lower()
    if topology.size == 1:
        return LoopbackBackend()
    if not envparse.get_str(envparse.PEERS, ""):
        # Launcher-spawned worker: discover peers through the driver's KV
        # rendezvous (reference: gloo_context.cc:150-228 bootstrapping from
        # the driver's HTTP store) instead of a hand-built peer list.
        from ..runner import rendezvous
        if rendezvous.rendezvous_config() is not None:
            rendezvous.bootstrap_peers(topology)
    if cpu_ops in ("xla", "xla-global", "nccl"):
        # Compiled data plane over the jax.distributed global mesh; the
        # TCP core stays as control plane ("nccl" accepted for scripts
        # written against the reference's HOROVOD_CPU_OPERATIONS knob).
        from .xla_global import XlaGlobalBackend
        return XlaGlobalBackend(topology)
    try:
        from .tcp_backend import TcpBackend
    except ImportError as e:
        raise NotImplementedError(
            "Multi-process SPMD mode requires the TCP data-plane backend "
            f"(horovod_tpu/backend/tcp_backend.py): {e}") from e
    return TcpBackend(topology)
