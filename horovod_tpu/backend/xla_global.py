"""Multi-host XLA data plane: native control plane + compiled collectives.

The SPMD analog of the reference's NCCL-executes/MPI-negotiates split
(reference: horovod/common/ops/nccl_operations.cc:80-119 — the NCCL data
plane bootstraps its communicator through the controller and executes the
negotiated responses; the controller only orders and fuses). Here:

- The native TCP core (csrc/) keeps the CONTROL plane: named-tensor
  negotiation, fusion ordering, response cache, stall detection —
  byte-identical semantics to the pure-TCP backend.
- Agreed data responses are *delegated* (CoreOptions.delegate_data_ops)
  and executed as jitted XLA collectives over a global device mesh built
  with ``jax.distributed`` — psum/all_gather over ICI/DCN instead of
  host-socket rings. On a TPU pod this is where tensor bytes belong; the
  TCP plane remains the CPU fallback (gloo analog) and still carries
  alltoall (uneven splits), barrier, and join.

The data-plane mesh uses ONE device per process (Horovod semantics: one
rank contributes one tensor); the user's compiled training step sharding
owns the remaining chips. Select with ``HVDTPU_CPU_OPERATIONS=xla``.
"""

import numpy as np

from .tcp_backend import TcpBackend
from .. import native
from ..exceptions import HorovodInternalError
from ..utils import envparse
from ..utils.jax_compat import shard_map as _shard_map
from ..utils.logging_util import get_logger

# Native wire enums (csrc/common.h).
_T_ALLREDUCE, _T_ALLGATHER, _T_BROADCAST = 0, 1, 2
_T_ALLTOALL, _T_REDUCESCATTER = 3, 4
_RED_SUM, _RED_MIN, _RED_MAX, _RED_PROD = 0, 1, 2, 3

JAXDIST_SCOPE = "jaxdist"


def _enum_to_np():
    return {v: k for k, v in native._dtype_table().items()}


def _bucket(n, min_b=256):
    """Round element counts up to the next power of two (min ``min_b``) so
    the jitted-collective cache sees a bounded set of shapes instead of
    one compilation per fusion-bucket size. The minimum is the autotuned
    delegated-plane knob (autotune.py): raising it turns a flood of small
    collectives into fewer, fuller launches."""
    b = min_b
    while b < n:
        b <<= 1
    return b


def _pad(flat, to_n, op=_RED_SUM):
    if flat.shape[0] == to_n:
        return flat
    out = np.full(to_n, XlaGlobalBackend._identity(op, flat.dtype),
                  dtype=flat.dtype)
    out[:flat.shape[0]] = flat
    return out


def _free_port():
    import socket
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def init_jax_distributed(topology):
    """Initialize the JAX distributed runtime so every process sees the
    global device set. The coordinator address comes from
    ``HVDTPU_XLA_COORD`` or is brokered through the launcher's KV store
    (rank 0 publishes; the analog of the NCCL unique-id broadcast through
    the controller, nccl_operations.cc:102-119)."""
    import jax
    try:
        if jax.distributed.is_initialized():
            # Fresh world pre-initialized by user code: reuse it. (A
            # stale post-reset world cannot reach here: elastic resets
            # on this plane happen across a process boundary —
            # elastic.py exit-restart — so a live process never holds a
            # previous cohort's jax.distributed world.)
            return
    except AttributeError:  # older jax
        pass
    log = get_logger()
    coord = envparse.get_str(envparse.XLA_COORD, "")
    if coord:
        log.info("xla-global: jax.distributed coordinator=%s process "
                 "%d/%d", coord, topology.rank, topology.size)
        jax.distributed.initialize(coordinator_address=coord,
                                   num_processes=topology.size,
                                   process_id=topology.rank)
        return

    from ..runner import http_client
    from ..runner import rendezvous as rdv
    cfg = rdv.rendezvous_config()
    if cfg is None:
        raise HorovodInternalError(
            "the xla-global backend needs HVDTPU_XLA_COORD=ip:port or "
            "the hvdrun launcher's rendezvous to broker the JAX "
            "coordinator address")
    addr, port, token = cfg
    # Elastic exit-restart: every membership version forms a fresh
    # jax.distributed world, so the coordinator key must be scoped to
    # the version this cohort joined — a respawned worker reading the
    # previous cohort's coordinator would dial a dead listener.
    ver = envparse.get_env(envparse.ELASTIC_VERSION)
    coord_key = f"coord.{ver}" if ver is not None else "coord"
    if topology.rank == 0:
        # initialize() blocks until every process connects, so the address
        # must be published while it runs. Bind happens immediately inside
        # initialize, the barrier after — so: start it in a thread, give a
        # bind failure a moment to surface (retrying a fresh port), then
        # publish the now-bound address. This closes the practical
        # publish-then-bind steal window.
        import threading
        ip = rdv._local_ip_towards(addr, port)
        errs = []
        thread = None
        last_err = None
        for _ in range(3):
            coord = f"{ip}:{_free_port()}"

            def _serve(c=coord):
                try:
                    jax.distributed.initialize(coordinator_address=c,
                                               num_processes=topology.size,
                                               process_id=0)
                except Exception as e:  # noqa: BLE001
                    errs.append(e)

            thread = threading.Thread(target=_serve, daemon=True)
            thread.start()
            thread.join(timeout=2.0)
            if not errs:
                break  # bound (blocked in the connect barrier) or done
            last_err = errs[0]
            errs.clear()
        else:
            raise HorovodInternalError(
                f"could not start the JAX coordinator: {last_err}")
        log.info("xla-global: serving jax.distributed coordinator at %s",
                 coord)
        http_client.put_kv(addr, port, JAXDIST_SCOPE, coord_key, coord,
                           token=token)
        thread.join()  # all ranks connected (or init failed)
        if errs:
            raise HorovodInternalError(
                f"could not start the JAX coordinator: {errs[0]}")
    else:
        coord = http_client.wait_for_kv(
            addr, port, JAXDIST_SCOPE, coord_key, token=token,
            deadline_s=envparse.get_float(
                envparse.START_TIMEOUT, 120.0)).decode()
        log.info("xla-global: jax.distributed coordinator=%s process "
                 "%d/%d", coord, topology.rank, topology.size)
        jax.distributed.initialize(coordinator_address=coord,
                                   num_processes=topology.size,
                                   process_id=topology.rank)


class XlaGlobalBackend(TcpBackend):
    """Delegated-execution backend: native negotiation, XLA data plane."""

    name = "xla-global"
    delegate_data_ops = True
    # Processes share one jax.distributed global mesh: jitted programs are
    # global-SPMD, so in-jit sharding-propagated reductions span every
    # rank (keras binding keys its trace-time identity-sync off this).
    global_mesh_spmd = True

    def __init__(self, topology):
        # Must run before the first jax backend touch in this process.
        init_jax_distributed(topology)
        import jax
        super().__init__(topology)
        self._jax = jax
        self._np_of = _enum_to_np()
        self._local_device = jax.local_devices()[0]
        # One data-plane device per process, ordered by process index ==
        # hvd rank (we pass process_id=rank to jax.distributed).
        by_proc = {}
        for d in jax.devices():
            by_proc.setdefault(d.process_index, d)
        if len(by_proc) != topology.size:
            raise HorovodInternalError(
                f"jax.distributed sees {len(by_proc)} processes, launcher "
                f"says {topology.size}")
        self._proc_devices = [by_proc[i] for i in range(topology.size)]
        self._ps_ranks = {0: list(range(topology.size))}
        self._mesh_cache = {}
        # Delegated-plane bucket floor (autotunable; see autotune.py).
        self.min_bucket = envparse.get_int(envparse.MIN_BUCKET, 256)
        self._fn_cache = {}
        # Gradient compression on the delegated plane: only the env
        # policy's catch-all wire rule applies (fused native responses
        # carry handles, not tensor names — no globs, no error
        # feedback; docs/compression.md). Every rank parses the same
        # env, so the selection is identical cluster-wide.
        from ..compression.policy import simple_wire_policy
        (self._q_codec, self._q_block,
         self._q_threshold) = simple_wire_policy()
        if self._q_codec is not None:
            get_logger().info(
                "xla-global: quantized allreduce enabled (codec=%s "
                "block=%d threshold=%d; no error feedback on the "
                "delegated plane)", self._q_codec, self._q_block,
                self._q_threshold)

    def set_min_bucket(self, n):
        """Autotune hook: floor for collective bucket sizes (elements).
        Applied at a cycle boundary on every rank with the same value
        (candidate changes are cycle-count driven, autotune.py), so the
        jitted-collective cache stays consistent across ranks."""
        self.min_bucket = max(1, int(n))

    # -- process sets -----------------------------------------------------
    def register_process_set(self, ps):
        super().register_process_set(ps)
        if ps.process_set_id != 0:
            self._ps_ranks[self._ps_map[ps.process_set_id]] = list(ps.ranks)

    def remove_process_set(self, ps):
        native_id = self._ps_map.get(ps.process_set_id)
        super().remove_process_set(ps)
        ranks = self._ps_ranks.pop(native_id, None)
        if ranks is not None:
            # Evict the set's mesh AND its jitted collectives (keyed by
            # id(mesh)) so removed sets don't accumulate executables.
            mesh = self._mesh_cache.pop(tuple(ranks), None)
            if mesh is not None:
                dead = id(mesh)
                self._fn_cache = {k: v for k, v in self._fn_cache.items()
                                  if k[0] != dead}

    def _mesh_for(self, ranks):
        key = tuple(ranks)
        mesh = self._mesh_cache.get(key)
        if mesh is None:
            devices = np.array([self._proc_devices[r] for r in ranks])
            mesh = self._jax.sharding.Mesh(devices, ("hvd",))
            self._mesh_cache[key] = mesh
        return mesh

    # -- the cycle --------------------------------------------------------
    def _drain_delegated(self):
        while True:
            token = self.core.next_delegated()
            if token == 0:
                break
            # The whole per-token block is isolated: an exception from
            # unmarshalling (`delegated`) or completion would otherwise
            # propagate through run_cycle and kill the coordinator's
            # cycle thread — wedging every future submission — instead
            # of poisoning only this response's handles.
            d = None
            try:
                d = self.core.delegated(token)
                self._execute_delegated(d)
            except Exception as exc:  # noqa: BLE001 — fail the handles
                msg = f"XLA data-plane execution failed: {exc}"
                get_logger().error("%s", msg)
                for h in (d["handles"] if d else ()):
                    if h >= 0:
                        try:
                            self.core.delegated_complete(h, error=msg)
                        except Exception:  # noqa: BLE001
                            pass
            finally:
                try:
                    self.core.delegated_finish(token)
                except Exception:  # noqa: BLE001 — keep draining
                    pass

    # -- delegated execution ----------------------------------------------
    def _execute_delegated(self, d):
        ranks = self._ps_ranks.get(d["ps_id"])
        if ranks is None:
            raise HorovodInternalError(
                f"native process set {d['ps_id']} unknown to the XLA "
                "data plane")
        mesh = self._mesh_for(ranks)
        me = ranks.index(self.topology.rank)
        dtype = self._np_of[d["dtype"]]
        t = d["type"]
        if t == _T_ALLREDUCE:
            self._delegated_allreduce(d, mesh, dtype)
        elif t == _T_BROADCAST:
            self._delegated_broadcast(d, mesh, dtype)
        elif t == _T_ALLGATHER:
            self._delegated_allgather(d, mesh, dtype, me)
        elif t == _T_REDUCESCATTER:
            self._delegated_reducescatter(d, mesh, dtype, me, len(ranks))
        else:
            raise HorovodInternalError(f"unexpected delegated type {t}")

    def _collective(self, mesh, kind, n, dtype, extra=()):
        """Cached jitted shard_map collective over the 1-D 'hvd' mesh.
        Input: global (P, n) stacked array; output replicated."""
        key = (id(mesh), kind, int(n), np.dtype(dtype).str, extra)
        fn = self._fn_cache.get(key)
        if fn is not None:
            return fn
        jax = self._jax
        from jax.sharding import PartitionSpec as P
        lax = jax.lax

        if kind == "qallreduce":
            # EQuARX pipeline over the global mesh: quantize →
            # all_to_all (the reduce-scatter leg, wire dtype) → f32
            # accumulate → requantize → all_gather → dequantize. The
            # caller pads n to a multiple of nprocs * block and only
            # routes float SUM reductions here (docs/compression.md).
            op, post, codec_name, block = extra
            from ..compression.codecs import CODECS
            codec = CODECS[codec_name]
            nprocs = int(mesh.devices.size)
            import jax.numpy as jnp

            def body(x):  # x: (1, n) local block, n % (nprocs*block)==0
                rows = x[0].astype(jnp.float32).reshape(nprocs, -1)
                q, s = codec.encode(rows, block)
                q = lax.all_to_all(q, "hvd", split_axis=0,
                                   concat_axis=0, tiled=True)
                s = lax.all_to_all(s, "hvd", split_axis=0,
                                   concat_axis=0, tiled=True)
                red = jnp.sum(codec.decode(q, s, block), axis=0)
                if post != 1.0:
                    red = red * np.asarray(post, dtype=red.dtype)
                q2, s2 = codec.encode(red, block)
                qg = lax.all_gather(q2, "hvd", tiled=True)
                sg = lax.all_gather(s2, "hvd", tiled=True)
                return codec.decode(qg, sg, block,
                                    dtype=x.dtype)[None]
            out_specs = P()
        elif kind.startswith("allreduce"):
            op, post = extra
            def body(x):  # x: (1, n) local block; prescale applied by caller
                if op == _RED_SUM:
                    r = lax.psum(x, "hvd")
                elif op == _RED_MIN:
                    r = lax.pmin(x, "hvd")
                elif op == _RED_MAX:
                    r = lax.pmax(x, "hvd")
                else:  # product: gather + local reduce (no pprod in XLA)
                    r = lax.all_gather(x, "hvd")
                    import jax.numpy as jnp
                    r = jnp.prod(r, axis=0)
                if post != 1.0:
                    r = r * np.asarray(post, dtype=r.dtype)
                return r
            out_specs = P()
        elif kind == "broadcast":
            (root,) = extra
            def body(x):
                g = lax.all_gather(x, "hvd")  # (P, 1, n)
                return g[root]
            out_specs = P()
        else:  # allgather (pad-to-max done by caller)
            def body(x):
                return lax.all_gather(x, "hvd")  # (P, 1, n)
            out_specs = P()

        # Replication-check off: all_gather-then-index outputs ARE
        # replicated over 'hvd' but the inference can't prove it (the
        # compat shim maps check_vma onto check_rep on older jax).
        fn = jax.jit(_shard_map(body, mesh=mesh, in_specs=P("hvd"),
                                out_specs=out_specs, check_vma=False))
        self._fn_cache[key] = fn
        return fn

    def _run_stacked(self, mesh, fn, flat_np):
        """Feed this process's (1, n) block of the global (P, n) array and
        return the replicated result as numpy."""
        jax = self._jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        n = int(flat_np.shape[0])
        nprocs = int(mesh.devices.size)
        local = jax.device_put(flat_np[None, :], self._local_device)
        glob = jax.make_array_from_single_device_arrays(
            (nprocs, n), NamedSharding(mesh, P("hvd")), [local])
        out = fn(glob)
        return np.asarray(out.addressable_data(0))

    @staticmethod
    def _identity(op, dtype):
        """Reduce-op identity for entry-less slots (joined ranks or
        handles released mid-negotiation) — zeros would corrupt
        min/max/prod, same guard as the native FillReduceIdentity
        (csrc/collectives.cc; integer dtypes use type extrema there too:
        np.inf would OverflowError on int min/max)."""
        dt = np.dtype(dtype)
        if op == _RED_MIN:
            return dt.type(np.inf) if dt.kind == "f" else np.iinfo(dt).max
        if op == _RED_MAX:
            return dt.type(-np.inf) if dt.kind == "f" else np.iinfo(dt).min
        if op == _RED_PROD:
            return dt.type(1)
        return dt.type(0)

    def _delegated_allreduce(self, d, mesh, dtype):
        sizes = d["sizes"]  # flat element count per fused tensor
        pre = float(d["prescale"])
        op = d["red_op"]
        parts = []
        for h, nelem in zip(d["handles"], sizes):
            if h >= 0:
                arr = np.ascontiguousarray(self._handle_arrays[h],
                                           dtype=dtype).reshape(-1)
                # Prescale contributed data HOST-SIDE so identity slots
                # below stay exact (the native path does the same,
                # csrc/core.cc per-entry ScaleBuffer).
                if pre != 1.0:
                    arr = arr * np.asarray(pre, dtype=dtype)
                parts.append(arr)
            else:
                parts.append(np.full(int(nelem), self._identity(op, dtype),
                                     dtype=dtype))
        flat = parts[0] if len(parts) == 1 else np.concatenate(parts)
        n = int(flat.shape[0])
        if (self._q_codec is not None and op == _RED_SUM
                and np.dtype(dtype).kind == "f"
                and n >= self._q_threshold):
            # Quantized pipeline (env policy catch-all; __init__ note).
            # Pad the power-of-two bucket up to a whole number of
            # blocks per rank; zero padding is SUM-neutral.
            from ..compression.codecs import padded_len
            pn = padded_len(_bucket(n, self.min_bucket),
                            int(mesh.devices.size), self._q_block)
            fn = self._collective(
                mesh, "qallreduce", pn, dtype,
                (op, float(d["postscale"]), self._q_codec,
                 self._q_block))
            out = self._run_stacked(mesh, fn, _pad(flat, pn, op))[0]
        else:
            fn = self._collective(
                mesh, "allreduce", _bucket(n, self.min_bucket), dtype,
                (op, float(d["postscale"])))
            out = self._run_stacked(
                mesh, fn, _pad(flat, _bucket(n, self.min_bucket), op))[0]
        off = 0
        for h, nelem in zip(d["handles"], sizes):
            nelem = int(nelem)
            if h >= 0:
                shape = self._handle_arrays[h].shape
                self.core.delegated_complete(
                    h, out[off:off + nelem].reshape(shape))
            off += nelem

    def _delegated_broadcast(self, d, mesh, dtype):
        # sizes = [count, root] (csrc/core.cc broadcast response layout).
        count, root = int(d["sizes"][0]), int(d["sizes"][1])
        h = d["handles"][0]
        if h >= 0:
            arr = np.ascontiguousarray(self._handle_arrays[h], dtype=dtype)
            shape = arr.shape
        else:
            arr = np.zeros(count, dtype=dtype)
            shape = None
        flat = arr.reshape(-1)
        fn = self._collective(mesh, "broadcast",
                              _bucket(count, self.min_bucket), dtype,
                              (root,))
        out = self._run_stacked(
            mesh, fn, _pad(flat, _bucket(count, self.min_bucket)))[0]
        if h >= 0:
            self.core.delegated_complete(h, out[:count].reshape(shape))

    def _delegated_allgather(self, d, mesh, dtype, me):
        # sizes = [rows per rank..., row_elems].
        nranks = int(mesh.devices.size)
        rows = [int(r) for r in d["sizes"][:nranks]]
        row_elems = int(d["sizes"][nranks])
        max_n = max(rows) * row_elems if rows else 0
        h = d["handles"][0]
        if h >= 0:
            arr = np.ascontiguousarray(self._handle_arrays[h], dtype=dtype)
            tail = arr.shape[1:] if arr.ndim > 0 else ()
            flat = arr.reshape(-1)
        else:
            tail = None
            flat = np.zeros(rows[me] * row_elems, dtype=dtype)
        bn = _bucket(max_n, self.min_bucket) if max_n else self.min_bucket
        padded = np.zeros(bn, dtype=dtype)
        padded[:flat.shape[0]] = flat
        fn = self._collective(mesh, "allgather", bn, dtype)
        out = self._run_stacked(mesh, fn, padded)  # (P, 1, bn)
        if h < 0:
            return
        pieces = [out[r, 0, :rows[r] * row_elems] for r in range(nranks)]
        total_rows = sum(rows)
        result = np.concatenate(pieces).reshape((total_rows,) + tail)
        self.core.delegated_complete(h, result)

    def _delegated_reducescatter(self, d, mesh, dtype, me, nranks):
        # Uneven dim-0 split (remainder to low ranks) prevents a direct
        # psum_scatter; reduce fully, then slice this rank's rows.
        h = d["handles"][0]
        if h < 0:
            # Unreachable via Join (the controller rejects join +
            # reducescatter at ConstructResponse, like the reference); only
            # a handle released mid-negotiation lands here, and the native
            # path errors identically (csrc/core.cc kReducescatter !e).
            raise HorovodInternalError("reducescatter with no local entry")
        arr = np.ascontiguousarray(self._handle_arrays[h], dtype=dtype)
        rows = arr.shape[0] if arr.ndim else 1
        row_elems = int(np.prod(arr.shape[1:])) if arr.ndim > 1 else 1
        op = d["red_op"]
        pre = float(d["prescale"])
        flat = arr.reshape(-1)
        if pre != 1.0:
            flat = flat * np.asarray(pre, dtype=dtype)
        fn = self._collective(
            mesh, "allreduce", _bucket(flat.shape[0], self.min_bucket), dtype,
            (op, float(d["postscale"])))
        out = self._run_stacked(mesh, fn,
                                _pad(flat, _bucket(flat.shape[0],
                                                   self.min_bucket), op))[0]
        base, rem = divmod(rows, nranks)
        my_rows = base + (1 if me < rem else 0)
        offset_rows = me * base + min(me, rem)
        seg = out[offset_rows * row_elems:(offset_rows + my_rows)
                  * row_elems]
        shape = (my_rows,) + arr.shape[1:]
        self.core.delegated_complete(h, seg.reshape(shape))
