"""Multi-process SPMD data plane backed by the native C++ core.

This is the gloo-analog path (reference: horovod/common/ops/
gloo_operations.cc + gloo/gloo_context.cc): N launcher-spawned processes
negotiate named tensors through the native controller (csrc/controller.cc)
and move bytes with ring collectives over a TCP mesh (csrc/collectives.cc).

Unlike the synchronous single-controller backend, this backend *owns the
cycle*: local fusion decisions would diverge across ranks, so grouping is
negotiated by the native controller exactly like the reference's background
loop. The Python coordinator detects ``drives_own_cycle`` and switches to
submit/cycle/complete mode (see coordinator.py).
"""

import time

import numpy as np

from . import Backend
from .. import chaos
from .. import native
from .. import tracing
from ..exceptions import HorovodInternalError, StalledTensorError
from ..ops import reduce_ops
from ..telemetry import core as telemetry
from ..utils import envparse
from ..utils.logging_util import get_logger

_KIND_TO_REQ = {
    "allreduce": native.REQ_ALLREDUCE,
    "allgather": native.REQ_ALLGATHER,
    "broadcast": native.REQ_BROADCAST,
    "alltoall": native.REQ_ALLTOALL,
    "reducescatter": native.REQ_REDUCESCATTER,
    "barrier": native.REQ_BARRIER,
    "join": native.REQ_JOIN,
}

_OP_TO_RED = {
    reduce_ops.Sum: native.RED_SUM,
    reduce_ops.Min: native.RED_MIN,
    reduce_ops.Max: native.RED_MAX,
    reduce_ops.Product: native.RED_PROD,
}


class _Pending:
    """Bookkeeping from one TensorEntry to its native handles."""

    __slots__ = ("entry", "handles", "unpack", "t0", "nbytes")

    def __init__(self, entry, handles, unpack):
        self.entry = entry
        self.handles = handles
        self.unpack = unpack
        # Telemetry (set by submit_entry only when metrics are on):
        # submit-time stamp + payload bytes for the per-collective
        # wall-time/byte series.
        self.t0 = 0.0
        self.nbytes = 0


class TcpBackend(Backend):
    name = "tcp-native"
    drives_own_cycle = True
    # Subclasses flip these to run the native core in delegated mode (the
    # negotiation/fusion stays native; data ops execute externally).
    delegate_data_ops = False

    def __init__(self, topology):
        peers = envparse.get_str(envparse.PEERS, "")
        if not peers:
            raise HorovodInternalError(
                "SPMD mode needs HVDTPU_PEERS=host:port,... (set by the "
                "hvdrun launcher)")
        timeline = envparse.get_str(envparse.TIMELINE, "")
        self.core = native.NativeCore(
            topology.rank, topology.size, transport="tcp", peers=peers,
            fusion_threshold=envparse.get_int(envparse.FUSION_THRESHOLD, 0),
            cache_capacity=envparse.get_int(envparse.CACHE_CAPACITY, 0),
            stall_warning_s=envparse.get_float(
                envparse.STALL_CHECK_TIME_SECONDS, 0.0),
            stall_shutdown_s=envparse.get_float(
                envparse.STALL_SHUTDOWN_TIME_SECONDS, 0.0),
            timeline_path=(timeline + f".rank{topology.rank}") if timeline
            else "",
            delegate_data_ops=self.delegate_data_ops)
        self.topology = topology
        # Hierarchical allreduce: derive host_of[] from the peer list's
        # host parts (every worker already knows the full mesh) and arm
        # the threshold. Default 1 MiB: below that the extra phases cost
        # more latency than the cross-host bandwidth they save.
        hier = envparse.get_int(envparse.HIERARCHICAL_THRESHOLD,
                                1 << 20)
        if hier > 0:
            host_names = [p.rsplit(":", 1)[0] for p in peers.split(",")]
            host_ids = {}
            host_of = [host_ids.setdefault(h, len(host_ids))
                       for h in host_names]
            if len(host_ids) > 1:
                self.core.set_topology(host_of, hier)
        # Thread-ownership contract (hvd-sanitize audit): _pending,
        # _chaos_swallowed, _handle_arrays and _transport_dead are
        # owned by the COORDINATOR CYCLE THREAD once it starts — every
        # mutator (submit_entry, run_cycle/_sweep_completions,
        # abort_inflight via _check_stalls, _fail_all) runs there.
        # close() is the one main-thread mutator, and basics.shutdown
        # only calls it AFTER coordinator.stop() joined the cycle
        # thread; the synchronous Backend methods below (_sync et al.)
        # are documented coordinator-less entry points (unit tests) and
        # must not be mixed with a running coordinator.
        self._pending = []
        # Chaos 'backend_submit:stall' victims: never enqueued with the
        # native core, but kept reachable so an abort / transport death
        # / close still resolves their waiters instead of hanging them.
        self._chaos_swallowed = []
        self._transport_dead = False
        # handle -> submitted np array (delegated execution needs the
        # local contribution by handle; only kept in delegated mode).
        self._handle_arrays = {}
        self._ps_map = {0: 0}  # python process-set id -> native id
        self._log = get_logger()
        # Set by the coordinator so in-flight tensor names release when the
        # entry completes (duplicate-name semantics live in Python too).
        self.entry_done_cb = None
        # NULL no-ops when HOROVOD_TPU_METRICS is off (docs/metrics.md).
        # Native-plane collectives are measured submit -> completion
        # sweep, so the series includes negotiation time — the honest
        # per-collective wall time on this plane.
        self._metrics_on = telemetry.enabled()
        # Chaos 'backend_submit' point (HVDTPU_CHAOS); cached bool so
        # the disabled path costs one compare per submission.
        self._chaos_on = chaos.enabled()
        self._m_time = telemetry.histogram(
            "hvd_backend_collective_seconds",
            "Per-collective backend wall time",
            labelnames=("backend", "kind"))
        self._m_bytes = telemetry.counter(
            "hvd_backend_collective_bytes_total",
            "Payload bytes through backend collectives",
            labelnames=("backend", "kind"))

    # -- process sets -----------------------------------------------------
    def register_process_set(self, ps):
        if ps.process_set_id == 0:
            return
        self._ps_map[ps.process_set_id] = self.core.add_process_set(ps.ranks)

    def remove_process_set(self, ps):
        native_id = self._ps_map.pop(ps.process_set_id, None)
        if native_id:
            self.core.remove_process_set(native_id)

    def _native_ps(self, ps):
        try:
            return self._ps_map[ps.process_set_id]
        except KeyError:
            raise HorovodInternalError(
                f"process set {ps.process_set_id} not registered with the "
                "native core")

    # -- submission (called from the coordinator cycle thread) ------------
    def submit_entry(self, entry):
        """Translate a TensorEntry into native enqueues; returns False if
        the entry failed synchronously (its handle is completed)."""
        try:
            if self._chaos_on:
                # A matching fail rule raises HorovodInternalError here,
                # which the except below routes to the entry's handle —
                # exactly the path a native enqueue failure takes.
                try:
                    chaos.inject("backend_submit", name=entry.name,
                                 kind=entry.kind)
                except chaos.ChaosSignal as sig:
                    if sig.action == "stall":
                        # Swallow the submission below the coordinator:
                        # the op stays in this rank's in-flight view but
                        # never reaches negotiation — a data-plane hang
                        # for the watchdog to time out (the watchdog's
                        # abort reaches the entry via abort_inflight).
                        self._log.warning(
                            "chaos: backend submission %r swallowed "
                            "(stall injection)", entry.name)
                        self._chaos_swallowed.append(entry)
                        return True
                    raise
            pending = self._enqueue_entry(entry)
            if self._metrics_on:
                pending.t0 = time.perf_counter()
                pending.nbytes = telemetry.payload_nbytes(entry.arrays)
            # Trace plane: the instant this entry entered NATIVE
            # negotiation — on the merged trace the gap between this and
            # the peers' marks is the negotiation wait (one global read
            # + None check when tracing AND flight recorder are off).
            tracing.trace_event("neg", entry.name or entry.kind,
                                o=getattr(entry, "corr", None))
            self._pending.append(pending)
            return True
        except Exception as exc:  # noqa: BLE001 - surfaced via the handle
            if self.entry_done_cb:
                self.entry_done_cb(entry, ok=False)
            entry.handle._fail(exc if isinstance(exc, HorovodInternalError)
                               else HorovodInternalError(str(exc)))
            return False

    def _native_enqueue(self, ps, name, req, array=None, **kw):
        if array is None:
            h = self.core.enqueue(ps, name, req, **kw)
        else:
            h = self.core.enqueue(ps, name, req, array, **kw)
        if self.delegate_data_ops and array is not None and h >= 0:
            self._handle_arrays[h] = array
        return h

    def _red_op(self, entry, n):
        """Map framework reduce op to (native op, extra postscale)."""
        op = entry.op
        if op is None or op == reduce_ops.Average:
            return native.RED_SUM, 1.0 / n
        if op == reduce_ops.Adasum:
            # VHDD on the host data plane (csrc/collectives.cc VhddAdasum;
            # reference spec adasum/adasum.h:194-343). No postscale: the
            # adasum combination IS the result.
            return native.RED_ADASUM, 1.0
        try:
            return _OP_TO_RED[op], 1.0
        except KeyError:
            raise HorovodInternalError(f"unknown reduce op {op!r}")

    def _enqueue_entry(self, entry):
        kind = entry.kind
        ps = self._native_ps(entry.process_set)
        n = len(entry.process_set.ranks)
        pre = 1.0 if entry.prescale is None else float(entry.prescale)
        post = 1.0 if entry.postscale is None else float(entry.postscale)
        core = self.core

        if kind == "allreduce":
            codec_sel = getattr(entry, "codec", None)
            if codec_sel is not None and not self.delegate_data_ops:
                from ..compression import codecs as comp_codecs
                codec = comp_codecs.CODECS[codec_sel[0]]
                if codec.wire:
                    return self._enqueue_quantized_allreduce(
                        entry, ps, n, pre, post, codec,
                        codec_sel[1])
                # Cast codec (fp16/bf16): the native ring reduces in
                # the narrow dtype; results cast back at the sweep.
                return self._enqueue_cast_allreduce(entry, codec)
            red, post_extra = self._red_op(entry, n)
            arrays = [np.asarray(a) for a in entry.arrays]
            if len(arrays) == 1:
                h = self._native_enqueue(
                    ps, entry.name, native.REQ_ALLREDUCE, arrays[0],
                    red_op=red, prescale=pre, postscale=post * post_extra)
                return _Pending(entry, [h],
                                _unpack_single(arrays[0].dtype,
                                               arrays[0].shape))
            # Grouped allreduce: concat-flatten so the group is one atomic
            # negotiated tensor (reference: group_table.cc semantics — the
            # group fuses as a unit). Adasum groups enqueue per-tensor
            # instead: its dot-product coefficients are per-tensor, and a
            # concatenated buffer would couple the layers' scale adaptation.
            dtype = arrays[0].dtype
            if any(a.dtype != dtype for a in arrays):
                raise HorovodInternalError(
                    "grouped allreduce requires uniform dtype per group")
            if red == native.RED_ADASUM:
                handles = [self._native_enqueue(
                    ps, f"{entry.name}.{i}", native.REQ_ALLREDUCE, a,
                    red_op=red, prescale=pre,
                    postscale=post * post_extra)
                    for i, a in enumerate(arrays)]
                return _Pending(entry, handles, _unpack_list_shaped(arrays))
            flat = np.concatenate([a.reshape(-1) for a in arrays])
            h = self._native_enqueue(
                ps, entry.name, native.REQ_ALLREDUCE, flat, red_op=red,
                prescale=pre, postscale=post * post_extra)
            return _Pending(entry, [h], _unpack_group(arrays))

        if kind == "allgather":
            arrays = [np.asarray(a) for a in entry.arrays]
            handles = []
            for i, a in enumerate(arrays):
                nm = entry.name if len(arrays) == 1 else f"{entry.name}.{i}"
                handles.append(self._native_enqueue(
                    ps, nm, native.REQ_ALLGATHER, a))
            return _Pending(entry, handles, _unpack_list(arrays))

        if kind == "broadcast":
            # Root arrives as a process-set-relative index (collectives.py
            # translates global -> set-relative before submission).
            arrays = [np.asarray(a) for a in entry.arrays]
            handles = []
            for i, a in enumerate(arrays):
                nm = entry.name if len(arrays) == 1 else f"{entry.name}.{i}"
                handles.append(self._native_enqueue(
                    ps, nm, native.REQ_BROADCAST, a,
                    root_rank=entry.root_rank))
            # Shape-preserving unpack: broadcast output shape == input
            # shape, and the native wire drops 0-d shapes (c_api.cc keeps
            # shape only for ndim > 0), so scalars would come back (1,).
            return _Pending(entry, handles, _unpack_list_shaped(arrays))

        if kind == "alltoall":
            a = np.asarray(entry.arrays[0])
            splits = entry.splits
            if splits is None:
                if a.shape[0] % n != 0:
                    raise HorovodInternalError(
                        f"alltoall without splits requires dim0 divisible "
                        f"by process-set size {n}")
                splits = np.full(n, a.shape[0] // n, dtype=np.int32)
            h = self._native_enqueue(
                ps, entry.name, native.REQ_ALLTOALL, a,
                splits=np.asarray(splits, dtype=np.int32))
            return _Pending(entry, [h], _unpack_alltoall(a.dtype, self))

        if kind == "reducescatter":
            if entry.op == reduce_ops.Adasum:
                raise HorovodInternalError(
                    "Adasum is not defined for reducescatter")
            red, post_extra = self._red_op(entry, n)
            arrays = [np.asarray(a) for a in entry.arrays]
            handles = []
            for i, a in enumerate(arrays):
                nm = entry.name if len(arrays) == 1 else f"{entry.name}.{i}"
                handles.append(self._native_enqueue(
                    ps, nm, native.REQ_REDUCESCATTER, a, red_op=red,
                    postscale=post * post_extra))
            return _Pending(entry, handles, _unpack_list(arrays))

        if kind == "sparse_allreduce":
            return self._enqueue_sparse_allgather(entry, ps, n)

        if kind == "barrier":
            h = self._native_enqueue(ps, entry.name, native.REQ_BARRIER)
            return _Pending(entry, [h], lambda core, hs: None)

        if kind == "join":
            h = self._native_enqueue(ps, "__join__", native.REQ_JOIN)
            return _Pending(entry, [h], _unpack_join())

        raise HorovodInternalError(f"unknown op kind {kind}")

    def _enqueue_cast_allreduce(self, entry, codec):
        """Cast codec (fp16/bf16) on the host plane: reference
        wire-compression semantics — the native ring carries and
        reduces the narrow dtype, and the sweep casts results back to
        the submitted dtypes."""
        import jax.numpy as jnp
        orig_arrays = [np.asarray(a) for a in entry.arrays]
        plane = getattr(self, "compression_plane", None)
        if plane is not None:
            plane.record(codec.name, [entry], orig_arrays, None)
        entry.arrays = [np.asarray(jnp.asarray(a)
                                   .astype(codec.cast_dtype))
                        for a in orig_arrays]
        entry.codec = None  # re-enter the normal allreduce path
        pending = self._enqueue_entry(entry)
        inner = pending.unpack
        orig_dtypes = [a.dtype for a in orig_arrays]

        def unpack(core, handles):
            out = inner(core, handles)
            if isinstance(out, list):
                return [_to_jax(np.asarray(o).astype(dt))
                        for o, dt in zip(out, orig_dtypes)]
            return _to_jax(np.asarray(out).astype(orig_dtypes[0]))
        pending.unpack = unpack
        return pending

    def _enqueue_quantized_allreduce(self, entry, ps, n, pre, post,
                                     codec, block):
        """Wire-codec allreduce on the host data plane (ISSUE 6): encode
        locally, allgather the (payload, scales) pair as TWO negotiated
        tensors, dequantize-accumulate in f32 at the completion sweep.
        This quantized-allgather formulation moves ~(n-1)·B bytes per
        rank where B ≈ orig/4 — a clear win over the fp32 ring's
        2·orig at the small cohort sizes the CPU plane serves; the
        compiled planes run the scalable reduce-scatter pipeline
        instead (docs/compression.md). Error-feedback residuals thread
        through the coordinator's plane (``compression_plane``); the
        residual is stored at transmit time — exactly what this rank
        put on the wire is what its debt reflects."""
        import jax.numpy as jnp

        codec_name = codec.name
        if entry.op not in (None, reduce_ops.Sum, reduce_ops.Average):
            raise HorovodInternalError(
                "quantized allreduce supports Sum/Average, got "
                f"{reduce_ops.op_name(entry.op)}")
        average = entry.op in (None, reduce_ops.Average)
        post_total = post * (1.0 / n if average else 1.0)
        arrays = [np.asarray(a) for a in entry.arrays]
        flats = [a.reshape(-1).astype(np.float32) for a in arrays]
        flat = flats[0] if len(flats) == 1 else np.concatenate(flats)
        if pre != 1.0:
            flat = flat * np.float32(pre)
        plane = getattr(self, "compression_plane", None)
        resid = (plane.residuals_in([entry])
                 if plane is not None else None)
        if resid:
            flat = flat + np.concatenate(
                [np.asarray(r, np.float32).reshape(-1) for r in resid])
        total = flat.shape[0]
        padded = -(-total // block) * block
        if padded != total:
            flat = np.pad(flat, (0, padded - total))
        q, s = codec.encode(jnp.asarray(flat), block)
        q_np = np.ascontiguousarray(np.asarray(q))
        s_np = np.ascontiguousarray(np.asarray(s, np.float32))
        if plane is not None and plane.error_feedback:
            err = (flat - np.asarray(codec.decode(q, s, block),
                                     np.float32))[:total]
            outs, off = [], 0
            for a in arrays:
                outs.append(err[off:off + a.size].reshape(a.shape))
                off += a.size
            plane.store_residuals([entry], outs)
            plane.record(codec_name, [entry], arrays, outs)
        elif plane is not None:
            plane.record(codec_name, [entry], arrays, None)
        hq = self._native_enqueue(ps, f"{entry.name}.q",
                                  native.REQ_ALLGATHER, q_np)
        hs = self._native_enqueue(ps, f"{entry.name}.s",
                                  native.REQ_ALLGATHER, s_np)
        return _Pending(entry, [hq, hs],
                        _unpack_quantized(codec, block, n, padded,
                                          arrays, post_total))

    def _enqueue_sparse_allgather(self, entry, ps, n):
        """Gather-path sparse allreduce on the host data plane
        (ops/sparse.py; docs/sparse.md): this rank's deduplicated
        (indices, values) slices ride TWO negotiated allgathers — the
        native allgather-v already negotiates per-rank first-dim sizes
        (csrc/collectives.cc RingAllgatherv), so ragged nnz needs no
        extra protocol — and the completion sweep scatter-adds the
        gathered slices into the dense shape. Wire bytes per rank are
        ~(n-1)*nnz*(row + index) instead of the fp32 ring's 2*table.
        With the int8 row codec the VALUES travel quantized (one f32
        scale per slice row, a third allgather); indices are exact
        always. The delegated xla-global plane has no uneven
        negotiation — entries densify into a plain allreduce there
        (lossless, warned once)."""
        from ..ops import sparse as sparse_mod

        m = entry.sparse
        idx = np.ascontiguousarray(np.asarray(entry.arrays[0]))
        vals = np.ascontiguousarray(np.asarray(entry.arrays[1]))
        if self.delegate_data_ops:
            if not getattr(self, "_warned_sparse_delegated", False):
                self._warned_sparse_delegated = True
                self._log.warning(
                    "sparse: the delegated xla-global data plane has no "
                    "uneven-allgather transport; gather-path entries "
                    "densify into a plain allreduce (lossless, no wire "
                    "win — docs/sparse.md)")
            dense = np.asarray(sparse_mod.scatter_add_dense(
                idx, vals, m.dense_shape, 1, reduce_ops.Sum))
            entry.arrays = [dense]
            entry.kind = "allreduce"
            entry.sparse = None
            return self._enqueue_entry(entry)
        row_elems = sparse_mod.row_elems(m.dense_shape)
        hi = self._native_enqueue(ps, f"{entry.name}.idx",
                                  native.REQ_ALLGATHER, idx)
        handles = [hi]
        if m.codec == "int8":
            q, s = sparse_mod.encode_rows(vals)
            handles.append(self._native_enqueue(
                ps, f"{entry.name}.q", native.REQ_ALLGATHER,
                np.ascontiguousarray(np.asarray(q))))
            handles.append(self._native_enqueue(
                ps, f"{entry.name}.s", native.REQ_ALLGATHER,
                np.ascontiguousarray(np.asarray(s, np.float32))))
        else:
            handles.append(self._native_enqueue(
                ps, f"{entry.name}.val", native.REQ_ALLGATHER, vals))
        # Accounting happens at completion (_unpack_sparse) where the
        # EXACT gathered total is known — approximating it here as
        # local-nnz x n mis-reports hvd_sparse_bytes_saved_total both
        # ways under per-rank nnz skew (the common sparse shape), and
        # diverges from the single-controller path's exact sums.
        # n <= 1: no fabric, nothing is "saved" (mirrors the
        # coordinator's guard).
        plane = getattr(self, "sparse_plane", None)
        return _Pending(entry, handles,
                        _unpack_sparse(m, n, row_elems, entry.op,
                                       vals.dtype,
                                       plane=(plane if n > 1 else None)))

    # -- the cycle --------------------------------------------------------
    def run_cycle(self):
        """One native negotiation cycle + completion sweep. Returns the
        number of TensorEntries completed."""
        rc = self.core.run_cycle()
        if rc == -2:
            self._transport_dead = True
            self._fail_all(HorovodInternalError(
                "native core transport failure (peer died?)"))
            return 0
        self._drain_delegated()
        return self._sweep_completions()

    def _drain_delegated(self):
        """Hook for delegated-execution subclasses (xla_global.py)."""

    def _sweep_completions(self):
        """Sweep pending entries for completion. Each entry is processed
        in isolation: a poisoned entry (bad unpack, a native-layer error
        while polling/releasing) fails only its OWN handles — the sweep
        continues and every other in-flight entry still completes,
        instead of one exception wedging the whole cycle loop forever."""
        done = 0
        still = []
        for p in self._pending:
            try:
                finished = self._sweep_one(p)
            except Exception as exc:  # noqa: BLE001 — isolate the entry
                self._log.error("completion sweep failed for %r: %s",
                                p.entry.name, exc)
                self._discard_pending(p, HorovodInternalError(
                    f"completion processing failed for {p.entry.name!r}: "
                    f"{exc}"))
                done += 1
                continue
            if finished:
                done += 1
            else:
                still.append(p)
        self._pending = still
        return done

    def _sweep_one(self, p):
        """Advance one pending entry; True when it reached a terminal
        state (completed or failed) and left the pending set."""
        states = [self.core.poll(h) for h in p.handles]
        if any(s == 0 for s in states):
            # Never release in-flight handles: a multi-handle entry with
            # one early error waits until every handle is terminal so
            # the native negotiation stays consistent.
            return False
        if any(s == 2 for s in states):
            errs = [self.core.error(h) for h, s in zip(p.handles, states)
                    if s == 2]
            for h in p.handles:
                self.core.release(h)
                self._handle_arrays.pop(h, None)
            if self.entry_done_cb:
                self.entry_done_cb(p.entry, ok=False)
            msg = "; ".join(errs)
            # "STALLED:" is the native layer's stable marker; a mixed
            # multi-handle failure (stall + transport) classifies as
            # internal so elastic recovery still catches it.
            exc = (StalledTensorError(msg)
                   if errs and all(e.startswith("STALLED:")
                                   for e in errs)
                   else HorovodInternalError(msg))
            p.entry.handle._fail(exc)
            return True
        # All handles done.
        try:
            result = p.unpack(self.core, p.handles)
            if self._metrics_on and p.t0:
                kind = p.entry.kind
                self._m_time.labels(
                    backend=self.name, kind=kind).observe(
                        time.perf_counter() - p.t0)
                if p.nbytes:
                    self._m_bytes.labels(
                        backend=self.name, kind=kind).inc(p.nbytes)
            if self.entry_done_cb:
                self.entry_done_cb(p.entry)
            p.entry.handle._complete(result)
        except Exception as exc:  # noqa: BLE001
            p.entry.handle._fail(HorovodInternalError(str(exc)))
        finally:
            for h in p.handles:
                self.core.release(h)
                self._handle_arrays.pop(h, None)
        return True

    def _discard_pending(self, p, exc):
        """Terminal cleanup for a poisoned entry: best-effort release of
        its native handles, then fail its framework handle."""
        for h in p.handles:
            try:
                self.core.release(h)
            except Exception:  # noqa: BLE001 — already failing
                pass
            self._handle_arrays.pop(h, None)
        if self.entry_done_cb:
            self.entry_done_cb(p.entry, ok=False)
        p.entry.handle._fail(exc)

    def _fail_all(self, exc):
        for p in self._pending:
            if self.entry_done_cb:
                self.entry_done_cb(p.entry, ok=False)
            p.entry.handle._fail(exc)
        self._pending = []
        for e in self._chaos_swallowed:
            if self.entry_done_cb:
                self.entry_done_cb(e, ok=False)
            e.handle._fail(exc)
        self._chaos_swallowed = []
        # Every in-flight submission is dead; drop the recorded arrays so
        # a backend surviving into elastic recovery does not retain them.
        self._handle_arrays.clear()

    def abort_inflight(self, exc):
        """Watchdog coordinated abort: fail every pending negotiation
        with the diagnostic-bearing exception. Native handles are
        released so a subsequent consensus shutdown does not wait on
        entries whose waiters have already been failed."""
        for p in self._pending:
            for h in p.handles:
                try:
                    self.core.release(h)
                except Exception:  # noqa: BLE001 — aborting anyway
                    pass
        self._fail_all(exc)

    def pending_count(self):
        return len(self._pending)

    # -- synchronous Backend interface ------------------------------------
    # These let the backend be used directly (without the coordinator), e.g.
    # from unit tests. Each drives cycles inline until completion.
    def _sync(self, entry):
        from ..coordinator import TensorEntry  # noqa: F401  (type only)
        if not self.submit_entry(entry):
            entry.handle.wait(0)
        while any(p.entry is entry for p in self._pending):
            self.run_cycle()
        return entry.handle.wait(300)

    def allreduce(self, arrays, op, process_set, prescale=None,
                  postscale=None):
        from ..coordinator import TensorEntry
        e = TensorEntry(_name("allreduce"), "allreduce", list(arrays),
                        process_set, op=op, prescale=prescale,
                        postscale=postscale)
        out = self._sync(e)
        return out if isinstance(out, list) else [out]

    def allgather(self, arrays, process_set):
        from ..coordinator import TensorEntry
        e = TensorEntry(_name("allgather"), "allgather", list(arrays),
                        process_set)
        out = self._sync(e)
        return out if isinstance(out, list) else [out]

    def broadcast(self, arrays, root_rank, process_set):
        from ..coordinator import TensorEntry
        e = TensorEntry(_name("broadcast"), "broadcast", list(arrays),
                        process_set, root_rank=root_rank)
        out = self._sync(e)
        return out if isinstance(out, list) else [out]

    def alltoall(self, array, splits, process_set):
        from ..coordinator import TensorEntry
        e = TensorEntry(_name("alltoall"), "alltoall", [array], process_set,
                        splits=splits)
        return self._sync(e)

    def reducescatter(self, arrays, op, process_set):
        from ..coordinator import TensorEntry
        e = TensorEntry(_name("reducescatter"), "reducescatter", list(arrays),
                        process_set, op=op)
        out = self._sync(e)
        return out if isinstance(out, list) else [out]

    def barrier(self, process_set):
        from ..coordinator import TensorEntry
        e = TensorEntry(_name("barrier"), "barrier", [], process_set)
        self._sync(e)

    def join(self, device=-1):
        from ..coordinator import TensorEntry
        from ..process_sets import global_process_set
        e = TensorEntry(_name("join"), "join", [], global_process_set)
        return self._sync(e)

    def close(self):
        try:
            if self._transport_dead:
                # A dead peer can never agree to the consensus shutdown;
                # draining would spin (elastic resets hit this path after
                # a rank is killed). Fail fast instead.
                self._fail_all(HorovodInternalError(
                    "runtime shut down after transport failure"))
                return
            self.core.request_shutdown()
            # Bounded drain through the FULL cycle (completion sweep
            # included) so waiters on in-flight entries resolve; peers must
            # agree before the consensus shutdown lands.
            for _ in range(10000):
                if self.core.shutdown_complete():
                    break
                self.run_cycle()
                if self._transport_dead:
                    break
            self._fail_all(HorovodInternalError(
                "runtime shut down with operations in flight"))
        finally:
            self.core.close()


_counter = [0]


def _name(kind):
    _counter[0] += 1
    return f"{kind}.sync.{_counter[0]}"


# -- unpack helpers (native outputs -> framework results) ------------------

def _to_jax(arr):
    import jax.numpy as jnp
    return jnp.asarray(arr)


def _unpack_single(dtype, shape):
    def unpack(core, handles):
        out = core.output(handles[0], dtype)
        return _to_jax(out.reshape(shape))
    return unpack


def _unpack_group(arrays):
    shapes = [a.shape for a in arrays]
    sizes = [a.size for a in arrays]
    dtype = arrays[0].dtype

    def unpack(core, handles):
        flat = core.output(handles[0], dtype)
        outs, off = [], 0
        for shape, size in zip(shapes, sizes):
            outs.append(_to_jax(flat[off:off + size].reshape(shape)))
            off += size
        return outs
    return unpack


def _unpack_list(arrays):
    dtypes = [a.dtype for a in arrays]

    def unpack(core, handles):
        outs = [_to_jax(core.output(h, dt))
                for h, dt in zip(handles, dtypes)]
        return outs if len(outs) > 1 else outs[0]
    return unpack


def _unpack_list_shaped(arrays):
    """Like _unpack_list, but reshapes each output to its input's shape —
    for ops whose output shape equals the input shape (broadcast), where
    the native wire cannot represent 0-d shapes."""
    dtypes = [a.dtype for a in arrays]
    shapes = [a.shape for a in arrays]

    def unpack(core, handles):
        outs = [_to_jax(core.output(h, dt).reshape(shape))
                for h, dt, shape in zip(handles, dtypes, shapes)]
        return outs if len(outs) > 1 else outs[0]
    return unpack


def _unpack_quantized(codec, block, n, padded, arrays, post):
    """Completion half of the host-plane quantized allreduce: the two
    gathered tensors are every rank's payload (n·padded wire values)
    and scales; dequantize per rank, sum in f32, apply the combined
    post/averaging scale, and split back into the entry's arrays in
    their original dtypes."""
    shapes = [a.shape for a in arrays]
    sizes = [a.size for a in arrays]
    dtypes = [a.dtype for a in arrays]
    payload_dtype = np.dtype(codec.payload_np)

    def unpack(core, handles):
        import jax.numpy as jnp
        qg = core.output(handles[0], payload_dtype).reshape(n, padded)
        sg = core.output(handles[1], np.float32).reshape(n, -1)
        wide = np.asarray(codec.decode(jnp.asarray(qg), jnp.asarray(sg),
                                       block), np.float32)
        red = wide.sum(axis=0)
        if post != 1.0:
            red = red * np.float32(post)
        outs, off = [], 0
        for shape, size, dtype in zip(shapes, sizes, dtypes):
            outs.append(_to_jax(red[off:off + size].reshape(shape)
                                .astype(dtype)))
            off += size
        return outs if len(outs) > 1 else outs[0]
    return unpack


def _unpack_sparse(meta, n, row_elems, op, val_dtype, plane=None):
    """Completion half of the sparse gather path: the concat-gathered
    indices and (possibly row-quantized) values scatter-add into the
    dense shape — order-invariant, so no per-rank boundary metadata is
    needed on the wire. With ``plane``, bytes-saved accounting runs
    here too (the sweep thread — the plane's accounting contract),
    using the EXACT gathered nnz total rather than a local estimate."""
    from ..ops import sparse as sparse_mod
    idx_dtype = np.dtype(meta.index_dtype)
    tail = tuple(meta.dense_shape[1:])
    codec = meta.codec
    dense_shape = meta.dense_shape

    def unpack(core, handles):
        idx = core.output(handles[0], idx_dtype).reshape(-1)
        if plane is not None:
            val_isize = np.dtype(val_dtype).itemsize
            plane.record_gather(
                sparse_mod.dense_wire_bytes(dense_shape, val_isize),
                sparse_mod.gather_wire_bytes(int(idx.shape[0]),
                                             row_elems, val_isize,
                                             idx_dtype.itemsize, n,
                                             codec=codec))
        if codec == "int8":
            q = core.output(handles[1], np.int8).reshape((-1,) + tail)
            s = core.output(handles[2], np.float32).reshape(-1)
            vals = np.asarray(sparse_mod.decode_rows(q, s, val_dtype))
        else:
            vals = core.output(handles[1],
                               val_dtype).reshape((-1,) + tail)
        return _to_jax(np.asarray(sparse_mod.scatter_add_dense(
            idx, vals, dense_shape, n, op, dtype=val_dtype)))
    return unpack


def _unpack_alltoall(dtype, backend):
    def unpack(core, handles):
        out = core.output(handles[0], dtype)
        splits = core.recv_splits(handles[0])
        return _to_jax(out), splits
    return unpack


def _unpack_join():
    def unpack(core, handles):
        out = core.output(handles[0], np.int32).reshape(-1)
        return int(out[0]) if out.size else -1
    return unpack
