"""Single-controller XLA data plane.

This is the TPU-native replacement for the reference's NCCL op layer
(reference: horovod/common/ops/nccl_operations.cc). Instead of an async
host-side collective library bridged to the framework stream, every eager
collective here is a **jitted XLA program over the replica mesh**: each mesh
device is a virtual rank, inputs are stacked along a leading virtual-rank
axis and sharded P('hvd'), and the collective lowers to the matching XLA/ICI
primitive (psum / all_gather / psum_scatter / all_to_all).

Fusion (reference: fusion_buffer_manager.cc + batched D2D kernels,
horovod/common/ops/cuda/cuda_kernels.cu:45-139) is achieved at a different
level: the coordinator concatenates flattened tensors into one buffer per
dtype and this backend runs ONE compiled collective per buffer — XLA then
handles all layout/fusion on-device, so no hand-written memcpy kernels are
needed.

Compiled programs are cached per (op-kind, process-set, reduce-op); together
with jit's shape-keyed cache this plays the role of the reference's response
cache (reference: horovod/common/response_cache.cc) — a steady-state training
step re-dispatches a cached executable with zero negotiation.
"""

import functools
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from . import Backend
from ..ops import reduce_ops
from ..telemetry import core as telemetry
from ..telemetry import span as tele_span
from ..utils import envparse
from ..utils.jax_compat import shard_map as _shard_map

AXIS = "hvd"
# Bound on cached compiled programs, the analog of the reference's
# response-cache capacity (reference: horovod/common/global_state.h:89,
# HOROVOD_CACHE_CAPACITY read at operations.cc:516).
DEFAULT_CACHE_CAPACITY = 1024


def _timed(kind):
    """Per-collective telemetry around a backend method: wall time (jax
    dispatch is async, so this is submit-to-future time — first calls
    include compilation) and payload bytes by op type. Zero work when
    metrics are off."""
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(self, payload, *args, **kwargs):
            if not self._metrics_on:
                return fn(self, payload, *args, **kwargs)
            with tele_span((), kind.upper(),
                           histogram=self._m_time.labels(
                               backend=self.name, kind=kind)):
                out = fn(self, payload, *args, **kwargs)
            nbytes = telemetry.payload_nbytes(payload)
            if nbytes:
                self._m_bytes.labels(backend=self.name,
                                     kind=kind).inc(nbytes)
            return out
        return wrapper
    return deco


def _scale(x, factor):
    if factor is None:
        return x
    return x * jnp.asarray(factor).astype(x.dtype)


def _pprod(x, n):
    """Cross-replica product via ppermute: O(block) device memory (the
    gather-then-prod alternative holds n blocks).

    Binomial-tree reduce to rank 0 (ceil(log2 n) rounds for ANY n — the
    idx+shift<n mask handles partial partners; one fixed association)
    then broadcast rank 0's result — every rank returns BITWISE-identical
    values, preserving the allreduce contract that all stacked slices are
    equal. A rotation-order ring would multiply in a different
    association per rank and drift at the ulp level.
    """
    idx = lax.axis_index(AXIS)
    acc = x
    shift = 1
    while shift < n:
        recv = lax.ppermute(acc, AXIS,
                            [(i, (i - shift) % n) for i in range(n)])
        take = (idx % (2 * shift) == 0) & (idx + shift < n)
        acc = jnp.where(take, acc * recv, acc)
        shift *= 2
    return _psum_broadcast(acc, 0)


def _psum_broadcast(x, root_rank):
    """One-to-all broadcast as a masked psum: every non-root contributes
    zeros, so per-device memory stays O(block) — no all_gather
    materializing n blocks. Bool rides as int32."""
    is_bool = x.dtype == jnp.bool_
    v = x.astype(jnp.int32) if is_bool else x
    idx = lax.axis_index(AXIS)
    picked = jnp.where(idx == root_rank, v, jnp.zeros_like(v))
    out = lax.psum(picked, AXIS)
    return out.astype(jnp.bool_) if is_bool else out


class XlaSingleBackend(Backend):
    name = "xla"

    def __init__(self, mesh):
        self.global_mesh = mesh
        self._meshes = {0: mesh}
        self._fns = OrderedDict()
        self._cache_capacity = envparse.get_int(
            envparse.CACHE_CAPACITY, DEFAULT_CACHE_CAPACITY)
        # NULL no-ops when HOROVOD_TPU_METRICS is off (docs/metrics.md).
        self._metrics_on = telemetry.enabled()
        self._m_time = telemetry.histogram(
            "hvd_backend_collective_seconds",
            "Per-collective backend wall time",
            labelnames=("backend", "kind"))
        self._m_bytes = telemetry.counter(
            "hvd_backend_collective_bytes_total",
            "Payload bytes through backend collectives",
            labelnames=("backend", "kind"))

    # -- process sets ------------------------------------------------------
    def register_process_set(self, ps):
        self._meshes[ps.process_set_id] = ps.mesh

    def remove_process_set(self, ps):
        self._meshes.pop(ps.process_set_id, None)
        self._fns = OrderedDict(
            (k, v) for k, v in self._fns.items()
            if k[1] != ps.process_set_id)

    def _mesh(self, ps):
        return self._meshes[ps.process_set_id]

    def shard(self, ps, x):
        """Place a stacked array so slice i lives on virtual rank i's device."""
        mesh = self._mesh(ps)
        return jax.device_put(x, NamedSharding(mesh, P(AXIS)))

    # -- compiled-program cache -------------------------------------------
    def _cached(self, key, builder):
        """LRU-bounded program cache. Dynamic keys (e.g. ragged alltoall
        splits) would otherwise grow without bound."""
        fn = self._fns.get(key)
        if fn is None:
            fn = builder()
            self._fns[key] = fn
            while len(self._fns) > self._cache_capacity:
                self._fns.popitem(last=False)
        else:
            self._fns.move_to_end(key)
        return fn

    # -- allreduce ---------------------------------------------------------
    @_timed("allreduce")
    def allreduce(self, arrays, op, process_set, prescale=None,
                  postscale=None):
        """Stacked allreduce: each array has leading axis == set size; output
        is stacked with every slice equal to the reduction.

        One jitted shard_map carries the whole list (a fusion bucket) in a
        single XLA program → one fused ICI collective sequence.
        """
        if op == reduce_ops.Adasum:
            return self._adasum_allreduce(arrays, process_set, prescale,
                                          postscale)
        mesh = self._mesh(process_set)
        n = mesh.devices.size
        key = ("ar", process_set.process_set_id, op)

        def build():
            def body(scales, xs):
                pre, post = scales
                outs = []
                for x in xs:
                    x = _scale(x, pre)
                    if op in (reduce_ops.Sum, reduce_ops.Average):
                        y = lax.psum(x, AXIS)
                        if op == reduce_ops.Average:
                            y = (y / n).astype(x.dtype)
                    elif op == reduce_ops.Min:
                        y = lax.pmin(x, AXIS)
                    elif op == reduce_ops.Max:
                        y = lax.pmax(x, AXIS)
                    elif op == reduce_ops.Product:
                        # ppermute-based product: O(block) memory per
                        # device vs the O(n*block) of gather-then-prod;
                        # binomial tree + broadcast, ~2*ceil(log2 n)
                        # rounds for any n.
                        y = _pprod(x, n)
                    else:
                        raise ValueError(
                            f"Unsupported op {reduce_ops.op_name(op)}")
                    y = _scale(y, post)
                    outs.append(y)
                return tuple(outs)

            sm = _shard_map(
                body, mesh=mesh,
                in_specs=(P(), P(AXIS)), out_specs=P(AXIS))
            return jax.jit(sm)

        fn = self._cached(key, build)
        pre = jnp.asarray(1.0 if prescale is None else prescale,
                          dtype=jnp.float32)
        post = jnp.asarray(1.0 if postscale is None else postscale,
                           dtype=jnp.float32)
        ins = tuple(self.shard(process_set, jnp.asarray(a)) for a in arrays)
        return list(fn((pre, post), ins))

    def _adasum_allreduce(self, arrays, process_set, prescale, postscale):
        from ..ops import adasum
        return adasum.adasum_allreduce_stacked(
            self, arrays, process_set, prescale, postscale)

    # -- quantized allreduce (EQuARX pipeline) -----------------------------
    @_timed("allreduce_quantized")
    def allreduce_quantized(self, arrays, op, process_set, codec, block,
                            prescale=None, postscale=None,
                            residuals=None):
        """Block-quantized fused allreduce: quantize → all_to_all (the
        reduce-scatter leg, wire dtype) → dequantized f32 accumulation →
        requantize → all_gather (wire dtype again) → dequantize. Both
        collective legs carry ~1 byte/value + one f32 scale per
        ``block`` instead of the input dtype's width (PAPERS.md: EQuARX,
        arXiv:2506.17615).

        ``residuals`` (error feedback, optional): f32 arrays aligned
        with ``arrays``; each is added to the (prescaled) input before
        quantization, and the call returns ``(outs, new_residuals)``
        where ``new_residuals[i] = input_i - dequant(quant(input_i))``
        — the quantization debt to carry into the next step. With
        ``residuals=None`` the second element is None.

        Only Sum/Average are supported: dequantize-then-accumulate is a
        linear-reduction identity; Min/Max/Product have no wide-dtype
        reduce stage (the policy never routes them here)."""
        if op not in (reduce_ops.Sum, reduce_ops.Average):
            raise ValueError(
                "quantized allreduce supports Sum/Average, got "
                f"{reduce_ops.op_name(op)}")
        mesh = self._mesh(process_set)
        n = mesh.devices.size
        ef = residuals is not None
        key = ("arq", process_set.process_set_id, op, codec.name,
               int(block), ef)

        def build():
            from ..compression.codecs import padded_len

            def pipeline(flats, post):
                """flats: list of f32 per-rank flat vectors (residual
                already folded in). Returns (reduced flats, local
                quantization errors)."""
                sizes = [f.shape[0] for f in flats]
                flat = (jnp.concatenate(flats) if len(flats) > 1
                        else flats[0])
                total = flat.shape[0]
                padded = padded_len(total, n, block)
                if padded != total:
                    flat = jnp.pad(flat, (0, padded - total))
                rows = flat.reshape(n, padded // n)
                q, s = codec.encode(rows, block)
                # Local reconstruction error BEFORE the exchange — the
                # residual each virtual rank carries forward.
                err = (rows - codec.decode(q, s, block)).reshape(padded)
                q = lax.all_to_all(q, AXIS, split_axis=0, concat_axis=0,
                                   tiled=True)
                s = lax.all_to_all(s, AXIS, split_axis=0, concat_axis=0,
                                   tiled=True)
                red = jnp.sum(codec.decode(q, s, block), axis=0)
                if op == reduce_ops.Average:
                    red = red / n
                red = _scale(red, post)
                q2, s2 = codec.encode(red, block)
                qg = lax.all_gather(q2, AXIS, tiled=True)
                sg = lax.all_gather(s2, AXIS, tiled=True)
                out = codec.decode(qg, sg, block)
                outs, errs, off = [], [], 0
                for size in sizes:
                    outs.append(out[off:off + size])
                    errs.append(err[off:off + size])
                    off += size
                return outs, errs

            def body(scales, xs, es):
                pre, post = scales
                flats = []
                for i, x in enumerate(xs):
                    f = _scale(x.reshape(-1).astype(jnp.float32), pre)
                    if es is not None:
                        f = f + es[i].reshape(-1)
                    flats.append(f)
                outs, errs = pipeline(flats, post)
                res, out_errs = [], []
                for x, o, err in zip(xs, outs, errs):
                    res.append(o.reshape(x.shape).astype(x.dtype))
                    out_errs.append(err.reshape(x.shape))
                if es is None:
                    return tuple(res)
                return tuple(res), tuple(out_errs)

            in_specs = ((P(), P(AXIS), P(AXIS)) if ef
                        else (P(), P(AXIS), None))
            sm = _shard_map(body, mesh=mesh, in_specs=in_specs,
                            out_specs=P(AXIS))
            return jax.jit(sm)

        fn = self._cached(key, build)
        pre = jnp.asarray(1.0 if prescale is None else prescale,
                          dtype=jnp.float32)
        post = jnp.asarray(1.0 if postscale is None else postscale,
                           dtype=jnp.float32)
        ins = tuple(self.shard(process_set, jnp.asarray(a))
                    for a in arrays)
        if ef:
            res_in = tuple(self.shard(process_set, jnp.asarray(r))
                           for r in residuals)
            outs, errs = fn((pre, post), ins, res_in)
            return list(outs), list(errs)
        return list(fn((pre, post), ins, None)), None

    # -- allgather ---------------------------------------------------------
    @_timed("allgather")
    def allgather(self, arrays, process_set):
        """Stacked allgather: (n, s0, ...) → (n, n*s0, ...), every slice the
        concatenation of all ranks' tensors (reference displacement logic:
        horovod/common/ops/collective_operations.h:129-179 — on TPU,
        lax.all_gather replaces the explicit displacement math)."""
        mesh = self._mesh(process_set)
        key = ("ag", process_set.process_set_id)

        def build():
            def body(*xs):
                outs = []
                for x in xs:
                    # Local block is (1, s0, ...); the gather stacks every
                    # rank's tensor then flattens to the concatenation.
                    g = lax.all_gather(x, AXIS, axis=0, tiled=True)
                    outs.append(g.reshape((-1,) + g.shape[2:])[None])
                return tuple(outs)
            sm = _shard_map(body, mesh=mesh, in_specs=P(AXIS),
                               out_specs=P(AXIS))
            return jax.jit(sm)

        fn = self._cached(key, build)
        ins = tuple(self.shard(process_set, jnp.asarray(a)) for a in arrays)
        return list(fn(*ins))

    @_timed("allgather")
    def allgather_uneven(self, per_rank_lists, process_set):
        """Allgather of per-rank tensors with differing dim-0 sizes.

        Data is already resident in this process, so "gathering" is a
        concatenation that XLA materializes replicated across the mesh.
        Returns stacked (n, total, ...) arrays for consistency with the
        equal-shape path.
        """
        mesh = self._mesh(process_set)
        n = mesh.devices.size
        sharding = NamedSharding(mesh, P(AXIS))
        outs = []
        for parts in per_rank_lists:
            full = np.concatenate([np.asarray(p) for p in parts], axis=0)
            block = full[None]
            # Build the stacked (n, total, ...) result shard-by-shard:
            # each device receives its (1, total, ...) block directly —
            # never materializing the n-fold (n, total, ...) copy that
            # broadcast_to would allocate before sharding.
            # Every stacked slice is identical, so each device's
            # (1, total, ...) shard IS the block, whatever its index.
            outs.append(jax.make_array_from_callback(
                (n,) + full.shape, sharding, lambda idx, b=block: b))
        return outs

    def replicate_stacked(self, array, process_set):
        """Stacked (n, ...) result with every slice == ``array``, built
        shard-by-shard like :meth:`allgather_uneven`: each mesh device
        receives one (1, ...) block directly — never materializing the
        n-fold copy ``broadcast_to`` would allocate before sharding
        (at bench geometry, GBs of identical replicas on one device)."""
        mesh = self._mesh(process_set)
        n = mesh.devices.size
        sharding = NamedSharding(mesh, P(AXIS))
        block = np.asarray(array)[None]
        return jax.make_array_from_callback(
            (n,) + block.shape[1:], sharding, lambda idx: block)

    # -- broadcast ---------------------------------------------------------
    @_timed("broadcast")
    def broadcast(self, arrays, root_rank, process_set):
        """Stacked broadcast: every virtual rank receives slice ``root_rank``
        (reference: BroadcastOp, horovod/common/ops/collective_operations.h:181)."""
        mesh = self._mesh(process_set)
        key = ("bc", process_set.process_set_id, root_rank)

        def build():
            def body(*xs):
                # Masked psum instead of gather-then-index: O(block)
                # device memory at any mesh size (the gather holds n
                # blocks per device before indexing one).
                return tuple(_psum_broadcast(x, root_rank) for x in xs)
            sm = _shard_map(body, mesh=mesh, in_specs=P(AXIS),
                               out_specs=P(AXIS))
            return jax.jit(sm)

        fn = self._cached(key, build)
        ins = tuple(self.shard(process_set, jnp.asarray(a)) for a in arrays)
        return list(fn(*ins))

    # -- alltoall ----------------------------------------------------------
    @_timed("alltoall")
    def alltoall(self, array, splits, process_set):
        """Stacked alltoall (reference: AlltoallOp::PrepareOutputAndParams,
        horovod/common/ops/collective_operations.h:195-273).

        ``array``: stacked (n, s0, ...); ``splits``: (n, n) host matrix where
        splits[r] partitions rank r's dim-0. Returns (list of per-rank
        outputs, recv_splits matrix). Uniform splits take the fast
        lax.all_to_all path; ragged splits compile a slicing program.
        """
        mesh = self._mesh(process_set)
        n = mesh.devices.size
        x = jnp.asarray(array)
        if splits is None:
            if x.shape[1] % n != 0:
                raise ValueError(
                    f"alltoall tensor dim0 {x.shape[1]} not divisible by "
                    f"process set size {n} and no splits given")
            splits = np.full((n, n), x.shape[1] // n, dtype=np.int64)
        else:
            splits = np.asarray(splits, dtype=np.int64)
            if splits.ndim == 1:
                splits = np.tile(splits, (n, 1))
        if splits.shape != (n, n):
            raise ValueError(f"splits must be ({n},{n}), got {splits.shape}")
        if np.any(splits.sum(axis=1) != x.shape[1]):
            raise ValueError("splits must sum to tensor dim0 per rank")
        recv_splits = splits.T.copy()

        uniform = np.all(splits == splits[0, 0])
        if uniform:
            key = ("a2a", process_set.process_set_id)

            def build():
                def body(x):
                    # Local block (1, s0, ...): split dim 1 into n pieces,
                    # exchange, stack received pieces source-major, flatten
                    # back to (1, s0, ...) — the concatenation of everyone's
                    # piece for this rank.
                    y = lax.all_to_all(x, AXIS, split_axis=1, concat_axis=0,
                                       tiled=True)
                    return y.reshape((1, -1) + y.shape[2:])
                sm = _shard_map(body, mesh=mesh, in_specs=P(AXIS),
                                   out_specs=P(AXIS))
                return jax.jit(sm)

            fn = self._cached(key, build)
            out = fn(self.shard(process_set, x))
            return [out[r] for r in range(n)], recv_splits

        # Ragged path: static-shape slicing program, cached by jit on shapes
        # and by tuple(splits) via static closure.
        key = ("a2a_ragged", process_set.process_set_id,
               tuple(splits.flatten().tolist()))

        def build():
            offs = np.zeros((n, n), dtype=np.int64)
            offs[:, 1:] = np.cumsum(splits, axis=1)[:, :-1]

            def fn(x):
                outs = []
                for r in range(n):
                    parts = [lax.slice_in_dim(x[s], int(offs[s, r]),
                                              int(offs[s, r] + splits[s, r]),
                                              axis=0)
                             for s in range(n)]
                    outs.append(jnp.concatenate(parts, axis=0))
                return tuple(outs)
            return jax.jit(fn)

        fn = self._cached(key, build)
        outs = fn(self.shard(process_set, x))
        return list(outs), recv_splits

    # -- reducescatter -----------------------------------------------------
    @_timed("reducescatter")
    def reducescatter(self, arrays, op, process_set):
        """Stacked reduce-scatter: (n, s0, ...) → list of per-rank chunks of
        the reduction, dim-0 partitioned like the reference (earlier ranks
        take the remainder, reference: horovod/common/ops/
        collective_operations.cc ReducescatterOp)."""
        if op not in (reduce_ops.Sum, reduce_ops.Average):
            raise ValueError("reducescatter supports Sum/Average")
        mesh = self._mesh(process_set)
        n = mesh.devices.size
        outs = []
        even = all(jnp.asarray(a).shape[1] % n == 0 for a in arrays)
        if even:
            key = ("rs", process_set.process_set_id, op)

            def build():
                def body(*xs):
                    res = []
                    for x in xs:
                        y = lax.psum_scatter(x, AXIS, scatter_dimension=1,
                                             tiled=True)
                        if op == reduce_ops.Average:
                            y = (y / n).astype(x.dtype)
                        res.append(y)
                    return tuple(res)
                sm = _shard_map(body, mesh=mesh, in_specs=P(AXIS),
                                   out_specs=P(AXIS))
                return jax.jit(sm)

            fn = self._cached(key, build)
            ins = tuple(self.shard(process_set, jnp.asarray(a))
                        for a in arrays)
            return list(fn(*ins))
        # Ragged: reduce fully, slice per rank on host-defined boundaries.
        reduced = self.allreduce(arrays, op, process_set)
        for full in reduced:
            s0 = full.shape[1]
            base, rem = divmod(s0, n)
            sizes = [base + (1 if r < rem else 0) for r in range(n)]
            offs = np.concatenate([[0], np.cumsum(sizes)])
            chunks = [full[r, int(offs[r]):int(offs[r + 1])]
                      for r in range(n)]
            outs.append(chunks)
        return outs

    # -- barrier / join ----------------------------------------------------
    def barrier(self, process_set):
        # Single controller: device-sync all outstanding work on the mesh.
        token = self.allreduce([jnp.zeros((self._mesh(process_set)
                                           .devices.size, 1))],
                               reduce_ops.Sum, process_set)[0]
        jax.block_until_ready(token)

    def close(self):
        self._fns.clear()
