"""MXNet binding (reference: horovod/mxnet/__init__.py:42
``DistributedOptimizer``, ``broadcast_parameters``).

MXNet is deprecated upstream (archived by Apache) and is not shipped in
TPU images; this adapter gates with a clear error. The surface mirrors
the reference so legacy scripts fail with guidance rather than
AttributeError, and runs if a user installs mxnet themselves: gradients
ride the same process-level collectives as the torch binding.
"""

from .. import basics
from ..ops import reduce_ops

Average = reduce_ops.Average
Sum = reduce_ops.Sum

init = basics.init
shutdown = basics.shutdown
is_initialized = basics.is_initialized
local_rank = basics.local_rank
local_size = basics.local_size


def rank():
    return basics.runtime().topology.rank


def size():
    return basics.runtime().topology.size


def _mxnet():
    try:
        import mxnet
        return mxnet
    except ImportError as e:
        raise ImportError(
            "horovod_tpu.mxnet requires mxnet, which is not installed "
            "(MXNet is archived upstream and not shipped in TPU images; "
            "`pip install mxnet` to use this legacy binding, or port the "
            "script to horovod_tpu.torch / horovod_tpu.jax).") from e


def _np_collective(fn):
    """Run an eager collective over an NDArray via numpy."""
    import numpy as np

    def wrapped(nd, *args, **kwargs):
        mx = _mxnet()
        out = fn(nd.asnumpy(), *args, **kwargs)
        return mx.nd.array(np.asarray(out), ctx=nd.context,
                           dtype=nd.dtype)
    return wrapped


def allreduce(tensor, average=True, name=None, priority=0):
    """Reference: horovod/mxnet/mpi_ops.py allreduce."""
    _mxnet()
    from ..ops import collectives as _c
    op = Average if average else Sum
    return _np_collective(
        lambda a: _c.allreduce(a, op=op, name=name))(tensor)


def broadcast_parameters(params, root_rank=0):
    """Reference: horovod/mxnet/__init__.py:226 broadcast_parameters.
    Accepts NDArray dicts AND gluon ParameterDicts (Block.collect_params()
    values are Parameter objects read via .data() / written via
    .set_data(), reference :255)."""
    mx = _mxnet()
    import numpy as np
    from ..functions import broadcast_variables
    if hasattr(params, "items"):
        items = sorted(params.items())
    else:
        items = sorted(params)

    def read(v):
        return v.data().asnumpy() if hasattr(v, "set_data") else v.asnumpy()

    arrays = [read(v) for _, v in items]
    outs = broadcast_variables(arrays, root_rank=root_rank)
    for (name, v), out in zip(items, outs):
        out = np.asarray(out)
        if hasattr(v, "set_data"):
            v.set_data(mx.nd.array(out, dtype=out.dtype))
        else:
            v[:] = out


def DistributedOptimizer(optimizer):
    """Wrap an mxnet optimizer so update() allreduces gradients first
    (reference: horovod/mxnet/__init__.py:42)."""
    mx = _mxnet()
    import numpy as np
    from ..ops import collectives as _c

    class _Distributed(mx.optimizer.Optimizer):
        """Two-way proxy: reads AND writes route to the inner optimizer —
        the gluon Trainer sets rescale_grad/lr on the optimizer every
        step, and a one-way proxy would silently drop them."""

        def __init__(self, opt):
            self.__dict__["_opt"] = opt

        def __getattr__(self, item):
            return getattr(self.__dict__["_opt"], item)

        def __setattr__(self, key, value):
            setattr(self.__dict__["_opt"], key, value)

        def _reduce(self, index, grad):
            reduced = _c.allreduce(grad.asnumpy(), op=Average,
                                   name=f"grad.{index}")
            grad[:] = np.asarray(reduced)

        def update(self, index, weight, grad, state):
            self._reduce(index, grad)
            return self._opt.update(index, weight, grad, state)

        def update_multi_precision(self, index, weight, grad, state):
            # The gluon Trainer path calls this, not update(); without the
            # override gradients would silently skip the allreduce
            # (reference: horovod/mxnet/__init__.py:92).
            self._reduce(index, grad)
            return self._opt.update_multi_precision(index, weight, grad,
                                                    state)

    return _Distributed(optimizer)
