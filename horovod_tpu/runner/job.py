"""Static job launch: rendezvous server + slot spawn + monitoring.

The analog of the reference's gloo launch path (reference:
horovod/runner/gloo_run.py:240 ``launch_gloo``): start the in-driver
rendezvous server, compute slot assignments, build per-slot env, spawn
every slot, and tear the job down as a unit — first failure kills the
rest, matching horovodrun's all-or-nothing semantics.
"""

import time

from . import spawn
from .hosts import HostInfo, get_host_assignments, parse_hostfile, \
    parse_hosts
from .http_server import RendezvousServer, new_job_token


class Settings:
    """Launcher configuration (subset of the reference's ~60 flags that
    is meaningful on TPU; reference: horovod/runner/launch.py:242)."""

    def __init__(self, num_proc=1, hosts=None, hostfile=None,
                 start_timeout=120, verbose=False, prefix_output=True,
                 env=None, rendezvous_addr=None, output_filename=None,
                 ssh_port=None, ssh_identity_file=None):
        self.num_proc = num_proc
        self.hosts = hosts
        self.hostfile = hostfile
        self.start_timeout = start_timeout
        self.verbose = verbose
        self.prefix_output = prefix_output
        self.env = dict(env or {})   # extra env forwarded to every slot
        self.rendezvous_addr = rendezvous_addr
        # Directory for per-rank rank.N/stdout|stderr capture (reference:
        # horovodrun --output-filename).
        self.output_filename = output_filename
        # Remote-spawn ssh options (reference: horovodrun --ssh-port /
        # --ssh-identity-file).
        self.ssh_port = ssh_port
        self.ssh_identity_file = ssh_identity_file

    def resolve_hosts(self):
        if self.hosts:
            return parse_hosts(self.hosts)
        if self.hostfile:
            return parse_hostfile(self.hostfile)
        return [HostInfo("localhost", self.num_proc)]


def _rendezvous_ip(slots):
    """Address workers use to reach the driver's KV store."""
    if all(spawn.is_local(s.hostname) for s in slots):
        return "127.0.0.1"
    import socket
    return socket.gethostbyname(socket.getfqdn())


def launch_job(settings, command):
    """Run ``command`` (argv list) across all slots; returns the job's
    exit code (0 only when every rank exits 0)."""
    slots = get_host_assignments(settings.resolve_hosts(), settings.num_proc)
    spawn.reset_capture_dir(settings.output_filename)
    token = new_job_token()
    server = RendezvousServer(job_token=token, verbose=settings.verbose)
    port = server.start()
    server.publish_assignments(slots)
    addr = settings.rendezvous_addr or _rendezvous_ip(slots)

    procs = []
    try:
        for slot in slots:
            env = dict(settings.env)
            env.update(slot.to_env())
            env.update({
                "HVDTPU_RENDEZVOUS_ADDR": addr,
                "HVDTPU_RENDEZVOUS_PORT": str(port),
                "HVDTPU_JOB_TOKEN": token,
                "HVDTPU_START_TIMEOUT": str(settings.start_timeout),
            })
            procs.append(spawn.SlotProcess(
                slot, command, env, prefix_output=settings.prefix_output,
                output_dir=settings.output_filename,
                ssh_port=settings.ssh_port,
                ssh_identity_file=settings.ssh_identity_file))

        return _monitor(procs, settings)
    finally:
        for p in procs:
            p.terminate()
        deadline = time.monotonic() + 5
        for p in procs:
            if p.poll() is None and time.monotonic() < deadline:
                try:
                    p.proc.wait(max(0.1, deadline - time.monotonic()))
                except Exception:  # noqa: BLE001
                    pass
        for p in procs:
            p.kill()
        server.stop()


def _monitor(procs, settings):
    """Wait for all slots; on first nonzero exit, give the rest a grace
    period then kill (the native core's consensus shutdown usually lets
    peers exit cleanly first)."""
    pending = list(procs)
    first_bad = 0
    fail_deadline = None
    while pending:
        for p in list(pending):
            rc = p.poll()
            if rc is None:
                continue
            p.wait()
            pending.remove(p)
            if rc != 0 and first_bad == 0:
                first_bad = rc
                fail_deadline = time.monotonic() + 10
                if settings.verbose:
                    print(f"hvdrun: rank {p.slot.rank} exited with "
                          f"code {rc}; terminating remaining ranks")
        if fail_deadline is not None and time.monotonic() > fail_deadline:
            for p in pending:
                p.terminate()
            fail_deadline = time.monotonic() + 1e9  # terminate once
        time.sleep(0.05)
    return first_bad
