"""Worker heartbeat lease + driver-side liveness tracking.

Closes the hung-worker gap: the elastic driver's ``_sweep_exits`` only
notices workers that *exit*, never workers that *hang* (a wedged NFS
mount, a deadlocked extension, a SIGSTOPped process). Each worker runs
a background thread that PUTs ``heartbeat/<worker_id>`` into the
driver's KV store every ``HVDTPU_HEARTBEAT_INTERVAL`` seconds; the
driver fails any worker whose published value stops *changing* for
``HVDTPU_HEARTBEAT_TIMEOUT`` seconds (0 disables).

Liveness is clock-skew free by construction: the beat value is
``<pid>:<count>`` — an opaque token the driver compares for *change*
against its own monotonic clock, never a timestamp compared across
hosts. The pid prefix makes a respawned worker's stream distinct from
its predecessor's, so a fresh process restarting the counter still
reads as "changed".

A worker that has never published a beat is NOT subject to the timeout:
process startup (imports, jax init, rendezvous) is governed by the
launcher's start timeout, and judging it by heartbeat silence would
just re-implement that timeout with a harsher penalty.
"""

import os
import threading
import time

from ..chaos import inject as _chaos_inject
from ..telemetry import core as telemetry
from ..utils import envparse
from ..utils.logging_util import get_logger

HEARTBEAT_SCOPE = "heartbeat"
DEFAULT_INTERVAL_S = 2.0
DEFAULT_TIMEOUT_S = 30.0
#: Consecutive beat failures before ONE warning names the endpoint —
#: a partitioned worker becomes diagnosable from its own log before
#: the driver declares it dead (errors stay swallowed regardless).
ERROR_WARN_STREAK = 5


def heartbeat_interval():
    return envparse.get_float(envparse.HEARTBEAT_INTERVAL,
                              DEFAULT_INTERVAL_S)


def heartbeat_timeout():
    return envparse.get_float(envparse.HEARTBEAT_TIMEOUT,
                              DEFAULT_TIMEOUT_S)


class HeartbeatThread:
    """Background lease renewal. Beat failures are swallowed (counted,
    logged at debug): liveness reporting must never kill a live worker
    — if the store is really gone, collectives and commits will surface
    it with a better error, and the driver's timeout judges us anyway."""

    def __init__(self, addr, port, token, worker_id, interval_s=None):
        self._addr = addr
        self._port = port
        self._token = token
        self._worker_id = worker_id
        self._interval = (heartbeat_interval() if interval_s is None
                          else interval_s)
        self._stop = threading.Event()
        self._thread = None
        self._count = 0
        self._log = get_logger()
        self._m_beats = telemetry.counter(
            "hvd_heartbeat_beats_total",
            "Worker heartbeat lease renewals", labelnames=("outcome",))
        self._m_errors = telemetry.counter(
            "hvd_heartbeat_errors_total",
            "Worker beat failures (error) and streak-ending successes "
            "(recovered)", labelnames=("outcome",))
        self._consec_errors = 0

    def start(self):
        if self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._loop, name="hvd-tpu-heartbeat", daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout=2.0):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None

    def _loop(self):
        from . import http_client
        while not self._stop.is_set():
            self._count += 1
            try:
                _chaos_inject("heartbeat", wid=self._worker_id)
                # Tight retry budget: a beat that cannot land within one
                # interval is worth less than the NEXT beat — backing up
                # stale beats behind a long retry would delay detection.
                http_client.put_kv(
                    self._addr, self._port, HEARTBEAT_SCOPE,
                    self._worker_id, f"{os.getpid()}:{self._count}",
                    token=self._token, retries=1,
                    deadline=max(self._interval, 1.0))
                self._m_beats.labels(outcome="ok").inc()
                if self._consec_errors:
                    self._m_errors.labels(outcome="recovered").inc()
                    self._log.info(
                        "heartbeat: beat landed again after %d "
                        "consecutive failures", self._consec_errors)
                    self._consec_errors = 0
            except Exception as e:  # noqa: BLE001 — never kill the worker
                self._m_beats.labels(outcome="error").inc()
                self._m_errors.labels(outcome="error").inc()
                self._consec_errors += 1
                if self._consec_errors == ERROR_WARN_STREAK:
                    # Previously these were swallowed at debug level
                    # FOREVER — a worker partitioned from the control
                    # plane looked healthy in its own log right up to
                    # the moment the driver killed it as hung. One
                    # warning per streak, naming where the beats were
                    # going.
                    addr, port = http_client.active_endpoint(
                        self._addr, self._port)
                    self._log.warning(
                        "heartbeat: %d consecutive beat failures "
                        "against %s:%d (last: %s) — this worker may be "
                        "partitioned from the control plane; the "
                        "driver will declare it hung after "
                        "HVDTPU_HEARTBEAT_TIMEOUT", self._consec_errors,
                        addr, port, e)
                else:
                    self._log.debug("heartbeat: beat %d failed: %s",
                                    self._count, e)
            self._stop.wait(self._interval)


class LivenessTracker:
    """Driver-side change detection over beat values. ``observe``
    returns True when ``wid`` is expired: its value has been seen
    unchanged for longer than ``timeout_s`` of the local clock."""

    def __init__(self, timeout_s):
        self.timeout_s = timeout_s
        self._seen = {}  # wid -> [value, last_change_monotonic]

    def observe(self, wid, value, now=None):
        if now is None:
            now = time.monotonic()
        rec = self._seen.get(wid)
        if rec is None or rec[0] != value:
            self._seen[wid] = [value, now]
            return False
        return (now - rec[1]) > self.timeout_s

    def age(self, wid, now=None):
        rec = self._seen.get(wid)
        if rec is None:
            return 0.0
        return (time.monotonic() if now is None else now) - rec[1]

    def forget(self, wid):
        self._seen.pop(wid, None)


# -- worker-side process singleton ----------------------------------------
# One lease per process for its whole lifetime: elastic re-inits must
# NOT stop the beat (a worker mid-reset is alive and must read as such),
# so this is started once by basics.init and left running; the daemon
# thread dies with the process.

_worker_thread = None


def start_worker_heartbeat():
    """Start the lease thread for this worker (idempotent). No-op when
    the job has no launcher rendezvous or no worker id — nothing to
    lease against. Returns the HeartbeatThread or None."""
    global _worker_thread
    if _worker_thread is not None:
        return _worker_thread
    from . import rendezvous as rdv
    cfg = rdv.rendezvous_config()
    worker_id = envparse.get_str(envparse.WORKER_ID)
    if cfg is None or not worker_id:
        return None
    addr, port, token = cfg
    _worker_thread = HeartbeatThread(addr, port, token,
                                     worker_id).start()
    return _worker_thread


def stop_worker_heartbeat():
    """Test hook: stop and forget the process singleton."""
    global _worker_thread
    if _worker_thread is not None:
        _worker_thread.stop()
        _worker_thread = None
