"""Driver-side elasticity: discovery, stable rank assignment, blacklist,
re-rendezvous, worker respawn.

The analog of the reference's ElasticDriver + WorkerStateRegistry +
HostDiscoveryScript (reference: horovod/runner/elastic/driver.py:68-314,
registration.py:28-150, discovery.py:80-185): a discovery script is polled
every second for the current ``host:slots`` membership; on change (or on a
worker failure) the driver bumps the membership **version**, publishes new
rank assignments to its KV store, spawns workers for new slots, and lets
surviving workers re-rendezvous under the new version. Failed hosts are
blacklisted after repeated worker failures. On TPU the discovery script is
where slice preemption signals surface (a preempted TPU-VM host simply
drops out of the script's output).
"""

import subprocess
import time
from types import SimpleNamespace

from . import spawn
from . import heartbeat as heartbeat_mod
from .hosts import HostInfo
from .http_server import RendezvousServer, new_job_token
from .job import _rendezvous_ip
from ..exceptions import PREEMPT_EXIT_CODE, RESTART_EXIT_CODE
from .rendezvous import ASSIGN_SCOPE, ELASTIC_SCOPE, PEER_SCOPE, VERSION_KEY
from ..telemetry import core as telemetry
from ..utils import envparse
from ..utils.logging_util import get_logger

RUNNING, SUCCEEDED, FAILED = "running", "succeeded", "failed"


def _check_heartbeat_config(timeout_s, worker_env):
    """True (and a warning logged) when the liveness timeout is below
    ~2 beat intervals — every healthy worker would read as hung and be
    killed on repeat, with logs blaming the workers instead of the
    configuration. The interval is read from the WORKER env when the
    job overrides it there, else from this process's knobs."""
    if timeout_s <= 0:
        return False
    interval = None
    for prefix in ("HVDTPU_", "HOROVOD_TPU_", "HOROVOD_"):
        value = (worker_env or {}).get(prefix + "HEARTBEAT_INTERVAL")
        if value:
            try:
                interval = float(value)
            except ValueError:
                pass
            break
    if interval is None:
        interval = heartbeat_mod.heartbeat_interval()
    if timeout_s < 2 * interval:
        get_logger().warning(
            "elastic driver: heartbeat timeout %.1fs is below twice the "
            "worker beat interval %.1fs — healthy workers WILL be "
            "failed as hung; raise HVDTPU_HEARTBEAT_TIMEOUT or lower "
            "HVDTPU_HEARTBEAT_INTERVAL", timeout_s, interval)
        return True
    return False


class ElasticSettings:
    def __init__(self, settings, discovery_script=None, min_np=1,
                 max_np=None, reset_limit=None, host_fail_limit=3,
                 discovery_interval=1.0, heartbeat_timeout=None,
                 sigkill_deadline=None):
        self.base = settings
        self.discovery_script = discovery_script
        self.min_np = min_np
        self.max_np = max_np
        self.reset_limit = reset_limit
        self.host_fail_limit = host_fail_limit
        self.discovery_interval = discovery_interval
        # Liveness: a worker whose heartbeat lease stops moving for this
        # long is failed (0 disables; docs/fault_tolerance.md).
        self.heartbeat_timeout = (
            heartbeat_mod.heartbeat_timeout() if heartbeat_timeout is None
            else heartbeat_timeout)
        # SIGTERM->SIGKILL escalation window for workers being stopped.
        self.sigkill_deadline = (
            envparse.get_float(envparse.SIGKILL_DEADLINE, 10.0)
            if sigkill_deadline is None else sigkill_deadline)
        _check_heartbeat_config(self.heartbeat_timeout,
                                getattr(settings, "env", None))


class HostDiscovery:
    """Poll the user's discovery script for the current host set
    (reference: discovery.py:152-175 ``HostDiscoveryScript``). Fixed-host
    fallback uses the static -H/--hostfile list."""

    def __init__(self, elastic_settings):
        self._settings = elastic_settings

    def find_available_hosts(self):
        script = self._settings.discovery_script
        if not script:
            return self._settings.base.resolve_hosts()
        try:
            proc = subprocess.run(script, shell=True, capture_output=True,
                                  timeout=30)
        except subprocess.TimeoutExpired:
            raise RuntimeError("host discovery script timed out (30s)")
        if proc.returncode != 0:
            raise RuntimeError(
                f"host discovery script failed (exit {proc.returncode}): "
                f"{proc.stderr.decode(errors='replace')[:500]}")
        hosts = []
        for line in proc.stdout.decode().splitlines():
            line = line.strip()
            if line:
                hosts.append(HostInfo.from_string(line))
        return hosts


class _Worker:
    __slots__ = ("worker_id", "host", "slot_index", "proc", "state")

    def __init__(self, worker_id, host, slot_index, proc):
        self.worker_id = worker_id
        self.host = host
        self.slot_index = slot_index
        self.proc = proc
        self.state = RUNNING


class ElasticDriver:
    """Owns the rendezvous server and the worker fleet for one job."""

    def __init__(self, elastic, command, discovery=None):
        self.elastic = elastic
        self.command = command
        # Pluggable membership source: anything with find_available_hosts()
        # -> [HostInfo]. The Ray integration substitutes actor-cluster
        # discovery here (ray/elastic.py RayHostDiscovery).
        self.discovery = discovery or HostDiscovery(elastic)
        self.token = new_job_token()
        self.server = RendezvousServer(job_token=self.token,
                                       verbose=elastic.base.verbose)
        self.port = self.server.start()
        self.addr = None
        self.version = -1
        self.workers = {}        # worker_id -> _Worker (running only)
        self.stopping = []       # (worker, sigkill_deadline) being reaped
        self.rank_order = []     # worker_ids in rank order
        self.blacklist = set()
        self.fail_counts = {}
        self.resets = 0
        self.completing = False
        self.succeeded = []
        self.log = get_logger()
        self._last_targets = []
        self._discovery_failures = 0
        # Driver-side elastic counters (NULL no-ops when metrics off).
        self._m_resets = telemetry.counter(
            "hvd_elastic_driver_resets_total",
            "Membership versions published after the initial cohort")
        self._m_worker_failures = telemetry.counter(
            "hvd_elastic_driver_worker_failures_total",
            "Worker processes that exited non-zero")
        self._m_blacklisted = telemetry.gauge(
            "hvd_elastic_driver_blacklisted_hosts",
            "Hosts excluded after repeated worker failures")
        self._m_heartbeat_failures = telemetry.counter(
            "hvd_elastic_driver_heartbeat_failures_total",
            "Workers failed for missing their heartbeat lease")
        self._liveness = heartbeat_mod.LivenessTracker(
            self.elastic.heartbeat_timeout)

    DISCOVERY_FAIL_LIMIT = 30  # consecutive failures before aborting

    # -- membership ------------------------------------------------------
    def _discover_targets(self):
        """(worker_id, host, slot_index) for every slot in the current
        discovery output, minus blacklisted hosts, capped at max_np. A
        transient discovery failure keeps the last known membership —
        flaky cloud APIs are exactly what elastic mode exists for."""
        try:
            hosts = [h for h in self.discovery.find_available_hosts()
                     if h.hostname not in self.blacklist]
            self._discovery_failures = 0
        except RuntimeError as e:
            self._discovery_failures += 1
            self.log.warning(
                "elastic driver: discovery failed (%d consecutive): %s",
                self._discovery_failures, e)
            if self._discovery_failures >= self.DISCOVERY_FAIL_LIMIT:
                raise
            return self._last_targets
        slots = []
        cap = self.elastic.max_np or float("inf")
        for h in hosts:
            for idx in range(h.slots):
                if len(slots) >= cap:
                    break
                slots.append((f"{h.hostname}:{idx}", h.hostname, idx))
            if len(slots) >= cap:
                break
        self._last_targets = slots
        return slots

    def _publish(self):
        """Compute stable rank order and publish assignment version N.
        Surviving workers keep their relative order (and therefore the
        lowest ranks — rank 0 is always a survivor, which is what makes
        ``state.sync()`` broadcast-from-0 correct); new workers append
        (reference: driver.py:232-276 stable host ordering)."""
        alive = [wid for wid in self.rank_order if wid in self.workers]
        alive += [wid for wid in self.workers if wid not in alive]
        self.rank_order = alive
        size = len(alive)

        # Host-level grouping for local/cross ranks.
        host_of = {wid: self.workers[wid].host for wid in alive}
        local_rank = {}
        local_counts = {}
        for wid in alive:
            h = host_of[wid]
            local_rank[wid] = local_counts.get(h, 0)
            local_counts[h] = local_rank[wid] + 1
        host_order = list(dict.fromkeys(host_of[wid] for wid in alive))

        scope = f"{ASSIGN_SCOPE}.{self.version}"
        for rank, wid in enumerate(alive):
            h = host_of[wid]
            lr = local_rank[wid]
            hosts_at_lr = [x for x in host_order if local_counts[x] > lr]
            line = (f"{rank},{size},{lr},{local_counts[h]},"
                    f"{hosts_at_lr.index(h)},{len(hosts_at_lr)}")
            self.server.put(scope, wid, line)
        self.server.put(ELASTIC_SCOPE, VERSION_KEY, str(self.version))
        self.log.info("elastic driver: published version %d with %d "
                      "workers", self.version, size)

    def _spawn(self, worker_id, host, slot_index):
        # Belt and braces for the never-beaten exemption: whatever path
        # led here, the fresh process must not inherit a stale lease.
        self._drop_heartbeat(worker_id)
        env = dict(self.elastic.base.env)
        env.update({
            "HVDTPU_ELASTIC": "1",
            "HVDTPU_WORKER_ID": worker_id,
            "HVDTPU_RENDEZVOUS_ADDR": self.addr,
            "HVDTPU_RENDEZVOUS_PORT": str(self.port),
            "HVDTPU_JOB_TOKEN": self.token,
            "HVDTPU_START_TIMEOUT": str(self.elastic.base.start_timeout),
        })
        slot = SimpleNamespace(hostname=host, rank=worker_id)
        proc = spawn.SlotProcess(
            slot, self.command, env,
            prefix_output=self.elastic.base.prefix_output,
            output_dir=self.elastic.base.output_filename,
            ssh_port=self.elastic.base.ssh_port,
            ssh_identity_file=self.elastic.base.ssh_identity_file)
        self.workers[worker_id] = _Worker(worker_id, host, slot_index, proc)

    def _reconcile(self, targets):
        """Diff targets vs running workers; returns True when membership
        changed (spawn/kill happened)."""
        target_ids = {t[0] for t in targets}
        changed = False
        for wid in list(self.workers):
            if wid not in target_ids:
                w = self.workers.pop(wid)
                if wid in self.rank_order:
                    self.rank_order.remove(wid)
                w.proc.terminate()
                self.stopping.append(
                    (w, time.monotonic() + self.elastic.sigkill_deadline))
                self._drop_heartbeat(wid)
                self.log.info("elastic driver: host removed, stopping %s",
                              wid)
                changed = True
        for wid, host, idx in targets:
            if wid not in self.workers:
                self._spawn(wid, host, idx)
                self.log.info("elastic driver: spawned worker %s", wid)
                changed = True
        return changed

    def _reap_stopping(self):
        """Reap scale-down terminations (no zombies) and escalate to
        SIGKILL for workers that ignore SIGTERM."""
        still = []
        now = time.monotonic()
        for w, kill_at in self.stopping:
            if w.proc.poll() is not None:
                w.proc.wait()
                # The lease may have been re-published between the stop
                # request and the actual exit (a SIGTERM-trapping worker
                # keeps beating until its commit-boundary hand-off);
                # retire it NOW so a respawn of the same slot is judged
                # by its own beats, not a dead predecessor's frozen one.
                # UNLESS the slot was already respawned: the lease then
                # belongs to the live successor — deleting it would
                # blind hung-worker detection until its next beat.
                if w.worker_id not in self.workers:
                    self._drop_heartbeat(w.worker_id)
                continue
            if now > kill_at:
                w.proc.kill()
            still.append((w, kill_at))
        self.stopping = still

    def _drop_heartbeat(self, wid):
        """Forget a worker's liveness state and retire its lease key so
        a respawn of the same slot starts with a clean record."""
        self._liveness.forget(wid)
        self.server.delete(heartbeat_mod.HEARTBEAT_SCOPE, wid)

    def _count_host_failure(self, host):
        """Failure accounting + blacklist escalation, shared by the
        exit sweep and the heartbeat detector (one place to keep the
        policy from drifting)."""
        self.fail_counts[host] = self.fail_counts.get(host, 0) + 1
        if self.fail_counts[host] >= self.elastic.host_fail_limit:
            self.blacklist.add(host)
            self._m_blacklisted.set(len(self.blacklist))
            self.log.warning(
                "elastic driver: blacklisting host %s after %d "
                "failures", host, self.fail_counts[host])

    def _check_heartbeats(self):
        """Fail workers whose heartbeat lease stopped moving — the
        hung-worker detector (`_sweep_exits` only sees exits). A missed
        lease takes the same exit ramp as a crash: SIGTERM now, SIGKILL
        after ``sigkill_deadline`` via the stopping reaper, a failure
        count against the host, and a membership change so survivors
        re-rendezvous. Workers that never published a beat are exempt
        (startup is the start timeout's jurisdiction). Returns True when
        membership changed."""
        if self.elastic.heartbeat_timeout <= 0 or self.completing:
            return False
        changed = False
        now = time.monotonic()
        for wid in list(self.workers):
            value = self.server.get(heartbeat_mod.HEARTBEAT_SCOPE, wid)
            if value is None:
                continue
            if not self._liveness.observe(wid, value, now):
                continue
            w = self.workers.pop(wid)
            if wid in self.rank_order:
                self.rank_order.remove(wid)
            w.state = FAILED
            w.proc.terminate()
            self.stopping.append(
                (w, now + self.elastic.sigkill_deadline))
            self._drop_heartbeat(wid)
            self._m_heartbeat_failures.inc()
            self._count_host_failure(w.host)
            self.log.warning(
                "elastic driver: worker %s missed its heartbeat lease "
                "for over %.0fs; treating as hung (SIGTERM, SIGKILL "
                "after %.0fs)", wid, self.elastic.heartbeat_timeout,
                self.elastic.sigkill_deadline)
            changed = True
        return changed

    def _rereq_pending(self):
        """True when a live worker asked for a re-rendezvous at a version
        beyond the current one (transport failure with no process death)."""
        for key in self.server.scope_keys(ELASTIC_SCOPE):
            if not key.startswith("rereq."):
                continue
            try:
                want = int(self.server.get(ELASTIC_SCOPE, key))
            except (TypeError, ValueError):
                continue
            if want > self.version:
                return True
        return False

    def _clear_stale_rereqs(self):
        for key in self.server.scope_keys(ELASTIC_SCOPE):
            if not key.startswith("rereq."):
                continue
            try:
                want = int(self.server.get(ELASTIC_SCOPE, key))
            except (TypeError, ValueError):
                want = -1
            if want <= self.version:
                self.server.delete(ELASTIC_SCOPE, key)

    def _sweep_exits(self):
        """Returns True when a failure changed membership."""
        changed = False
        for wid in list(self.workers):
            w = self.workers[wid]
            rc = w.proc.poll()
            if rc is None:
                continue
            w.proc.wait()
            del self.workers[wid]
            self._drop_heartbeat(wid)
            # Drop the dead worker's rank slot NOW: if the same worker id
            # is respawned it must re-enter at the END of the order — a
            # fresh-state replacement taking rank 0 would make
            # state.sync() broadcast empty state over the survivors.
            if wid in self.rank_order:
                self.rank_order.remove(wid)
            if rc == 0:
                w.state = SUCCEEDED
                self.succeeded.append(wid)
                self.completing = True
                self.log.info("elastic driver: worker %s finished", wid)
            elif rc == PREEMPT_EXIT_CODE:
                # Graceful preemption hand-off (elastic.py SIGTERM
                # handler): the worker persisted its commit and left on
                # purpose. A membership change, not a failure — no
                # fail count, no blacklist pressure on a host that did
                # everything right on its way out. Unconditional on
                # ``completing`` (the re-publish below is gated anyway):
                # a preemption during wind-down must not read as a crash.
                self.log.info(
                    "elastic driver: worker %s left after a graceful "
                    "preemption hand-off", wid)
                changed = True
            elif rc == RESTART_EXIT_CODE and not self.completing:
                # Compiled-plane reset (elastic.py exit-restart): the
                # worker persisted its commit and asked to be respawned
                # fresh so jax.distributed can re-form at the new world
                # size. Not a failure: no blacklist count, and no
                # membership change beyond what triggered the reset —
                # bumping the version here would make the respawned
                # cohort immediately stale and loop.
                self._spawn(wid, w.host, w.slot_index)
                self.log.info(
                    "elastic driver: worker %s exited for data-plane "
                    "reset; respawned fresh", wid)
            else:
                w.state = FAILED
                self._m_worker_failures.inc()
                self._count_host_failure(w.host)
                self.log.warning(
                    "elastic driver: worker %s failed (exit %d)", wid, rc)
                changed = True
        return changed

    # -- main loop -------------------------------------------------------
    def run(self):
        deadline = time.monotonic() + self.elastic.base.start_timeout
        while True:
            targets = self._discover_targets()
            if len(targets) >= self.elastic.min_np:
                break
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"discovery produced only {len(targets)} slots within "
                    f"the start timeout; min_np={self.elastic.min_np}")
            time.sleep(self.elastic.discovery_interval)

        self.addr = self.elastic.base.rendezvous_addr or _rendezvous_ip(
            [SimpleNamespace(hostname=t[1]) for t in targets])
        self.version = 0
        self._reconcile(targets)
        self._publish()

        last_discovery = time.monotonic()
        finish_deadline = None
        try:
            while self.workers:
                changed = self._sweep_exits()
                changed |= self._check_heartbeats()
                self._reap_stopping()
                now = time.monotonic()
                targets = None
                if (not self.completing
                        and now - last_discovery
                        >= self.elastic.discovery_interval):
                    last_discovery = now
                    targets = self._discover_targets()
                    changed |= ({t[0] for t in targets}
                                != set(self.workers))
                if not self.completing and self._rereq_pending():
                    changed = True
                if changed and not self.completing:
                    self.resets += 1
                    self._m_resets.inc()
                    if (self.elastic.reset_limit is not None
                            and self.resets > self.elastic.reset_limit):
                        raise RuntimeError(
                            f"elastic reset count {self.resets} exceeded "
                            f"--reset-limit {self.elastic.reset_limit}")
                    # Retire the old version's keys BEFORE spawning
                    # replacements: a respawned worker must never pick up
                    # the dead cohort's assignment and try to dial stale
                    # listeners. The version key itself is published last,
                    # after the new assignment is complete.
                    old = self.version
                    self.version += 1
                    self.server.clear_scope(f"{ASSIGN_SCOPE}.{old}")
                    self.server.clear_scope(f"{PEER_SCOPE}.{old}")
                    if targets is None:
                        targets = self._discover_targets()
                    self._reconcile(targets)
                    if len(self.workers) < self.elastic.min_np:
                        # Below quorum: keep polling discovery for
                        # replacement hosts until the start timeout.
                        wait_until = now + self.elastic.base.start_timeout
                        while (len(self.workers) < self.elastic.min_np
                               and time.monotonic() < wait_until):
                            self._sweep_exits()
                            self._check_heartbeats()
                            self._reap_stopping()
                            self._reconcile(self._discover_targets())
                            time.sleep(self.elastic.discovery_interval)
                        if len(self.workers) < self.elastic.min_np:
                            raise RuntimeError(
                                f"{len(self.workers)} workers alive < "
                                f"min_np={self.elastic.min_np}; aborting")
                    self._publish()
                    self._clear_stale_rereqs()
                if self.completing and finish_deadline is None:
                    finish_deadline = now + 60
                if finish_deadline is not None and now > finish_deadline:
                    self.log.warning(
                        "elastic driver: stragglers after success; killing")
                    for w in self.workers.values():
                        w.proc.terminate()
                    finish_deadline = now + 1e9
                time.sleep(0.05)
        except Exception:
            for w in self.workers.values():
                w.proc.terminate()
            raise
        finally:
            deadline = time.monotonic() + 5
            leftovers = list(self.workers.values()) + [w for w, _ in
                                                       self.stopping]
            for w in leftovers:
                if w.proc.poll() is None and time.monotonic() < deadline:
                    try:
                        w.proc.proc.wait(
                            max(0.1, deadline - time.monotonic()))
                    except Exception:  # noqa: BLE001
                        pass
                w.proc.kill()
            self.server.stop()

        return 0 if self.succeeded else 1


def launch_elastic_job(elastic, command):
    """Entry used by hvdrun for elastic flags; returns the exit code."""
    spawn.reset_capture_dir(elastic.base.output_filename)
    driver = ElasticDriver(elastic, command)
    try:
        return driver.run()
    except RuntimeError as e:
        get_logger().error("elastic job failed: %s", e)
        return 1


def run_elastic(elastic, command):  # API-parity alias
    return launch_elastic_job(elastic, command)


__all__ = ["ElasticSettings", "ElasticDriver", "HostDiscovery",
           "launch_elastic_job", "run_elastic"]
