"""Driver-side elasticity: discovery, stable rank assignment, blacklist,
re-rendezvous, worker respawn.

The analog of the reference's ElasticDriver + WorkerStateRegistry +
HostDiscoveryScript (reference: horovod/runner/elastic/driver.py:68-314,
registration.py:28-150, discovery.py:80-185): a discovery script is polled
every second for the current ``host:slots`` membership; on change (or on a
worker failure) the driver bumps the membership **version**, publishes new
rank assignments to its KV store, spawns workers for new slots, and lets
surviving workers re-rendezvous under the new version. Failed hosts are
blacklisted after repeated worker failures. On TPU the discovery script is
where slice preemption signals surface (a preempted TPU-VM host simply
drops out of the script's output).
"""

import os
import signal
import subprocess
import time
from types import SimpleNamespace

from . import spawn
from . import heartbeat as heartbeat_mod
from . import journal as journal_mod
from .hosts import HostInfo
from .http_server import RendezvousServer, new_job_token
from .job import _rendezvous_ip
from ..chaos import ChaosSignal, inject as _chaos_inject
from ..exceptions import PREEMPT_EXIT_CODE, RESTART_EXIT_CODE
from ..fleet import ledger as fleet_ledger
from .rendezvous import (ASSIGN_SCOPE, ELASTIC_SCOPE, EXIT_SCOPE,
                         PEER_SCOPE, VERSION_KEY)
from ..telemetry import core as telemetry
from ..utils import envparse
from ..utils.logging_util import get_logger

RUNNING, SUCCEEDED, FAILED = "running", "succeeded", "failed"

#: Exit code of a driver that discovered it is a fenced stale primary
#: and demoted itself (its workers belong to the newer primary now).
DEMOTED_RC = 3


def _check_heartbeat_config(timeout_s, worker_env):
    """True (and a warning logged) when the liveness timeout is below
    ~2 beat intervals — every healthy worker would read as hung and be
    killed on repeat, with logs blaming the workers instead of the
    configuration. The interval is read from the WORKER env when the
    job overrides it there, else from this process's knobs."""
    if timeout_s <= 0:
        return False
    interval = None
    for prefix in ("HVDTPU_", "HOROVOD_TPU_", "HOROVOD_"):
        value = (worker_env or {}).get(prefix + "HEARTBEAT_INTERVAL")
        if value:
            try:
                interval = float(value)
            except ValueError:
                pass
            break
    if interval is None:
        interval = heartbeat_mod.heartbeat_interval()
    if timeout_s < 2 * interval:
        get_logger().warning(
            "elastic driver: heartbeat timeout %.1fs is below twice the "
            "worker beat interval %.1fs — healthy workers WILL be "
            "failed as hung; raise HVDTPU_HEARTBEAT_TIMEOUT or lower "
            "HVDTPU_HEARTBEAT_INTERVAL", timeout_s, interval)
        return True
    return False


class ElasticSettings:
    def __init__(self, settings, discovery_script=None, min_np=1,
                 max_np=None, reset_limit=None, host_fail_limit=3,
                 discovery_interval=1.0, heartbeat_timeout=None,
                 sigkill_deadline=None, journal_dir=None,
                 standby_addrs=None, driver_port=None):
        self.base = settings
        self.discovery_script = discovery_script
        self.min_np = min_np
        self.max_np = max_np
        self.reset_limit = reset_limit
        self.host_fail_limit = host_fail_limit
        self.discovery_interval = discovery_interval
        # Control-plane HA (docs/fault_tolerance.md "Control-plane HA"):
        # journal directory (unset = no journal object, no term
        # fencing, no extra KV traffic — the existing code path),
        # standby endpoints exported to workers for KV failover, and
        # an optional fixed listen port so standbys are addressable
        # before they exist.
        self.journal_dir = (
            envparse.get_str(envparse.DRIVER_JOURNAL, "")
            if journal_dir is None else journal_dir)
        self.standby_addrs = (
            envparse.get_str(envparse.DRIVER_STANDBY_ADDRS, "")
            if standby_addrs is None else standby_addrs)
        self.driver_port = (
            envparse.get_int(envparse.DRIVER_PORT, 0)
            if driver_port is None else driver_port)
        self.lease_interval = envparse.get_float(
            envparse.DRIVER_LEASE_INTERVAL, 1.0)
        # Liveness: a worker whose heartbeat lease stops moving for this
        # long is failed (0 disables; docs/fault_tolerance.md).
        self.heartbeat_timeout = (
            heartbeat_mod.heartbeat_timeout() if heartbeat_timeout is None
            else heartbeat_timeout)
        # SIGTERM->SIGKILL escalation window for workers being stopped.
        self.sigkill_deadline = (
            envparse.get_float(envparse.SIGKILL_DEADLINE, 10.0)
            if sigkill_deadline is None else sigkill_deadline)
        _check_heartbeat_config(self.heartbeat_timeout,
                                getattr(settings, "env", None))


class HostDiscovery:
    """Poll the user's discovery script for the current host set
    (reference: discovery.py:152-175 ``HostDiscoveryScript``). Fixed-host
    fallback uses the static -H/--hostfile list."""

    def __init__(self, elastic_settings):
        self._settings = elastic_settings

    def find_available_hosts(self):
        script = self._settings.discovery_script
        if not script:
            return self._settings.base.resolve_hosts()
        try:
            proc = subprocess.run(script, shell=True, capture_output=True,
                                  timeout=30)
        except subprocess.TimeoutExpired:
            raise RuntimeError("host discovery script timed out (30s)")
        if proc.returncode != 0:
            raise RuntimeError(
                f"host discovery script failed (exit {proc.returncode}): "
                f"{proc.stderr.decode(errors='replace')[:500]}")
        hosts = []
        for line in proc.stdout.decode().splitlines():
            line = line.strip()
            if line:
                hosts.append(HostInfo.from_string(line))
        return hosts


class _Worker:
    __slots__ = ("worker_id", "host", "slot_index", "proc", "state")

    def __init__(self, worker_id, host, slot_index, proc):
        self.worker_id = worker_id
        self.host = host
        self.slot_index = slot_index
        self.proc = proc
        self.state = RUNNING


class _AdoptedProc:
    """SlotProcess-shaped shim for a worker *inherited* through a
    control-plane failover: the promoted standby never spawned it, so
    there is no child handle. Exit detection reads the worker's
    ``elastic.exit`` KV marker (durable — journaled; written by
    elastic.py on success/preempt/restart exits); signaling falls back
    to the pid carried in the worker's heartbeat lease when the worker
    runs on this host. A worker that dies without a marker is caught by
    the heartbeat timeout like any hung worker."""

    def __init__(self, server, wid, host=None):
        self._server = server
        self._wid = wid
        self._host = host
        self._rc = None
        self.proc = self  # the reaper's w.proc.proc.wait() shape

    def poll(self):
        if self._rc is not None:
            return self._rc
        value = self._server.get(EXIT_SCOPE, self._wid)
        if value is not None:
            try:
                self._rc = int(value.decode())
            except (ValueError, UnicodeDecodeError):
                self._rc = 1
        return self._rc

    def wait(self, timeout=None):
        del timeout
        return self.poll()

    def _pid(self):
        value = self._server.get(heartbeat_mod.HEARTBEAT_SCOPE,
                                 self._wid)
        if not value:
            return None
        try:
            return int(value.split(b":")[0])
        except ValueError:
            return None

    def _signal(self, sig):
        if self._host is None or not spawn.is_local(self._host):
            return
        pid = self._pid()
        if pid:
            try:
                os.kill(pid, sig)
            except (ProcessLookupError, PermissionError):
                pass

    def terminate(self):
        self._signal(signal.SIGTERM)

    def kill(self):
        self._signal(signal.SIGKILL)


class ElasticDriver:
    """Owns the rendezvous server and the worker fleet for one job."""

    def __init__(self, elastic, command, discovery=None, server=None,
                 resume_state=None, term=None):
        self.elastic = elastic
        self.command = command
        # Pluggable membership source: anything with find_available_hosts()
        # -> [HostInfo]. The Ray integration substitutes actor-cluster
        # discovery here (ray/elastic.py RayHostDiscovery).
        self.discovery = discovery or HostDiscovery(elastic)
        # An externally-fixed token lets a warm standby share the job's
        # auth domain (hvdrun --standby exports it; workers keep their
        # spawn-time token across a takeover).
        self.token = envparse.get_str(envparse.JOB_TOKEN) \
            or new_job_token()
        # Durable control plane: every mutation below goes through the
        # journal first when HVDTPU_DRIVER_JOURNAL is set; None keeps
        # the pre-HA code path byte for byte (guard-tested).
        self.journal = None
        self.term = None  # None = unfenced writes (HA off)
        if elastic.journal_dir:
            self.journal = journal_mod.DriverJournal(
                elastic.journal_dir,
                snapshot_every=envparse.get_int(
                    envparse.DRIVER_JOURNAL_SNAPSHOT_EVERY, 256),
                term=1 if term is None else term)
            self.term = self.journal.term
        elif term is not None:
            self.term = term
        if server is not None:
            # Promotion path: adopt the standby's already-running
            # server (workers are already pointed at its endpoint).
            self.server = server
            self.port = server.port
        else:
            self.server = RendezvousServer(job_token=self.token,
                                           verbose=elastic.base.verbose,
                                           port=elastic.driver_port)
            self.port = self.server.start()
        if self.term is not None:
            self.server.set_term(self.term)
        if self.journal is not None:
            self.server.attach_journal(self.journal)
        self.addr = None
        self.version = -1
        self.workers = {}        # worker_id -> _Worker (running only)
        self.stopping = []       # (worker, sigkill_deadline) being reaped
        self.rank_order = []     # worker_ids in rank order
        self.blacklist = set()
        self.fail_counts = {}
        self.resets = 0
        self.completing = False
        self.succeeded = []
        self.log = get_logger()
        self._demoted = False
        self._last_term_probe = 0.0
        self._probe_idx = 0
        self._adopted_deadlines = {}  # wid -> silent-adoption deadline
        self._last_targets = []
        self._discovery_failures = 0
        # Driver-side elastic counters (NULL no-ops when metrics off).
        self._m_resets = telemetry.counter(
            "hvd_elastic_driver_resets_total",
            "Membership versions published after the initial cohort")
        self._m_worker_failures = telemetry.counter(
            "hvd_elastic_driver_worker_failures_total",
            "Worker processes that exited non-zero")
        self._m_blacklisted = telemetry.gauge(
            "hvd_elastic_driver_blacklisted_hosts",
            "Hosts excluded after repeated worker failures")
        self._m_heartbeat_failures = telemetry.counter(
            "hvd_elastic_driver_heartbeat_failures_total",
            "Workers failed for missing their heartbeat lease")
        # Graceful-preemption cause ledger: cloud notice vs fleet
        # arbiter lease transfer (the fleet/ chip arbiter marks its
        # victims in the durable "fleet" KV scope before shrinking the
        # target; docs/fault_tolerance.md "Fleet arbitration").
        self.preempt_causes = {"preempt": 0, "arbiter_transfer": 0}
        self._liveness = heartbeat_mod.LivenessTracker(
            self.elastic.heartbeat_timeout)
        if resume_state is not None:
            self._adopt_state(resume_state)

    DISCOVERY_FAIL_LIMIT = 30  # consecutive failures before aborting

    # -- control-plane HA ------------------------------------------------
    def _adopt_state(self, state):
        """Promotion: rebuild in-memory driver state from a journal
        replica and adopt the running cohort. Deliberately does NOT
        bump the elastic version — a takeover with unchanged
        membership must be invisible to in-flight collectives; only a
        real membership change moves the version."""
        self.version = state["version"]
        self.rank_order = list(state["rank_order"])
        self.blacklist = set(state["blacklist"])
        self.fail_counts = dict(state["fail_counts"])
        self.resets = state.get("resets", 0)
        self._m_blacklisted.set(len(self.blacklist))
        # Durable KV (commits, exit markers, assignment table) is
        # re-served as-is; worker-written keys that landed here after
        # the primary died win over the replica (overwrite=False).
        self.server.load_state(state["kv"])
        grace = max(self.elastic.heartbeat_timeout,
                    2 * heartbeat_mod.heartbeat_interval(), 10.0)
        now = time.monotonic()
        for wid, rec in state["workers"].items():
            self.workers[wid] = _Worker(
                wid, rec["host"], rec["slot"],
                _AdoptedProc(self.server, wid, host=rec["host"]))
            if self.elastic.heartbeat_timeout > 0:
                # An adopted worker that never surfaces on this
                # control plane (no beat, no exit marker) died with
                # the old primary; without a deadline the never-beaten
                # exemption would wait for it forever.
                self._adopted_deadlines[wid] = now + grace
        self._last_targets = [
            (wid, rec["host"], rec["slot"])
            for wid, rec in state["workers"].items()]

    def _wt(self):
        """Term stamped on this driver's own store mutations (None =
        unfenced when HA is off)."""
        return self.term

    def _jrec(self, op, **fields):
        if self.journal is not None:
            self.journal.record(op, **fields)

    def _endpoint_csv(self):
        """Ordered rendezvous endpoint list for workers: this driver
        first, then the configured standbys ('' when HA is off)."""
        if not self.elastic.standby_addrs:
            return ""
        own = f"{self.addr}:{self.port}"
        rest = [e.strip() for e in
                self.elastic.standby_addrs.split(",")
                if e.strip() and e.strip() != own]
        return ",".join([own] + rest)

    def _chaos_driver(self):
        """Chaos `driver` injection point: `kill` fires directly
        (SIGKILL — the abrupt driver-death scenario); `partition` is a
        signal this site consumes by black-holing the KV/journal
        routes for the rule's ms window."""
        try:
            _chaos_inject("driver", wid="primary", version=self.version)
        except ChaosSignal as sig:
            if sig.action == "partition":
                ms = sig.rule.ms if sig.rule.ms is not None else 5000
                self.log.warning(
                    "chaos: partitioning driver KV store for %d ms", ms)
                self.server.pause_for(ms / 1000.0)

    def _check_term_fence(self, now):
        """Probe the configured standby endpoints for a higher term.
        A healed stale primary must discover the takeover and demote
        LOUDLY instead of mutating cohort state the moment its next
        membership event fires; the probe turns that race into a
        bounded window (one lease interval)."""
        if self.term is None or not self.elastic.standby_addrs:
            return
        if self.server.paused():
            # A partitioned driver cannot reach its peers either; the
            # probe resumes when the partition heals (chaos realism).
            return
        if now - self._last_term_probe < self.elastic.lease_interval:
            return
        self._last_term_probe = now
        from . import http_client
        peers = [c.strip() for c in self.elastic.standby_addrs.split(",")
                 if c.strip() and c.strip() != f"{self.addr}:{self.port}"]
        if not peers:
            return
        # ONE endpoint per tick, short timeout: the probe runs on the
        # single-threaded main loop, and a black-holed standby must not
        # wedge exit sweeping / heartbeat detection for seconds per
        # iteration — the fence window widens to len(peers) intervals,
        # still bounded.
        chunk = peers[self._probe_idx % len(peers)]
        self._probe_idx += 1
        host, _, port = chunk.rpartition(":")
        observed = http_client.probe_term(host, port, token=self.token,
                                          timeout=1)
        if observed is not None and journal_mod.term_fences(self.term,
                                                            observed):
            raise journal_mod.StaleTermError(
                f"term probe of standby {chunk}", self.term, observed)

    # -- membership ------------------------------------------------------
    def _discover_targets(self):
        """(worker_id, host, slot_index) for every slot in the current
        discovery output, minus blacklisted hosts, capped at max_np. A
        transient discovery failure keeps the last known membership —
        flaky cloud APIs are exactly what elastic mode exists for."""
        try:
            hosts = [h for h in self.discovery.find_available_hosts()
                     if h.hostname not in self.blacklist]
            self._discovery_failures = 0
        except RuntimeError as e:
            self._discovery_failures += 1
            self.log.warning(
                "elastic driver: discovery failed (%d consecutive): %s",
                self._discovery_failures, e)
            if self._discovery_failures >= self.DISCOVERY_FAIL_LIMIT:
                raise
            return self._last_targets
        slots = []
        cap = self.elastic.max_np or float("inf")
        for h in hosts:
            for idx in range(h.slots):
                if len(slots) >= cap:
                    break
                slots.append((f"{h.hostname}:{idx}", h.hostname, idx))
            if len(slots) >= cap:
                break
        self._last_targets = slots
        return slots

    def _publish(self):
        """Compute stable rank order and publish assignment version N.
        Surviving workers keep their relative order (and therefore the
        lowest ranks — rank 0 is always a survivor, which is what makes
        ``state.sync()`` broadcast-from-0 correct); new workers append
        (reference: driver.py:232-276 stable host ordering)."""
        alive = [wid for wid in self.rank_order if wid in self.workers]
        alive += [wid for wid in self.workers if wid not in alive]
        self.rank_order = alive
        size = len(alive)

        # Host-level grouping for local/cross ranks.
        host_of = {wid: self.workers[wid].host for wid in alive}
        local_rank = {}
        local_counts = {}
        for wid in alive:
            h = host_of[wid]
            local_rank[wid] = local_counts.get(h, 0)
            local_counts[h] = local_rank[wid] + 1
        host_order = list(dict.fromkeys(host_of[wid] for wid in alive))

        scope = f"{ASSIGN_SCOPE}.{self.version}"
        assign = {}
        for rank, wid in enumerate(alive):
            h = host_of[wid]
            lr = local_rank[wid]
            hosts_at_lr = [x for x in host_order if local_counts[x] > lr]
            assign[wid] = (f"{rank},{size},{lr},{local_counts[h]},"
                           f"{hosts_at_lr.index(h)},{len(hosts_at_lr)}")
        # Journal BEFORE publish: a standby replaying the journal may
        # trail reality but can never be ahead of it.
        self._jrec("membership", version=self.version, rank_order=alive,
                   workers={wid: {"host": host_of[wid],
                                  "slot": self.workers[wid].slot_index}
                            for wid in alive},
                   resets=self.resets, assign=assign)
        for wid, line in assign.items():
            self.server.put(scope, wid, line, term=self._wt())
        self.server.put(ELASTIC_SCOPE, VERSION_KEY, str(self.version),
                        term=self._wt())
        self.log.info("elastic driver: published version %d with %d "
                      "workers", self.version, size)

    def _spawn(self, worker_id, host, slot_index):
        # Belt and braces for the never-beaten exemption: whatever path
        # led here, the fresh process must not inherit a stale lease —
        # nor a predecessor's exit marker (it would be reaped at birth).
        # The marker delete is JOURNALED: the marker arrived over HTTP
        # (journaled by the handler), so without a matching delete a
        # journal replica would resurrect it and a promoted standby
        # would reap the live respawn the moment it adopted it.
        self._drop_heartbeat(worker_id)
        self._jrec("kv_delete", scope=EXIT_SCOPE, key=worker_id)
        self.server.delete(EXIT_SCOPE, worker_id, term=self._wt())
        env = dict(self.elastic.base.env)
        env.update({
            "HVDTPU_ELASTIC": "1",
            "HVDTPU_WORKER_ID": worker_id,
            "HVDTPU_RENDEZVOUS_ADDR": self.addr,
            "HVDTPU_RENDEZVOUS_PORT": str(self.port),
            "HVDTPU_JOB_TOKEN": self.token,
            "HVDTPU_START_TIMEOUT": str(self.elastic.base.start_timeout),
        })
        endpoints = self._endpoint_csv()
        if endpoints:
            # Ordered failover list for the worker's KV client
            # (http_client: re-resolve on connection-class exhaustion).
            env["HVDTPU_RENDEZVOUS_ADDRS"] = endpoints
        slot = SimpleNamespace(hostname=host, rank=worker_id)
        proc = spawn.SlotProcess(
            slot, self.command, env,
            prefix_output=self.elastic.base.prefix_output,
            output_dir=self.elastic.base.output_filename,
            ssh_port=self.elastic.base.ssh_port,
            ssh_identity_file=self.elastic.base.ssh_identity_file)
        self.workers[worker_id] = _Worker(worker_id, host, slot_index, proc)

    def _reconcile(self, targets):
        """Diff targets vs running workers; returns True when membership
        changed (spawn/kill happened)."""
        target_ids = {t[0] for t in targets}
        changed = False
        for wid in list(self.workers):
            if wid not in target_ids:
                w = self.workers.pop(wid)
                if wid in self.rank_order:
                    self.rank_order.remove(wid)
                w.proc.terminate()
                self.stopping.append(
                    (w, time.monotonic() + self.elastic.sigkill_deadline))
                self._drop_heartbeat(wid)
                self.log.info("elastic driver: host removed, stopping %s",
                              wid)
                changed = True
        for wid, host, idx in targets:
            if wid not in self.workers:
                self._spawn(wid, host, idx)
                self.log.info("elastic driver: spawned worker %s", wid)
                changed = True
        return changed

    def _reap_stopping(self):
        """Reap scale-down terminations (no zombies) and escalate to
        SIGKILL for workers that ignore SIGTERM."""
        still = []
        now = time.monotonic()
        for w, kill_at in self.stopping:
            rc = w.proc.poll()
            if rc is not None:
                w.proc.wait()
                if rc == PREEMPT_EXIT_CODE:
                    # A stop-requested worker that hands off at its
                    # commit boundary is the arbiter-shrink path (the
                    # target file shrank under a lease): same cause
                    # accounting as a self-initiated exit 83.
                    self._count_preempt_exit(w.worker_id)
                # The lease may have been re-published between the stop
                # request and the actual exit (a SIGTERM-trapping worker
                # keeps beating until its commit-boundary hand-off);
                # retire it NOW so a respawn of the same slot is judged
                # by its own beats, not a dead predecessor's frozen one.
                # UNLESS the slot was already respawned: the lease then
                # belongs to the live successor — deleting it would
                # blind hung-worker detection until its next beat.
                if w.worker_id not in self.workers:
                    self._drop_heartbeat(w.worker_id)
                continue
            if now > kill_at:
                w.proc.kill()
            still.append((w, kill_at))
        self.stopping = still

    def _drop_heartbeat(self, wid):
        """Forget a worker's liveness state and retire its lease key so
        a respawn of the same slot starts with a clean record."""
        self._liveness.forget(wid)
        self.server.delete(heartbeat_mod.HEARTBEAT_SCOPE, wid,
                           term=self._wt())

    def _count_host_failure(self, host):
        """Failure accounting + blacklist escalation, shared by the
        exit sweep and the heartbeat detector (one place to keep the
        policy from drifting)."""
        self.fail_counts[host] = self.fail_counts.get(host, 0) + 1
        if self.fail_counts[host] >= self.elastic.host_fail_limit:
            self.blacklist.add(host)
            self._m_blacklisted.set(len(self.blacklist))
            self.log.warning(
                "elastic driver: blacklisting host %s after %d "
                "failures", host, self.fail_counts[host])
        self._jrec("fail_count", host=host,
                   count=self.fail_counts[host],
                   blacklisted=host in self.blacklist)

    def _check_heartbeats(self):
        """Fail workers whose heartbeat lease stopped moving — the
        hung-worker detector (`_sweep_exits` only sees exits). A missed
        lease takes the same exit ramp as a crash: SIGTERM now, SIGKILL
        after ``sigkill_deadline`` via the stopping reaper, a failure
        count against the host, and a membership change so survivors
        re-rendezvous. Workers that never published a beat are exempt
        (startup is the start timeout's jurisdiction). Returns True when
        membership changed."""
        if self.elastic.heartbeat_timeout <= 0 or self.completing:
            return False
        changed = False
        now = time.monotonic()
        for wid in list(self.workers):
            value = self.server.get(heartbeat_mod.HEARTBEAT_SCOPE, wid)
            if value is None:
                # Adopted workers (promotion) get a bounded grace to
                # surface on the NEW control plane; spawned workers
                # keep the never-beaten exemption (startup is the
                # start timeout's jurisdiction).
                deadline = self._adopted_deadlines.get(wid)
                if deadline is None or now < deadline:
                    continue
            else:
                self._adopted_deadlines.pop(wid, None)
                if not self._liveness.observe(wid, value, now):
                    continue
            self._adopted_deadlines.pop(wid, None)
            w = self.workers.pop(wid)
            if wid in self.rank_order:
                self.rank_order.remove(wid)
            w.state = FAILED
            w.proc.terminate()
            self.stopping.append(
                (w, now + self.elastic.sigkill_deadline))
            self._drop_heartbeat(wid)
            self._m_heartbeat_failures.inc()
            self._count_host_failure(w.host)
            self.log.warning(
                "elastic driver: worker %s missed its heartbeat lease "
                "for over %.0fs; treating as hung (SIGTERM, SIGKILL "
                "after %.0fs)", wid, self.elastic.heartbeat_timeout,
                self.elastic.sigkill_deadline)
            changed = True
        return changed

    def _rereq_pending(self):
        """True when a live worker asked for a re-rendezvous at a version
        beyond the current one (transport failure with no process death)."""
        for key in self.server.scope_keys(ELASTIC_SCOPE):
            if not key.startswith("rereq."):
                continue
            try:
                want = int(self.server.get(ELASTIC_SCOPE, key))
            except (TypeError, ValueError):
                continue
            if want > self.version:
                return True
        return False

    def _clear_stale_rereqs(self):
        for key in self.server.scope_keys(ELASTIC_SCOPE):
            if not key.startswith("rereq."):
                continue
            try:
                want = int(self.server.get(ELASTIC_SCOPE, key))
            except (TypeError, ValueError):
                want = -1
            if want <= self.version:
                self.server.delete(ELASTIC_SCOPE, key)

    def _count_preempt_exit(self, wid):
        """Account one graceful exit-83 hand-off to its cause. The
        fleet arbiter marks its lease victims in the durable "fleet"
        scope BEFORE the target shrinks (ledger-before-actuation), so
        a marker present at exit time means this hand-off belongs to a
        journaled transfer; the marker is retired durably (journaled
        delete) so a promoted standby does not re-count it and a later
        respawn of the slot is judged on its own."""
        cause = "preempt"
        marker = self.server.get(fleet_ledger.SCOPE,
                                 fleet_ledger.TRANSFER_PREFIX + wid)
        if marker:
            cause = "arbiter_transfer"
            self._jrec("kv_delete", scope=fleet_ledger.SCOPE,
                       key=fleet_ledger.TRANSFER_PREFIX + wid)
            self.server.delete(fleet_ledger.SCOPE,
                               fleet_ledger.TRANSFER_PREFIX + wid,
                               term=self._wt())
        self.preempt_causes[cause] += 1
        self.log.info(
            "elastic driver: worker %s left after a graceful "
            "preemption hand-off (cause=%s)", wid, cause)

    def _sweep_exits(self):
        """Returns True when a failure changed membership."""
        changed = False
        for wid in list(self.workers):
            w = self.workers[wid]
            rc = w.proc.poll()
            if rc is None:
                continue
            w.proc.wait()
            del self.workers[wid]
            self._drop_heartbeat(wid)
            # Drop the dead worker's rank slot NOW: if the same worker id
            # is respawned it must re-enter at the END of the order — a
            # fresh-state replacement taking rank 0 would make
            # state.sync() broadcast empty state over the survivors.
            if wid in self.rank_order:
                self.rank_order.remove(wid)
            if rc == 0:
                w.state = SUCCEEDED
                self.succeeded.append(wid)
                self.completing = True
                self.log.info("elastic driver: worker %s finished", wid)
            elif rc == PREEMPT_EXIT_CODE:
                # Graceful preemption hand-off (elastic.py SIGTERM
                # handler): the worker persisted its commit and left on
                # purpose. A membership change, not a failure — no
                # fail count, no blacklist pressure on a host that did
                # everything right on its way out. Unconditional on
                # ``completing`` (the re-publish below is gated anyway):
                # a preemption during wind-down must not read as a crash.
                self._count_preempt_exit(wid)
                changed = True
            elif rc == RESTART_EXIT_CODE and not self.completing:
                # Compiled-plane reset (elastic.py exit-restart): the
                # worker persisted its commit and asked to be respawned
                # fresh so jax.distributed can re-form at the new world
                # size. Not a failure: no blacklist count, and no
                # membership change beyond what triggered the reset —
                # bumping the version here would make the respawned
                # cohort immediately stale and loop.
                self._spawn(wid, w.host, w.slot_index)
                self.log.info(
                    "elastic driver: worker %s exited for data-plane "
                    "reset; respawned fresh", wid)
            else:
                w.state = FAILED
                self._m_worker_failures.inc()
                self._count_host_failure(w.host)
                self.log.warning(
                    "elastic driver: worker %s failed (exit %d)", wid, rc)
                changed = True
        return changed

    # -- main loop -------------------------------------------------------
    def run(self, resume=False):
        """Drive the job to completion. ``resume=True`` is the
        promoted-standby entry: membership, durable KV and the adopted
        cohort are already in place (``_adopt_state``), so the initial
        discovery/publish is skipped and the elastic version does NOT
        move — the takeover is invisible to in-flight collectives."""
        if not resume:
            deadline = time.monotonic() + self.elastic.base.start_timeout
            while True:
                targets = self._discover_targets()
                if len(targets) >= self.elastic.min_np:
                    break
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"discovery produced only {len(targets)} slots "
                        f"within the start timeout; "
                        f"min_np={self.elastic.min_np}")
                time.sleep(self.elastic.discovery_interval)

            self.addr = (self.elastic.base.rendezvous_addr
                         or _rendezvous_ip([SimpleNamespace(hostname=t[1])
                                            for t in targets]))
            self.server.set_primary_hint(f"{self.addr}:{self.port}")
            self.version = 0
            self._reconcile(targets)
            self._publish()

        last_discovery = time.monotonic()
        finish_deadline = None
        try:
            while self.workers:
                self._chaos_driver()
                self._check_term_fence(time.monotonic())
                changed = self._sweep_exits()
                changed |= self._check_heartbeats()
                self._reap_stopping()
                now = time.monotonic()
                targets = None
                if (not self.completing
                        and now - last_discovery
                        >= self.elastic.discovery_interval):
                    last_discovery = now
                    targets = self._discover_targets()
                    changed |= ({t[0] for t in targets}
                                != set(self.workers))
                if not self.completing and self._rereq_pending():
                    changed = True
                if changed and not self.completing:
                    self.resets += 1
                    self._m_resets.inc()
                    if (self.elastic.reset_limit is not None
                            and self.resets > self.elastic.reset_limit):
                        raise RuntimeError(
                            f"elastic reset count {self.resets} exceeded "
                            f"--reset-limit {self.elastic.reset_limit}")
                    # Retire the old version's keys BEFORE spawning
                    # replacements: a respawned worker must never pick up
                    # the dead cohort's assignment and try to dial stale
                    # listeners. The version key itself is published last,
                    # after the new assignment is complete.
                    old = self.version
                    self.version += 1
                    self.server.clear_scope(f"{ASSIGN_SCOPE}.{old}",
                                            term=self._wt())
                    self.server.clear_scope(f"{PEER_SCOPE}.{old}",
                                            term=self._wt())
                    if targets is None:
                        targets = self._discover_targets()
                    self._reconcile(targets)
                    if len(self.workers) < self.elastic.min_np:
                        # Below quorum: keep polling discovery for
                        # replacement hosts until the start timeout.
                        wait_until = now + self.elastic.base.start_timeout
                        while (len(self.workers) < self.elastic.min_np
                               and time.monotonic() < wait_until):
                            self._sweep_exits()
                            self._check_heartbeats()
                            self._reap_stopping()
                            self._reconcile(self._discover_targets())
                            time.sleep(self.elastic.discovery_interval)
                        if len(self.workers) < self.elastic.min_np:
                            raise RuntimeError(
                                f"{len(self.workers)} workers alive < "
                                f"min_np={self.elastic.min_np}; aborting")
                    self._publish()
                    self._clear_stale_rereqs()
                if self.completing and finish_deadline is None:
                    finish_deadline = now + 60
                if finish_deadline is not None and now > finish_deadline:
                    self.log.warning(
                        "elastic driver: stragglers after success; killing")
                    for w in self.workers.values():
                        w.proc.terminate()
                    finish_deadline = now + 1e9
                time.sleep(0.05)
        except journal_mod.StaleTermError as e:
            # A newer primary owns the cohort: demote WITHOUT touching
            # the workers — they are the new primary's now, and killing
            # them would be exactly the split-brain damage the fence
            # exists to prevent. Loud, never silent.
            self._demoted = True
            self.log.error(
                "elastic driver: STALE PRIMARY FENCED — %s. Demoting; "
                "leaving the worker fleet to the newer primary.", e)
        except Exception:
            for w in self.workers.values():
                w.proc.terminate()
            raise
        finally:
            if not self._demoted:
                deadline = time.monotonic() + 5
                leftovers = list(self.workers.values()) + \
                    [w for w, _ in self.stopping]
                for w in leftovers:
                    if w.proc.poll() is None \
                            and time.monotonic() < deadline:
                        try:
                            w.proc.proc.wait(
                                max(0.1, deadline - time.monotonic()))
                        except Exception:  # noqa: BLE001
                            pass
                    w.proc.kill()
            self.server.stop()
            if self.journal is not None:
                self.journal.close()

        if self._demoted:
            return DEMOTED_RC
        return 0 if self.succeeded else 1


def launch_elastic_job(elastic, command):
    """Entry used by hvdrun for elastic flags; returns the exit code."""
    spawn.reset_capture_dir(elastic.base.output_filename)
    driver = ElasticDriver(elastic, command)
    try:
        return driver.run()
    except RuntimeError as e:
        get_logger().error("elastic job failed: %s", e)
        return 1


def run_elastic(elastic, command):  # API-parity alias
    return launch_elastic_job(elastic, command)


__all__ = ["ElasticSettings", "ElasticDriver", "HostDiscovery",
           "launch_elastic_job", "run_elastic", "DEMOTED_RC"]
