"""Host parsing and slot assignment for the launcher.

TPU-first rethink of the reference's host utilities (reference:
horovod/runner/common/util/hosts.py — ``parse_hosts``,
``get_host_assignments``): a job is a list of ``host:slots`` entries; the
launcher assigns each process a global rank, a per-host local rank, and a
cross rank (its host's index among hosts that carry the same local rank).
On TPU a "slot" is one worker process; on a real pod each host runs one
process per chip-group and the GLOBAL/LOCAL/CROSS triple maps to mesh axes
(ICI within a host, DCN across hosts).
"""


class HostInfo:
    __slots__ = ("hostname", "slots")

    def __init__(self, hostname, slots):
        if slots < 1:
            raise ValueError(f"host {hostname!r} must have >=1 slots")
        self.hostname = hostname
        self.slots = slots

    @classmethod
    def from_string(cls, host_string):
        parts = host_string.strip().split(":")
        if len(parts) == 1 or parts[1] == "":
            return cls(parts[0], 1)
        return cls(parts[0], int(parts[1]))

    def __repr__(self):
        return f"HostInfo({self.hostname}:{self.slots})"


class SlotInfo:
    __slots__ = ("hostname", "rank", "size", "local_rank", "local_size",
                 "cross_rank", "cross_size")

    def __init__(self, hostname, rank, size, local_rank, local_size,
                 cross_rank, cross_size):
        self.hostname = hostname
        self.rank = rank
        self.size = size
        self.local_rank = local_rank
        self.local_size = local_size
        self.cross_rank = cross_rank
        self.cross_size = cross_size

    def to_env(self):
        """The env the worker's ``Topology.from_env`` reads (analog of the
        reference's slot env vars, horovod/runner/gloo_run.py:65-77)."""
        return {
            "HVDTPU_RANK": str(self.rank),
            "HVDTPU_SIZE": str(self.size),
            "HVDTPU_LOCAL_RANK": str(self.local_rank),
            "HVDTPU_LOCAL_SIZE": str(self.local_size),
            "HVDTPU_CROSS_RANK": str(self.cross_rank),
            "HVDTPU_CROSS_SIZE": str(self.cross_size),
        }

    def __repr__(self):
        return (f"SlotInfo({self.hostname} rank={self.rank}/{self.size} "
                f"local={self.local_rank}/{self.local_size} "
                f"cross={self.cross_rank}/{self.cross_size})")


def parse_hosts(hosts_string):
    """Parse ``host1:slots,host2:slots`` into HostInfo list."""
    hosts = [HostInfo.from_string(h) for h in hosts_string.split(",")
             if h.strip()]
    if not hosts:
        raise ValueError(f"no hosts in {hosts_string!r}")
    seen = set()
    for h in hosts:
        if h.hostname in seen:
            raise ValueError(f"duplicate host {h.hostname!r}")
        seen.add(h.hostname)
    return hosts


def parse_hostfile(path):
    """One ``host slots=N`` (or ``host:N`` / bare ``host``) per line."""
    hosts = []
    with open(path) as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            if "slots=" in line:
                name, _, slots = line.partition("slots=")
                hosts.append(HostInfo(name.strip(), int(slots.strip())))
            else:
                hosts.append(HostInfo.from_string(line))
    if not hosts:
        raise ValueError(f"hostfile {path} is empty")
    return hosts


def get_host_assignments(hosts, num_proc):
    """Assign ``num_proc`` ranks to hosts in order, filling each host's
    slots before moving on (reference semantics:
    horovod/runner/common/util/hosts.py get_host_assignments).

    Returns a list of SlotInfo ordered by rank. cross_size for a slot is
    the number of hosts that have a worker with the same local_rank;
    cross_rank is this host's index among them.
    """
    total = sum(h.slots for h in hosts)
    if total < num_proc:
        raise ValueError(
            f"requested {num_proc} processes but hosts provide only "
            f"{total} slots")
    # (hostname, local_rank) per rank, in rank order.
    placements = []
    for h in hosts:
        for local_rank in range(h.slots):
            if len(placements) == num_proc:
                break
            placements.append((h.hostname, local_rank))
        if len(placements) == num_proc:
            break

    local_sizes = {}
    for hostname, _ in placements:
        local_sizes[hostname] = local_sizes.get(hostname, 0) + 1
    # Hosts in first-rank order, for stable cross-rank numbering.
    host_order = list(dict.fromkeys(h for h, _ in placements))

    slots = []
    for rank, (hostname, local_rank) in enumerate(placements):
        hosts_at_lr = [h for h in host_order if local_sizes[h] > local_rank]
        slots.append(SlotInfo(
            hostname=hostname, rank=rank, size=num_proc,
            local_rank=local_rank, local_size=local_sizes[hostname],
            cross_rank=hosts_at_lr.index(hostname),
            cross_size=len(hosts_at_lr)))
    return slots
