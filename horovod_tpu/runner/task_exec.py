"""Worker entry for the programmatic ``horovod_tpu.run()`` API: load the
pickled (func, args, kwargs) payload, run it, write this rank's result
(reference: horovod/runner/task_fn executing the pickled wrapped func)."""

import os
import pickle
import sys

from ..utils import envparse


def main():
    payload_path, out_dir = sys.argv[1], sys.argv[2]
    rank = envparse.get_int(envparse.RANK, 0)
    with open(payload_path, "rb") as f:
        func, args, kwargs = pickle.load(f)
    result = func(*args, **kwargs)
    tmp = os.path.join(out_dir, f".result_{rank}.tmp")
    with open(tmp, "wb") as f:
        pickle.dump(result, f)
    os.replace(tmp, os.path.join(out_dir, f"result_{rank}.pkl"))


if __name__ == "__main__":
    main()
