"""Warm-standby driver: journal tailing, lease tracking, promotion.

The second half of control-plane HA (docs/fault_tolerance.md
"Control-plane HA"): a standby launcher started with ``hvdrun
--standby PRIMARY_HOST:PORT`` runs this controller instead of an
``ElasticDriver``. It

1. binds its own KV store on a FIXED port (``HVDTPU_DRIVER_PORT``) —
   the endpoint the primary already advertised to workers in
   ``HVDTPU_RENDEZVOUS_ADDRS`` — and hints every early caller back at
   the primary (``X-Hvd-Primary``) while the primary is alive;
2. tails the primary's token-gated ``GET /journal?since=seq`` route
   every ``HVDTPU_DRIVER_LEASE_INTERVAL`` seconds into a
   ``JournalReplica`` (a read-only copy of membership, blacklist and
   the durable KV scopes);
3. treats each successful poll as a lease renewal; once the primary
   has been unreachable for ``HVDTPU_DRIVER_LEASE_TIMEOUT`` seconds it
   **promotes**: term := replica term + 1, the replica state becomes a
   live ``ElasticDriver`` over the standby's already-running server
   (the cohort is *adopted*, not respawned; the elastic version does
   NOT move), and the takeover is counted in
   ``hvd_driver_failover_total``.

Split-brain: the promotion bumps the term, so a healed stale primary
is fenced — its in-process mutations raise ``StaleTermError`` once its
store observes the newer term (a failed-over worker's write, or its
own standby probe), and it demotes without touching the workers that
now belong to the promoted standby.
"""

import json
import time

from . import http_client
from .elastic_driver import ElasticDriver
from .http_server import RendezvousServer
from .journal import JournalReplica
from ..chaos import ChaosSignal, inject as _chaos_inject
from ..telemetry import core as telemetry
from ..utils import envparse
from ..utils.logging_util import get_logger


def _m_failover():
    return telemetry.counter(
        "hvd_driver_failover_total",
        "Warm-standby promotions (control-plane takeovers)")


class StandbyController:
    """One warm standby for one primary. ``run()`` blocks: replicate
    until the lease expires, then promote and drive the adopted job to
    completion (returning its exit code)."""

    def __init__(self, elastic, command, primary, advertise=None,
                 lease_interval=None, lease_timeout=None):
        self.elastic = elastic
        self.command = command
        host, _, port = primary.rpartition(":")
        if not host:
            raise ValueError(
                f"--standby expects PRIMARY_HOST:PORT, got {primary!r}")
        self.primary = (host, int(port))
        self.token = envparse.get_str(envparse.JOB_TOKEN)
        if not self.token:
            raise RuntimeError(
                "a standby needs the job's shared token: export "
                "HVDTPU_JOB_TOKEN to the same value on the primary "
                "and the standby")
        self.lease_interval = (
            envparse.get_float(envparse.DRIVER_LEASE_INTERVAL, 1.0)
            if lease_interval is None else lease_interval)
        self.lease_timeout = (
            envparse.get_float(envparse.DRIVER_LEASE_TIMEOUT, 10.0)
            if lease_timeout is None else lease_timeout)
        self.replica = JournalReplica()
        self.advertise = advertise or elastic.base.rendezvous_addr \
            or "127.0.0.1"
        self.server = RendezvousServer(job_token=self.token,
                                       verbose=elastic.base.verbose,
                                       port=elastic.driver_port)
        self.port = self.server.start()
        # Primary hint pre-promotion is DYNAMIC (_update_hint): while
        # our lease view says the primary is alive, stray callers — a
        # worker that defected here during a transient primary blip —
        # are pointed back at it, so a sub-lease-timeout outage cannot
        # permanently strand workers on a store the primary never
        # reads. Once the lease looks expired the hint is withdrawn (a
        # hint at a dead endpoint would just flap every client), and
        # at promotion it names ourselves.
        self.synced = False
        self.promoted = None     # ElasticDriver after promotion
        self.promoted_digest = None
        self.log = get_logger()

    # -- replication -------------------------------------------------------
    def poll_once(self):
        """One /journal fetch; True = lease renewed (entries applied to
        the replica and mirrored into this store's durable scopes)."""
        host, port = self.primary
        url = (f"http://{host}:{port}/journal"
               f"?since={self.replica.seq}")
        try:
            with http_client._request("GET", url, token=self.token,
                                      timeout=max(2.0,
                                                  self.lease_interval)
                                      ) as resp:
                payload = json.loads(resp.read().decode())
        except Exception as e:  # noqa: BLE001 — any transport failure
            self.log.debug("standby: journal poll failed: %s", e)
            return False
        self.replica.apply_payload(payload)
        self.synced = True
        return True

    def _update_hint(self, primary_alive):
        """Advertise the primary on our responses only while the lease
        view says it is alive (see __init__ note)."""
        hint = (f"{self.primary[0]}:{self.primary[1]}"
                if primary_alive else None)
        if hint != self.server.primary_hint:
            self.server.set_primary_hint(hint)

    # -- promotion ---------------------------------------------------------
    def promote(self):
        """Turn the replica into a live driver at term+1. The adopted
        cohort keeps its membership version; the elastic version moves
        only if membership later actually changes."""
        state = self.replica.snapshot_state()
        new_term = max(self.replica.term, 1) + 1
        self.promoted_digest = self.replica.digest()
        _m_failover().inc()
        self.log.warning(
            "standby: PRIMARY LEASE EXPIRED — promoting to primary at "
            "term %d (replica seq %d, membership version %s, %d "
            "workers adopted)", new_term, self.replica.seq,
            state["version"], len(state["workers"]))
        self.server.set_term(new_term)
        self.server.set_primary_hint(f"{self.advertise}:{self.port}")
        driver = ElasticDriver(self.elastic, self.command,
                               server=self.server, resume_state=state,
                               term=new_term)
        driver.addr = self.advertise
        if driver.journal is not None:
            # Chainable HA: re-state term + membership + EVERY durable
            # KV key in OUR journal, so a next-generation standby (or a
            # crash-recovery replay of this dir) reconstructs the same
            # state — membership alone would lose the workers' commits.
            driver.journal.set_term(new_term)
            driver.journal.record("term", term=new_term)
            if state["version"] >= 0:
                assign = state["kv"].get(f"assign.{state['version']}",
                                         {})
                driver.journal.record(
                    "membership", version=state["version"],
                    rank_order=state["rank_order"],
                    workers=state["workers"],
                    resets=state.get("resets", 0), assign=assign)
            # Journal from the live STORE, not the replica snapshot:
            # worker writes that landed here during the takeover
            # window (journal was None pre-promotion) are newer than
            # the replica's values and load_state let them win.
            from .journal import DURABLE_SCOPES
            for scope in DURABLE_SCOPES:
                for key in self.server.scope_keys(scope):
                    value = self.server.get(scope, key)
                    if value is not None:
                        driver.journal.record(
                            "kv_put", scope=scope, key=key,
                            value=value.decode("latin-1"))
        self.promoted = driver
        return driver

    # -- main loop ---------------------------------------------------------
    def run(self):
        """Replicate until the lease expires, then promote and run the
        adopted job to completion."""
        self.log.info(
            "standby: tailing journal of primary %s:%d (lease "
            "interval %.1fs, timeout %.1fs), serving on port %d",
            self.primary[0], self.primary[1], self.lease_interval,
            self.lease_timeout, self.port)
        last_ok = time.monotonic()
        sync_deadline = (last_ok + self.elastic.base.start_timeout
                         + self.lease_timeout)
        while True:
            try:
                _chaos_inject("driver", wid="standby",
                              version=self.replica.seq)
            except ChaosSignal as sig:
                if sig.action == "partition":
                    ms = sig.rule.ms if sig.rule.ms is not None else 5000
                    self.server.pause_for(ms / 1000.0)
            ok = self.poll_once()
            if ok:
                last_ok = time.monotonic()
            self._update_hint(
                self.synced
                and time.monotonic() - last_ok <= self.lease_timeout)
            if not ok and self.synced \
                    and (time.monotonic() - last_ok
                         > self.lease_timeout):
                # Never promote before the FIRST successful sync: an
                # empty replica describes no cohort — taking over with
                # it would "adopt" nothing and exit successfully.
                break
            elif not self.synced \
                    and time.monotonic() > sync_deadline:
                self.server.stop()
                raise RuntimeError(
                    "standby: never reached the primary's journal at "
                    f"{self.primary[0]}:{self.primary[1]} within the "
                    "start timeout — wrong endpoint, token, or the "
                    "primary has no HVDTPU_DRIVER_JOURNAL")
            time.sleep(self.lease_interval)
        driver = self.promote()
        if not driver.workers:
            # The primary died before publishing any membership (or the
            # replica describes a cohort with nobody in it): there is
            # nothing to adopt, but we hold the command and settings —
            # run the job FRESH instead of reporting a phantom failure.
            self.log.warning(
                "standby: promoted over an empty cohort (primary died "
                "before publishing membership); starting the job fresh")
            return driver.run(resume=False)
        return driver.run(resume=True)

    def observed_term(self):
        """Probe helper (tests/ops): the primary's current term as
        advertised on its response headers, or None when unreachable."""
        host, port = self.primary
        return http_client.probe_term(host, port, token=self.token)

    def stop(self):
        """Tear down a standby that never promoted (tests)."""
        if self.promoted is None:
            self.server.stop()


def launch_standby(elastic, command, primary):
    """Entry used by hvdrun --standby; returns the exit code.
    Construction is inside the try: a missing HVDTPU_JOB_TOKEN or a
    malformed HOST:PORT must take the clean error path too, not an
    unhandled traceback."""
    try:
        controller = StandbyController(elastic, command, primary)
        return controller.run()
    except (RuntimeError, ValueError) as e:
        get_logger().error("standby failed: %s", e)
        return 1


__all__ = ["StandbyController", "launch_standby"]
