"""Worker-side rendezvous: discover peers through the launcher's KV store.

The reference's gloo ranks bootstrap by connecting back to the driver's
HTTP store and exchanging addresses (reference:
horovod/common/gloo/gloo_context.cc:150-228 + http_store.cc). Here each
worker picks a free TCP port for its native-core listener, publishes
``rank -> ip:port``, then polls until every peer in its process set has
published, yielding the ``HVDTPU_PEERS`` list the TCP data plane consumes.
"""

import os
import socket

from . import http_client
from ..utils import envparse

PEER_SCOPE = "peers"


def _local_ip_towards(addr, port):
    """The local IP the rendezvous server sees us from — a UDP connect
    performs routing without sending packets (NIC selection, the analog of
    HOROVOD_GLOO_IFACE, reference: gloo_context.cc:163)."""
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect((addr, port))
        return s.getsockname()[0]
    finally:
        s.close()


def _reserve_port():
    """Reserve the native core's listen port with the socket kept open
    (no close-then-rebind TOCTOU window): the native transport adopts the
    bound fd when it starts (csrc/transport.cc ReserveListenPort)."""
    from .. import native
    return native.reserve_listen_port()


def rendezvous_config():
    """(addr, port, token) of the launcher's KV store, or None."""
    addr = envparse.get_str(envparse.RENDEZVOUS_ADDR, "")
    port = envparse.get_int(envparse.RENDEZVOUS_PORT, 0)
    if not addr or not port:
        return None
    token = os.environ.get("HVDTPU_JOB_TOKEN", "")
    return addr, port, token


def bootstrap_peers(topology, deadline_s=None):
    """Publish our listener address, gather everyone's, return the peers
    csv ordered by rank (and export it as HVDTPU_PEERS)."""
    cfg = rendezvous_config()
    if cfg is None:
        raise RuntimeError(
            "no rendezvous configured: set HVDTPU_RENDEZVOUS_ADDR/PORT "
            "(the hvdrun launcher does this) or provide HVDTPU_PEERS")
    addr, port, token = cfg
    if deadline_s is None:
        deadline_s = float(os.environ.get("HVDTPU_START_TIMEOUT", "120"))

    my_ip = _local_ip_towards(addr, port)
    my_port = _reserve_port()
    http_client.put_kv(addr, port, PEER_SCOPE, str(topology.rank),
                       f"{my_ip}:{my_port}", token=token)

    peers = []
    for r in range(topology.size):
        value = http_client.wait_for_kv(addr, port, PEER_SCOPE, str(r),
                                        token=token, deadline_s=deadline_s)
        peers.append(value.decode())
    peers_csv = ",".join(peers)
    os.environ["HVDTPU_PEERS"] = peers_csv
    return peers_csv
