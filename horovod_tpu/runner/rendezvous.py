"""Worker-side rendezvous: discover peers through the launcher's KV store.

The reference's gloo ranks bootstrap by connecting back to the driver's
HTTP store and exchanging addresses (reference:
horovod/common/gloo/gloo_context.cc:150-228 + http_store.cc). Here each
worker picks a free TCP port for its native-core listener, publishes
``rank -> ip:port``, then polls until every peer in its process set has
published, yielding the ``HVDTPU_PEERS`` list the TCP data plane consumes.
"""

import os
import socket
import time

from . import http_client
from ..utils import envparse
from ..utils.logging_util import get_logger

PEER_SCOPE = "peers"
#: Durable worker exit markers (elastic.py writes rc on success/
#: preempt/restart exits) — how a promoted standby, which never
#: spawned the cohort, observes worker completion.
EXIT_SCOPE = "elastic.exit"
#: How often a peer-waiting worker re-verifies its OWN published key
#: (a restored/failed-over store may have lost the ephemeral scope).
REPUBLISH_CHECK_S = 1.0


def _local_ip_towards(addr, port):
    """The local IP the rendezvous server sees us from — a UDP connect
    performs routing without sending packets (NIC selection, the analog of
    HOROVOD_GLOO_IFACE, reference: gloo_context.cc:163)."""
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect((addr, port))
        return s.getsockname()[0]
    finally:
        s.close()


def _reserve_port():
    """Reserve the native core's listen port with the socket kept open
    (no close-then-rebind TOCTOU window): the native transport adopts the
    bound fd when it starts (csrc/transport.cc ReserveListenPort)."""
    from .. import native
    return native.reserve_listen_port()


def rendezvous_config():
    """(addr, port, token) of the launcher's KV store, or None. With
    an ``HVDTPU_RENDEZVOUS_ADDRS`` failover list configured, the
    *active* endpoint is returned — callers holding the tuple across a
    takeover still reach the store because the KV client re-resolves
    per call, but fresh lookups should not dial a known-dead primary."""
    token = envparse.get_str(envparse.JOB_TOKEN)
    addr = envparse.get_str(envparse.RENDEZVOUS_ADDR, "")
    port = envparse.get_int(envparse.RENDEZVOUS_PORT, 0)
    if not addr or not port:
        addrs = envparse.get_str(envparse.RENDEZVOUS_ADDRS, "")
        if not addrs:
            return None
        try:
            endpoints = http_client.parse_endpoints(addrs)
        except ValueError:
            return None
        if not endpoints:
            return None
        addr, port = endpoints[0]
    addr, port = http_client.active_endpoint(addr, port)
    return addr, port, token


def bootstrap_peers(topology, deadline_s=None, scope=None, my_addr=None):
    """Publish our listener address, gather everyone's, return the peers
    csv ordered by rank (and export it as HVDTPU_PEERS). ``my_addr`` lets
    the caller reserve the listener ONCE across retries — re-reserving on
    a retry would overwrite the published key with a new port after peers
    may already have read the old one."""
    cfg = rendezvous_config()
    if cfg is None:
        raise RuntimeError(
            "no rendezvous configured: set HVDTPU_RENDEZVOUS_ADDR/PORT "
            "(the hvdrun launcher does this) or provide HVDTPU_PEERS")
    addr, port, token = cfg
    if deadline_s is None:
        deadline_s = envparse.get_float(envparse.START_TIMEOUT, 120.0)
    if scope is None:
        # Elastic re-rendezvous uses one peer scope per membership version
        # so stale addresses from a previous epoch can never mix in.
        version = envparse.get_env(envparse.ELASTIC_VERSION)
        scope = f"{PEER_SCOPE}.{version}" if version else PEER_SCOPE

    if my_addr is None:
        my_ip = _local_ip_towards(addr, port)
        my_addr = f"{my_ip}:{_reserve_port()}"
    my_key = str(topology.rank)
    http_client.put_kv(addr, port, scope, my_key, my_addr, token=token)
    _arm_republish(scope, my_key, my_addr, token)

    def _heal_own_key():
        # Self-healing while we wait on peers: verify OUR OWN key is
        # still published and re-put it when the scope vanished (a
        # restarted store, or a failover to a standby that
        # deliberately does not replicate ephemeral peer keys) —
        # without this, every worker waits out the full deadline
        # against a store that will never hold the address it already
        # "published".
        mine = http_client.get_kv(addr, port, scope, my_key,
                                  token=token, retries=1, deadline=2.0)
        if mine is None:
            get_logger().warning(
                "rendezvous: own peer key %s/%s missing from the "
                "store (restore/failover?); republishing", scope,
                my_key)
            http_client.put_kv(addr, port, scope, my_key, my_addr,
                               token=token, retries=1, deadline=2.0)

    peers = []
    for r in range(topology.size):
        value = http_client.wait_for_kv(
            addr, port, scope, str(r), token=token,
            deadline_s=deadline_s, heal=_heal_own_key,
            heal_every=REPUBLISH_CHECK_S)
        peers.append(value.decode())
    peers_csv = ",".join(peers)
    os.environ["HVDTPU_PEERS"] = peers_csv
    return peers_csv


def _arm_republish(scope, key, value, token):
    """Register the failover re-registration hook for this worker's
    peer key: peer addresses are EPHEMERAL by the HA contract (never
    journaled), so after a takeover the worker republishes its own
    rank -> ip:port mapping against the new primary."""
    def _republish():
        cfg = rendezvous_config()
        if cfg is None:
            return
        a, p, tok = cfg
        http_client.put_kv(a, p, scope, key, value, token=tok,
                           retries=2, deadline=5.0)
    http_client.on_new_primary("rendezvous.peer", _republish)


# -- elastic assignment protocol ------------------------------------------
# The driver publishes, per membership version V:
#   elastic/version              -> str(V)
#   assign.V/<worker_id>         -> "rank,size,local_rank,local_size,
#                                    cross_rank,cross_size"
# and workers re-rendezvous their listeners under peers.V/<rank>.
# (Reference analog: the elastic rendezvous serving dynamic rank
# assignments from the driver's latest host allocation,
# horovod/runner/elastic/rendezvous.py:28-60.)

ELASTIC_SCOPE = "elastic"
VERSION_KEY = "version"
ASSIGN_SCOPE = "assign"


def current_elastic_version(addr, port, token):
    value = http_client.get_kv(addr, port, ELASTIC_SCOPE, VERSION_KEY,
                               token=token)
    return -1 if value is None else int(value)


def elastic_bootstrap(deadline_s=None):
    """Fetch this worker's rank assignment at the newest membership
    version, export the topology env, and rendezvous peers. Retries across
    version bumps (a membership change mid-bootstrap simply restarts the
    exchange at the new version). Returns the version."""
    cfg = rendezvous_config()
    if cfg is None:
        raise RuntimeError(
            "elastic mode requires the hvdrun launcher's rendezvous "
            "(HVDTPU_RENDEZVOUS_ADDR/PORT)")
    addr, port, token = cfg
    worker_id = envparse.get_env(envparse.WORKER_ID)
    if not worker_id:
        raise RuntimeError("elastic worker is missing HVDTPU_WORKER_ID")
    if deadline_s is None:
        deadline_s = envparse.get_float(envparse.START_TIMEOUT, 120.0)
    deadline = time.monotonic() + deadline_s
    # A re-init always follows a membership event, so the driver will have
    # bumped (or is about to bump) the version — joining the version we
    # were already part of would dial a dead cohort's listeners.
    prev = envparse.get_env(envparse.ELASTIC_VERSION)
    min_version = int(prev) + 1 if prev is not None else 0
    if min_version > current_elastic_version(addr, port, token):
        # Ask the driver to re-rendezvous: a transport failure with no
        # process death (transient socket error) changes no membership, so
        # without this request the version would never move and every
        # worker would wedge waiting for it.
        http_client.put_kv(addr, port, ELASTIC_SCOPE,
                           f"rereq.{worker_id}", str(min_version),
                           token=token)

    # One listener reservation for the whole bootstrap: retries must
    # republish the SAME address, and each reservation pins an fd.
    my_ip = _local_ip_towards(addr, port)
    my_addr = f"{my_ip}:{_reserve_port()}"

    while True:
        version = current_elastic_version(addr, port, token)
        line = None
        if version >= min_version:
            line = http_client.get_kv(addr, port,
                                      f"{ASSIGN_SCOPE}.{version}",
                                      worker_id, token=token)
        if line is None:
            # Assignment not published yet (driver still collecting hosts,
            # or we are not part of this version).
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"no elastic assignment for worker {worker_id} within "
                    f"{deadline_s}s (version={version})")
            time.sleep(0.1)
            continue

        fields = [int(x) for x in line.decode().split(",")]
        rank, size, local_rank, local_size, cross_rank, cross_size = fields
        env = {
            "HVDTPU_RANK": str(rank), "HVDTPU_SIZE": str(size),
            "HVDTPU_LOCAL_RANK": str(local_rank),
            "HVDTPU_LOCAL_SIZE": str(local_size),
            "HVDTPU_CROSS_RANK": str(cross_rank),
            "HVDTPU_CROSS_SIZE": str(cross_size),
            "HVDTPU_ELASTIC_VERSION": str(version),
        }
        os.environ.update(env)
        os.environ.pop("HVDTPU_PEERS", None)

        class _Topo:
            pass

        topo = _Topo()
        topo.rank, topo.size = rank, size
        try:
            # Short per-attempt window: if the membership changes while we
            # wait for peers, the version check below restarts us instead
            # of burning the whole start timeout on a dead cohort.
            attempt = min(15.0, max(1.0, deadline - time.monotonic()))
            bootstrap_peers(topo, deadline_s=attempt,
                            scope=f"{PEER_SCOPE}.{version}",
                            my_addr=my_addr)
            return version
        except TimeoutError:
            if (current_elastic_version(addr, port, token) == version
                    and time.monotonic() > deadline):
                raise
            # else: version moved (or time remains) — retry.
