"""Process spawn + output streaming for the launcher.

The reference execs per-slot commands over ssh threads with a
process-group-safe shell wrapper (reference:
horovod/runner/common/util/safe_shell_exec.py:270, gloo_run.py exec).
Localhost slots run as direct child process groups; remote hosts go
through ``ssh`` with the slot env inlined. Output is streamed line by
line with a ``[rank]<stream>`` prefix exactly like horovodrun.
"""

import os
import shlex
import signal
import subprocess
import sys
import threading

_LOCAL_NAMES = {"localhost", "127.0.0.1", "::1"}


def is_local(hostname):
    import socket
    if hostname in _LOCAL_NAMES:
        return True
    try:
        return hostname in (socket.gethostname(), socket.getfqdn())
    except OSError:
        return False


def _stream(pipe, sinks, console_sinks=()):
    """Forward lines from pipe to each (sink, prefix) pair — the console
    gets the [rank] prefix, a per-rank capture file gets the raw line
    (reference: horovod/runner/gloo_run.py MultiFile). A capture-file sink
    that fails twice in a row (disk full, dir deleted) is dropped so the
    others keep streaming and the pipe stays drained (an abandoned pipe
    would EPIPE-kill a healthy worker). Console sinks are never dropped:
    a transient EINTR/EAGAIN on the console fd must not silence a rank
    for the rest of the job — errors there are swallowed per line."""
    sinks = list(sinks)
    console_sinks = set(id(s) for s, _ in console_sinks)
    failed_once = set()
    try:
        for raw in iter(pipe.readline, b""):
            line = raw.decode(errors="replace")
            for pair in list(sinks):
                sink, prefix = pair
                try:
                    sink.write(f"{prefix}{line}")
                    sink.flush()
                    failed_once.discard(id(sink))
                except (OSError, ValueError):
                    if id(sink) in console_sinks:
                        continue  # keep console unconditionally
                    if id(sink) in failed_once:
                        sinks.remove(pair)
                    else:
                        failed_once.add(id(sink))
    finally:
        pipe.close()


def _safe_rank_name(rank):
    """Filesystem-safe capture dir component: elastic worker ids are
    'host:slot' strings — colons break non-POSIX filesystems."""
    return str(rank).replace(":", ".").replace("/", "_")


def reset_capture_dir(output_dir):
    """Remove stale rank.* capture dirs once per LAUNCH so runs don't
    concatenate and a later launch with fewer ranks doesn't leave old
    empty rank.N dirs that read as ranks-with-no-output. Per-process
    opens append so same-job elastic respawns keep earlier attempts."""
    import shutil
    if not output_dir or not os.path.isdir(output_dir):
        return
    for name in os.listdir(output_dir):
        if not name.startswith("rank."):
            continue
        try:
            shutil.rmtree(os.path.join(output_dir, name))
        except OSError:
            pass


class SlotProcess:
    """One spawned worker with its output pumps."""

    def __init__(self, slot, command, env, prefix_output=True,
                 output_dir=None, ssh_port=None, ssh_identity_file=None):
        self.slot = slot
        # hvd-sanitize tripwire: worker spawns fork + exec (and ssh
        # dials out) — never acceptable on a collective-critical thread.
        from ..analysis import sanitizer
        sanitizer.check_blocking("subprocess.Popen", slot.hostname)
        if is_local(slot.hostname):
            full_env = dict(os.environ)
            full_env.update(env)
            self.proc = subprocess.Popen(
                command, env=full_env,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                start_new_session=True)
        else:
            # Remote exec: inline the env into the remote shell line. The
            # worker's login shell provides PATH/python.
            exports = " ".join(f"{k}={shlex.quote(v)}"
                               for k, v in env.items())
            remote = f"cd {shlex.quote(os.getcwd())} && env {exports} " + \
                " ".join(shlex.quote(c) for c in command)
            ssh_cmd = ["ssh", "-o", "BatchMode=yes"]
            if ssh_port:
                ssh_cmd += ["-p", str(ssh_port)]
            if ssh_identity_file:
                ssh_cmd += ["-i", ssh_identity_file]
            self.proc = subprocess.Popen(
                ssh_cmd + [slot.hostname, remote],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                start_new_session=True)
        rank = slot.rank
        out_prefix = f"[{rank}]<stdout> " if prefix_output else ""
        err_prefix = f"[{rank}]<stderr> " if prefix_output else ""
        out_sinks = [(sys.stdout, out_prefix)]
        err_sinks = [(sys.stderr, err_prefix)]
        self._files = []
        if output_dir:
            # Per-rank capture alongside the console (reference:
            # gloo_run.py:157-166 output_filename/rank.N/std{out,err});
            # the file gets raw lines, the console keeps the prefix.
            rank_dir = os.path.join(output_dir,
                                    f"rank.{_safe_rank_name(rank)}")
            os.makedirs(rank_dir, exist_ok=True)
            # Append: an elastic respawn of the same rank must not
            # truncate the previous attempt's capture.
            fo = open(os.path.join(rank_dir, "stdout"), "a")
            fe = open(os.path.join(rank_dir, "stderr"), "a")
            self._files = [fo, fe]
            out_sinks.append((fo, ""))
            err_sinks.append((fe, ""))
        self._pumps = [
            threading.Thread(target=_stream,
                             args=(self.proc.stdout, out_sinks,
                                   out_sinks[:1]),
                             daemon=True),
            threading.Thread(target=_stream,
                             args=(self.proc.stderr, err_sinks,
                                   err_sinks[:1]),
                             daemon=True),
        ]
        for t in self._pumps:
            t.start()

    def poll(self):
        return self.proc.poll()

    def wait(self, timeout=None):
        rc = self.proc.wait(timeout)
        for t in self._pumps:
            t.join(timeout=5)
        for f in self._files:
            try:
                f.close()
            except OSError:
                pass
        return rc

    def terminate(self):
        """Kill the whole process group (children included)."""
        if self.proc.poll() is None:
            try:
                os.killpg(os.getpgid(self.proc.pid), signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                pass

    def kill(self):
        if self.proc.poll() is None:
            try:
                os.killpg(os.getpgid(self.proc.pid), signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
