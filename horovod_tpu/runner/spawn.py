"""Process spawn + output streaming for the launcher.

The reference execs per-slot commands over ssh threads with a
process-group-safe shell wrapper (reference:
horovod/runner/common/util/safe_shell_exec.py:270, gloo_run.py exec).
Localhost slots run as direct child process groups; remote hosts go
through ``ssh`` with the slot env inlined. Output is streamed line by
line with a ``[rank]<stream>`` prefix exactly like horovodrun.
"""

import os
import shlex
import signal
import subprocess
import sys
import threading

_LOCAL_NAMES = {"localhost", "127.0.0.1", "::1"}


def is_local(hostname):
    import socket
    if hostname in _LOCAL_NAMES:
        return True
    try:
        return hostname in (socket.gethostname(), socket.getfqdn())
    except OSError:
        return False


def _stream(pipe, sink, prefix):
    """Forward lines from pipe to sink with the rank prefix."""
    try:
        for raw in iter(pipe.readline, b""):
            line = raw.decode(errors="replace")
            sink.write(f"{prefix}{line}")
            sink.flush()
    finally:
        pipe.close()


class SlotProcess:
    """One spawned worker with its output pumps."""

    def __init__(self, slot, command, env, prefix_output=True):
        self.slot = slot
        if is_local(slot.hostname):
            full_env = dict(os.environ)
            full_env.update(env)
            self.proc = subprocess.Popen(
                command, env=full_env,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                start_new_session=True)
        else:
            # Remote exec: inline the env into the remote shell line. The
            # worker's login shell provides PATH/python.
            exports = " ".join(f"{k}={shlex.quote(v)}"
                               for k, v in env.items())
            remote = f"cd {shlex.quote(os.getcwd())} && env {exports} " + \
                " ".join(shlex.quote(c) for c in command)
            self.proc = subprocess.Popen(
                ["ssh", "-o", "BatchMode=yes", slot.hostname, remote],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                start_new_session=True)
        rank = slot.rank
        out_prefix = f"[{rank}]<stdout> " if prefix_output else ""
        err_prefix = f"[{rank}]<stderr> " if prefix_output else ""
        self._pumps = [
            threading.Thread(target=_stream,
                             args=(self.proc.stdout, sys.stdout, out_prefix),
                             daemon=True),
            threading.Thread(target=_stream,
                             args=(self.proc.stderr, sys.stderr, err_prefix),
                             daemon=True),
        ]
        for t in self._pumps:
            t.start()

    def poll(self):
        return self.proc.poll()

    def wait(self, timeout=None):
        rc = self.proc.wait(timeout)
        for t in self._pumps:
            t.join(timeout=5)
        return rc

    def terminate(self):
        """Kill the whole process group (children included)."""
        if self.proc.poll() is None:
            try:
                os.killpg(os.getpgid(self.proc.pid), signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                pass

    def kill(self):
        if self.proc.poll() is None:
            try:
                os.killpg(os.getpgid(self.proc.pid), signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
