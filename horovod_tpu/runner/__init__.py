"""Job launcher: ``hvdrun`` CLI and the programmatic ``run()`` API.

The analog of the reference's runner package (reference:
horovod/runner/__init__.py:92 ``horovod.run``, launch.py:763
``run_commandline``). Launch is rendezvous-based: the driver starts an
HTTP KV store, publishes slot assignments, and workers discover each
other's native-core listeners through it — no hand-built peer lists.
"""

import os
import pickle
import sys
import tempfile

from .job import Settings, launch_job


def run(func, args=(), kwargs=None, num_proc=1, hosts=None, hostfile=None,
        env=None, start_timeout=120, verbose=False):
    """Run ``func(*args, **kwargs)`` on ``num_proc`` workers; returns the
    list of per-rank return values ordered by rank.

    The function is pickled to a spill directory that must be visible on
    every host (always true on localhost; use a shared filesystem for
    multi-host jobs) — the reference ships pickled functions over its
    task services instead (horovod/runner/__init__.py:92).
    """
    if kwargs is None:
        kwargs = {}
    with tempfile.TemporaryDirectory(prefix="hvdtpu_run_") as tmp:
        payload = os.path.join(tmp, "payload.pkl")
        with open(payload, "wb") as f:
            pickle.dump((func, args, kwargs), f)
        settings = Settings(num_proc=num_proc, hosts=hosts,
                            hostfile=hostfile, start_timeout=start_timeout,
                            verbose=verbose, env=env)
        command = [sys.executable, "-m", "horovod_tpu.runner.task_exec",
                   payload, tmp]
        rc = launch_job(settings, command)
        if rc != 0:
            raise RuntimeError(f"hvdrun job failed with exit code {rc}")
        results = []
        for rank in range(num_proc):
            path = os.path.join(tmp, f"result_{rank}.pkl")
            if not os.path.exists(path):
                raise RuntimeError(
                    f"rank {rank} produced no result file (crashed after "
                    "collectives completed?)")
            with open(path, "rb") as f:
                results.append(pickle.load(f))
        return results


def run_command(command, num_proc=1, hosts=None, hostfile=None, env=None,
                start_timeout=120, verbose=False):
    """Launch an argv list across workers; returns the job exit code."""
    settings = Settings(num_proc=num_proc, hosts=hosts, hostfile=hostfile,
                        start_timeout=start_timeout, verbose=verbose,
                        env=env)
    return launch_job(settings, command)
