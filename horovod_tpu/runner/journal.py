"""Driver control-plane journal: the durable half of control-plane HA.

Every robustness subsystem so far funnels through one launcher process
— kill that host and the job dies with the in-memory membership,
blacklist, and commit state (docs/fault_tolerance.md "Control-plane
HA"). This module makes the driver's mutations *durable* and
*replicable*:

- ``DriverJournal`` is an append-only fsync'd JSONL (one entry per
  control-plane mutation, each stamped with a monotonically-increasing
  ``seq`` and the writer's ``term``) plus a periodic full-state
  snapshot (atomic tmp+fsync+rename, the checkpoint.py discipline).
  ``HVDTPU_DRIVER_JOURNAL`` names the directory; unset = no journal
  object exists at all (the disabled-mode contract — zero I/O).
- ``replay`` reconstructs the driver state from snapshot + journal,
  tolerating a torn final line (a crash mid-append loses at most the
  entry being written, never the file).
- ``JournalReplica`` is the warm-standby's in-memory copy, fed by the
  primary's token-gated ``GET /journal?since=seq`` route
  (runner/standby.py) and promoted into a live driver on lease expiry.

Durable vs ephemeral KV partition: worker *commits* (``elastic.state``)
and exit markers (``elastic.exit``) are durable — they are journaled by
the HTTP handler and survive a failover. Peer addresses, heartbeats,
metrics, trace shards and serving-member keys are **ephemeral** by
contract: workers republish them against the new primary
(http_client's ``on_new_primary`` hooks), so replicating them would
only replicate staleness.

Terms fence split-brain: every mutation carries the writer's term; a
resurrected stale primary whose server has observed a higher term gets
``StaleTermError`` naming BOTH terms instead of silently corrupting
the cohort (docs/fault_tolerance.md "Split-brain fencing").
"""

import json
import os
import threading

# The journal's state machine lives in the protocol spec
# (spec-is-implementation — analysis/protocol/journal_spec.py is the
# module the hvd-model checker explores, and this module executes the
# exact same functions; tests/test_protocol_model.py asserts the
# delegation by identity). This file owns everything impure: files,
# fsync, locks, telemetry.
from ..analysis.protocol.journal_spec import (
    DURABLE_SCOPES,
    JournalError,
    apply_entry,
    durable_key,
    new_state,
    state_digest,
    term_fences,
)
from ..telemetry import core as telemetry
from ..utils.logging_util import get_logger

JOURNAL_FILE = "journal.jsonl"
SNAPSHOT_FILE = "snapshot.json"

DEFAULT_SNAPSHOT_EVERY = 256


class StaleTermError(RuntimeError):
    """A control-plane mutation carried a term older than the one the
    store has observed — the writer is a fenced stale primary. Carries
    both terms so the split-brain is diagnosable from the one line."""

    def __init__(self, mutation, writer_term, observed_term):
        super().__init__(
            f"term fenced: {mutation} carries term {writer_term} but a "
            f"newer primary at term {observed_term} has taken over — "
            "this driver is stale and must not mutate cohort state")
        self.writer_term = writer_term
        self.observed_term = observed_term


def _m_bytes():
    return telemetry.gauge(
        "hvd_journal_bytes",
        "Bytes in the driver journal dir (journal + snapshot)")


def _read_lines(path):
    """(entries, good_bytes, torn) — parse a journal file, stopping at
    the first unparseable line. A torn FINAL line is the crash-
    mid-append signature and is recoverable; a torn line with entries
    after it means corruption and raises."""
    entries = []
    good = 0
    torn = False
    with open(path, "rb") as f:
        raw = f.read()
    for line in raw.splitlines(keepends=True):
        stripped = line.strip()
        if not stripped:
            good += len(line)
            continue
        try:
            entry = json.loads(stripped.decode())
        except (ValueError, UnicodeDecodeError):
            if raw[good + len(line):].strip():
                raise JournalError(
                    f"journal {path} is corrupt mid-file at byte {good} "
                    "(unparseable line with entries after it)")
            torn = True
            break
        if not line.endswith(b"\n"):
            # Parsed but unterminated: the trailing newline never hit
            # the disk; treat like a torn line so a replayer and the
            # recovered writer agree on what counts as durable.
            torn = True
            break
        entries.append(entry)
        good += len(line)
    return entries, good, torn


def read_dir(dirpath):
    """(state, seq, snapshot_seq) replayed from ``dirpath`` without
    modifying anything — usable on a dead primary's journal."""
    state = new_state()
    seq = 0
    snap_path = os.path.join(dirpath, SNAPSHOT_FILE)
    if os.path.exists(snap_path):
        with open(snap_path) as f:
            snap = json.load(f)
        state = snap["state"]
        seq = snap["seq"]
    snap_seq = seq
    jpath = os.path.join(dirpath, JOURNAL_FILE)
    if os.path.exists(jpath):
        entries, _, torn = _read_lines(jpath)
        if torn:
            get_logger().warning(
                "journal %s: torn final line (crash mid-append); "
                "replaying the intact prefix", jpath)
        for entry in entries:
            if entry["seq"] <= seq:
                continue
            apply_entry(state, entry)
            seq = entry["seq"]
    return state, seq, snap_seq


def replay(dirpath):
    """(state, seq) — public replay entry; raises JournalError on a
    journal corrupted anywhere but its final line."""
    state, seq, _ = read_dir(dirpath)
    return state, seq


class DriverJournal:
    """The primary's write-side: every control-plane mutation lands
    here (fsync'd) BEFORE it takes effect, so a standby replaying the
    journal can never be ahead of reality."""

    def __init__(self, dirpath, snapshot_every=None, term=1):
        self.dirpath = dirpath
        self.snapshot_every = (DEFAULT_SNAPSHOT_EVERY
                               if snapshot_every is None
                               else max(1, int(snapshot_every)))
        self._lock = threading.Lock()
        self._log = get_logger()
        os.makedirs(dirpath, exist_ok=True)
        # Crash recovery: adopt whatever a previous incarnation left
        # (repairing a torn final line in place), then resume its seq.
        self.state, self.seq, self._snap_seq = read_dir(dirpath)
        self._repair_torn_tail()
        self.term = max(int(term), self.state.get("term", 0))
        self.state["term"] = self.term
        self._entries = self._reload_entries()
        self._file = open(self._jpath, "ab")
        self._update_bytes()

    @property
    def _jpath(self):
        return os.path.join(self.dirpath, JOURNAL_FILE)

    @property
    def _spath(self):
        return os.path.join(self.dirpath, SNAPSHOT_FILE)

    def _repair_torn_tail(self):
        if not os.path.exists(self._jpath):
            return
        _, good, torn = _read_lines(self._jpath)
        if torn:
            self._log.warning(
                "journal %s: truncating torn final line at byte %d",
                self._jpath, good)
            with open(self._jpath, "r+b") as f:
                f.truncate(good)
                f.flush()
                os.fsync(f.fileno())

    def _reload_entries(self):
        if not os.path.exists(self._jpath):
            return []
        entries, _, _ = _read_lines(self._jpath)
        return [e for e in entries if e["seq"] > self._snap_seq]

    # -- write side --------------------------------------------------------
    def record(self, op, **fields):
        """Journal one mutation and apply it to the tracked state.
        Returns the entry. fsync before return: an acknowledged entry
        is durable."""
        with self._lock:
            self.seq += 1
            entry = {"seq": self.seq, "term": self.term, "op": op}
            entry.update(fields)
            apply_entry(self.state, entry)
            line = json.dumps(entry, sort_keys=True,
                              separators=(",", ":")) + "\n"
            self._file.write(line.encode())
            self._file.flush()
            os.fsync(self._file.fileno())
            if len(self._entries) >= self.snapshot_every:
                self._snapshot_locked()
                # The entry that triggered rotation is inside the
                # snapshot; the in-memory window restarts empty.
            else:
                self._entries.append(entry)
            self._update_bytes()
            return entry

    def set_term(self, term):
        with self._lock:
            self.term = int(term)
            self.state["term"] = self.term

    def _snapshot_locked(self):
        tmp = self._spath + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"seq": self.seq, "term": self.term,
                       "state": self.state}, f, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._spath)
        dir_fd = os.open(self.dirpath, os.O_RDONLY)
        try:
            os.fsync(dir_fd)
        finally:
            os.close(dir_fd)
        self._file.close()
        self._file = open(self._jpath, "wb")  # rotate: entries subsumed
        os.fsync(self._file.fileno())
        self._snap_seq = self.seq
        self._entries = []

    def snapshot(self):
        """Force a snapshot + journal rotation (also called on the
        snapshot_every cadence from record())."""
        with self._lock:
            self._snapshot_locked()
            self._update_bytes()

    def close(self):
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None

    def _update_bytes(self):
        total = 0
        for name in (self._jpath, self._spath):
            try:
                total += os.path.getsize(name)
            except OSError:
                pass
        _m_bytes().set(total)

    # -- read side (the /journal route) ------------------------------------
    def sync_payload(self, since_seq):
        """What a standby at ``since_seq`` needs to catch up: the
        snapshot too when the journal was rotated past it, else just
        the missing entries."""
        with self._lock:
            payload = {"term": self.term, "seq": self.seq,
                       "snapshot": None, "entries": []}
            if since_seq < self._snap_seq:
                # The journal was rotated past the replica's position:
                # ship the on-disk snapshot so the entry seqs line up
                # (fallback: the full live state at the current seq).
                try:
                    with open(self._spath) as f:
                        snap = json.load(f)
                    payload["snapshot"] = {"seq": snap["seq"],
                                           "state": snap["state"]}
                    payload["entries"] = list(self._entries)
                except (OSError, ValueError):
                    # DEEP COPY under the lock: the payload is JSON-
                    # serialized by the HTTP layer after we release it,
                    # and a concurrent record() mutates self.state.
                    payload["snapshot"] = {
                        "seq": self.seq,
                        "state": json.loads(json.dumps(self.state))}
            else:
                payload["entries"] = [e for e in self._entries
                                      if e["seq"] > since_seq]
            return payload

    def digest(self):
        with self._lock:
            return state_digest(self.state)


class JournalReplica:
    """The standby's in-memory copy, advanced by sync payloads."""

    def __init__(self):
        self.state = new_state()
        self.seq = 0
        self.term = 0
        self._lock = threading.Lock()

    def apply_payload(self, payload):
        """Apply one /journal response; returns entries applied."""
        applied = 0
        with self._lock:
            snap = payload.get("snapshot")
            if snap and snap.get("state") is not None \
                    and snap["seq"] >= self.seq:
                self.state = snap["state"]
                self.seq = snap["seq"]
                applied += 1
            for entry in payload.get("entries", ()):
                if entry["seq"] <= self.seq:
                    continue
                apply_entry(self.state, entry)
                self.seq = entry["seq"]
                applied += 1
            self.term = max(self.term, int(payload.get("term", 0)),
                            self.state.get("term", 0))
        return applied

    def digest(self):
        with self._lock:
            return state_digest(self.state)

    def snapshot_state(self):
        """Deep copy of the replica state for promotion."""
        with self._lock:
            return json.loads(json.dumps(self.state))


__all__ = ["DriverJournal", "JournalReplica", "JournalError",
           "StaleTermError", "DURABLE_SCOPES", "durable_key",
           "term_fences", "new_state", "apply_entry", "state_digest",
           "replay", "read_dir"]
