"""``hvdrun`` command line (reference: horovod/runner/launch.py:763
``run_commandline``).

Usage mirrors horovodrun:

    hvdrun -np 4 python train.py
    hvdrun -np 8 -H host1:4,host2:4 python train.py
    hvdrun -np 2 --min-np 1 --max-np 4 \
        --host-discovery-script ./discover.sh python train.py   (elastic)

Runtime knobs are argparse flags that become HVDTPU_* env for the workers
(the reference's config_parser pattern,
horovod/runner/common/util/config_parser.py).
"""

import argparse
import sys

from .job import Settings, launch_job


def parse_args(argv=None):
    parser = argparse.ArgumentParser(
        prog="hvdrun",
        description="Launch an SPMD horovod_tpu job.",
        usage="hvdrun -np N [options] <command> [args...]")
    parser.add_argument("-np", "--num-proc", type=int, default=1,
                        dest="num_proc", help="number of worker processes")
    parser.add_argument("-H", "--hosts", default=None,
                        help="comma-separated host:slots list")
    parser.add_argument("--hostfile", default=None,
                        help="file with one 'host slots=N' per line")
    parser.add_argument("--version", action="store_true", dest="version",
                        help="print the horovod_tpu version and exit")
    parser.add_argument("--ssh-port", type=int, default=None,
                        help="ssh port for remote worker spawn "
                             "(reference: horovodrun --ssh-port)")
    parser.add_argument("--ssh-identity-file", default=None,
                        help="ssh identity (private key) file for remote "
                             "worker spawn")
    parser.add_argument("--network-interface", default=None,
                        help="network interface the driver advertises for "
                             "rendezvous (reference: horovodrun "
                             "--network-interface; default: routed "
                             "automatically)")
    parser.add_argument("--start-timeout", type=int, default=120,
                        help="seconds workers may take to rendezvous")
    parser.add_argument("--verbose", action="store_true")
    parser.add_argument("--disable-prefix-output", action="store_true",
                        help="do not prefix worker output with [rank]")
    parser.add_argument("--output-filename", default=None,
                        help="directory collecting per-rank "
                             "rank.N/stdout|stderr captures")
    parser.add_argument("--config-file", default=None,
                        help="YAML file of flag values (flag names with "
                             "dashes or underscores); explicit CLI flags "
                             "win")
    # Elastic flags (reference: launch.py --min-np/--max-np/
    # --host-discovery-script routed to _run_elastic).
    parser.add_argument("--min-np", type=int, default=None,
                        help="minimum workers to keep an elastic job alive")
    parser.add_argument("--max-np", type=int, default=None,
                        help="maximum workers an elastic job may use")
    parser.add_argument("--host-discovery-script", default=None,
                        help="script printing current 'host:slots' lines; "
                             "enables elastic mode")
    parser.add_argument("--reset-limit", type=int, default=None,
                        help="max elastic resets before the job aborts")
    # Control-plane HA flags (docs/fault_tolerance.md "Control-plane
    # HA"): journaled driver state + warm-standby failover.
    parser.add_argument("--journal-dir", default=None,
                        help="directory for the driver's control-plane "
                             "journal (sets HVDTPU_DRIVER_JOURNAL; "
                             "enables the /journal standby-sync route)")
    parser.add_argument("--standby", default=None, metavar="HOST:PORT",
                        help="run as a warm STANDBY tailing the primary "
                             "driver at HOST:PORT; promotes itself when "
                             "the primary's lease expires (requires the "
                             "shared HVDTPU_JOB_TOKEN)")
    parser.add_argument("--standby-endpoints", default=None,
                        metavar="HOST:PORT[,...]",
                        help="primary: ordered standby endpoints exported "
                             "to workers as HVDTPU_RENDEZVOUS_ADDRS for "
                             "KV failover (sets "
                             "HVDTPU_DRIVER_STANDBY_ADDRS)")
    parser.add_argument("--driver-port", type=int, default=None,
                        help="fixed KV-store listen port (default: "
                             "ephemeral; standbys need one workers can "
                             "be told in advance)")
    # Runtime knobs -> env.
    parser.add_argument("--fusion-threshold-mb", type=float, default=None)
    parser.add_argument("--cycle-time-ms", type=float, default=None)
    parser.add_argument("--cache-capacity", type=int, default=None)
    parser.add_argument("--timeline-filename", default=None)
    parser.add_argument("--timeline-mark-cycles", action="store_true",
                        help="drop an instant event per negotiation cycle "
                             "into the timeline")
    parser.add_argument("--hierarchical-threshold-mb", type=float,
                        default=None,
                        help="min buffer MiB before multi-host collectives "
                             "take the two-level intra/cross-host path; 0 "
                             "disables (this design's single knob behind "
                             "the reference's --hierarchical-allreduce/"
                             "--hierarchical-allgather pair)")
    parser.add_argument("--autotune", action="store_true")
    parser.add_argument("--autotune-log-file", default=None)
    parser.add_argument("--log-level", default=None)
    parser.add_argument("--stall-check-disable", action="store_true")
    parser.add_argument("--stall-check-time-seconds", type=float,
                        default=None)
    parser.add_argument("--stall-shutdown-time-seconds", type=float,
                        default=None)
    parser.add_argument("--check-build", action="store_true",
                        help="print framework/backend support and exit "
                             "(reference: horovodrun --check-build)")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="the training command to run on every slot")
    args = parser.parse_args(argv)
    if args.check_build or args.version:
        return args
    if not args.command:
        parser.error("no command given")
    if args.command[0] == "--":
        args.command = args.command[1:]
    if args.config_file:
        _apply_config_file(parser, args, argv)
    return args


def _explicit_dests(parser, argv):
    """Dests the user actually passed on the command line — re-parse
    with all defaults suppressed so unset flags don't appear at all
    (a value equal to its default is otherwise indistinguishable)."""
    import copy
    p = copy.deepcopy(parser)
    for action in p._actions:
        action.default = argparse.SUPPRESS
    ns, _ = p.parse_known_args(argv if argv is not None
                               else sys.argv[1:])
    return set(vars(ns))


def _apply_config_file(parser, args, argv):
    """Fill args from a YAML mapping of flag names (reference:
    horovod/runner/launch.py:513 + common/util/config_parser.py
    set_args_from_config). Explicit CLI flags win even when they equal
    the parser default; values go through the flag's argparse type."""
    import yaml
    with open(args.config_file) as f:
        config = yaml.safe_load(f) or {}
    if not isinstance(config, dict):
        raise SystemExit(f"config file {args.config_file} must be a "
                         "YAML mapping of flag names to values")
    explicit = _explicit_dests(parser, argv)
    actions = {a.dest: a for a in parser._actions}
    for key, value in config.items():
        dest = key.replace("-", "_").lstrip("_")
        if dest in ("command", "config_file", "help"):
            raise SystemExit(f"config file cannot set '{key}'")
        if dest not in actions:
            raise SystemExit(f"unknown config key '{key}' (use hvdrun "
                             "flag names)")
        if dest in explicit:
            # Explicit CLI flags win — including over a malformed
            # config value for the same key.
            continue
        if value is None:
            raise SystemExit(f"config key '{key}' has a null value; "
                             "omit the key or give it a value")
        action = actions[dest]
        if isinstance(action, (argparse._StoreTrueAction,
                               argparse._StoreFalseAction)):
            value = _config_bool(key, value)
        elif action.type is not None:
            try:
                value = action.type(str(value))
            except (TypeError, ValueError):
                raise SystemExit(
                    f"config key '{key}': cannot convert {value!r} "
                    f"to {action.type.__name__}")
        setattr(args, dest, value)


def _config_bool(key, value):
    """Strict boolean for flag-valued config keys: bool('false') being
    True would silently enable a feature the user asked to disable."""
    if isinstance(value, bool):
        return value
    text = str(value).strip().lower()
    if text in ("true", "1", "yes", "on"):
        return True
    if text in ("false", "0", "no", "off"):
        return False
    raise SystemExit(f"config key '{key}': expected a boolean, got "
                     f"{value!r}")


def _knob_env(args):
    env = {}
    if args.fusion_threshold_mb is not None:
        env["HVDTPU_FUSION_THRESHOLD"] = str(
            int(args.fusion_threshold_mb * 1024 * 1024))
    if args.cycle_time_ms is not None:
        env["HVDTPU_CYCLE_TIME"] = str(args.cycle_time_ms)
    if args.cache_capacity is not None:
        env["HVDTPU_CACHE_CAPACITY"] = str(args.cache_capacity)
    if args.timeline_filename:
        env["HVDTPU_TIMELINE"] = args.timeline_filename
    if args.timeline_mark_cycles:
        env["HVDTPU_TIMELINE_MARK_CYCLES"] = "1"
    if args.hierarchical_threshold_mb is not None:
        env["HVDTPU_HIERARCHICAL_THRESHOLD"] = str(
            int(args.hierarchical_threshold_mb * 1024 * 1024))
    if args.autotune:
        env["HVDTPU_AUTOTUNE"] = "1"
    if args.autotune_log_file:
        env["HVDTPU_AUTOTUNE_LOG"] = args.autotune_log_file
    if args.log_level:
        env["HVDTPU_LOG_LEVEL"] = args.log_level
    if args.stall_check_disable:
        env["HVDTPU_STALL_CHECK_DISABLE"] = "1"
    if args.stall_check_time_seconds is not None:
        env["HVDTPU_STALL_CHECK_TIME_SECONDS"] = str(
            args.stall_check_time_seconds)
    if args.stall_shutdown_time_seconds is not None:
        env["HVDTPU_STALL_SHUTDOWN_TIME_SECONDS"] = str(
            args.stall_shutdown_time_seconds)
    return env


def _iface_addr(iface):
    """IPv4 address of a named interface (reference: horovodrun
    --network-interface NIC pinning). None passes through — the driver
    then routes automatically (rendezvous.py _local_ip_towards)."""
    if not iface:
        return None
    import fcntl
    import socket
    import struct
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        # SIOCGIFADDR; ifreq packs the interface name in the first 16
        # bytes, the sockaddr_in's address at offset 20.
        packed = fcntl.ioctl(
            s.fileno(), 0x8915,
            struct.pack("256s", iface.encode()[:15]))
        return socket.inet_ntoa(packed[20:24])
    except OSError as e:
        raise SystemExit(
            f"--network-interface {iface!r}: cannot resolve an IPv4 "
            f"address ({e}); check `ip -4 addr` for available "
            "interfaces")
    finally:
        s.close()


def check_build():
    """Print available frameworks/backends (reference: horovodrun
    --check-build, horovod/runner/launch.py check_build)."""
    from .. import basics

    def probe(mod):
        try:
            __import__(mod)
            return True
        except ImportError:
            return False

    lines = ["horovod_tpu build/runtime support:", "", "Frameworks:"]
    for name, mod in [("jax", "jax"), ("tensorflow", "tensorflow"),
                      ("keras", "keras"), ("pytorch", "torch"),
                      ("mxnet", "mxnet")]:
        lines.append(f"    [{'X' if probe(mod) else ' '}] {name}")
    lines += ["", "Data planes:"]
    xla = probe("jax")
    for name, ok in [("XLA collectives (single + delegated)", xla),
                     ("TCP ring collectives (native core)", True),
                     ("MPI", basics.mpi_built()),
                     ("NCCL", basics.nccl_built())]:
        lines.append(f"    [{'X' if ok else ' '}] {name}")
    lines += ["", "Integrations:"]
    for name, mod in [("spark", "pyspark"), ("ray", "ray")]:
        lines.append(f"    [{'X' if probe(mod) else ' '}] {name}")
    print("\n".join(lines), flush=True)
    return 0


def run_commandline(argv=None):
    args = parse_args(argv)
    if args.version:
        from ..version import __version__
        print(__version__, flush=True)
        return 0
    if args.check_build:
        return check_build()
    settings = Settings(
        num_proc=args.num_proc, hosts=args.hosts, hostfile=args.hostfile,
        start_timeout=args.start_timeout, verbose=args.verbose,
        prefix_output=not args.disable_prefix_output, env=_knob_env(args),
        output_filename=args.output_filename,
        rendezvous_addr=_iface_addr(args.network_interface),
        ssh_port=args.ssh_port,
        ssh_identity_file=args.ssh_identity_file)
    if (args.host_discovery_script or args.min_np or args.max_np
            or args.standby):
        from .elastic_driver import ElasticSettings, launch_elastic_job
        elastic = ElasticSettings(
            settings,
            discovery_script=args.host_discovery_script,
            min_np=args.min_np or 1,
            # None = uncapped: -np is the *starting* size, not a growth
            # limit (matching horovodrun, where --max-np is optional).
            max_np=args.max_np,
            reset_limit=args.reset_limit,
            journal_dir=args.journal_dir,
            standby_addrs=args.standby_endpoints,
            driver_port=args.driver_port)
        if args.standby:
            from .standby import launch_standby
            rc = launch_standby(elastic, args.command, args.standby)
        else:
            rc = launch_elastic_job(elastic, args.command)
    else:
        rc = launch_job(settings, args.command)
    sys.exit(rc)


if __name__ == "__main__":
    run_commandline()
