"""Tiny HTTP KV client used by workers to talk to the launcher's
rendezvous store (reference: horovod/runner/http/http_client.py)."""

import time
import urllib.error
import urllib.request

from .http_server import AUTH_HEADER


def _url(addr, port, scope, key):
    return f"http://{addr}:{port}/{scope}/{key}"


def _request(method, url, data=None, token="", timeout=10):
    req = urllib.request.Request(url, data=data, method=method)
    if token:
        req.add_header(AUTH_HEADER, token)
    return urllib.request.urlopen(req, timeout=timeout)


def put_kv(addr, port, scope, key, value, token="", timeout=10):
    if isinstance(value, str):
        value = value.encode()
    with _request("PUT", _url(addr, port, scope, key), data=value,
                  token=token, timeout=timeout) as resp:
        if resp.status != 200:
            raise RuntimeError(
                f"KV PUT {scope}/{key} failed: HTTP {resp.status}")


def get_kv(addr, port, scope, key, token="", timeout=10):
    """Returns bytes, or None when the key does not exist yet."""
    try:
        with _request("GET", _url(addr, port, scope, key), token=token,
                      timeout=timeout) as resp:
            return resp.read()
    except urllib.error.HTTPError as e:
        if e.code == 404:
            return None
        raise


def delete_kv(addr, port, scope, key, token="", timeout=10):
    with _request("DELETE", _url(addr, port, scope, key), token=token,
                  timeout=timeout):
        pass


def wait_for_kv(addr, port, scope, key, token="", deadline_s=120,
                poll_s=0.05):
    """Poll GET until the key appears; raises TimeoutError."""
    deadline = time.monotonic() + deadline_s
    while True:
        value = get_kv(addr, port, scope, key, token=token)
        if value is not None:
            return value
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"rendezvous key {scope}/{key} not published within "
                f"{deadline_s}s")
        time.sleep(poll_s)
