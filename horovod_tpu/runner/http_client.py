"""Retrying HTTP KV client used by workers to talk to the launcher's
rendezvous store (reference: horovod/runner/http/http_client.py).

Every worker↔driver control-plane exchange — peer rendezvous, elastic
version polls, commit persistence, heartbeats, metric pushes — rides on
these four verbs, so a single transient connection error here used to
kill the very worker elastic mode was keeping alive. Each call now
retries with exponential backoff + jitter under an overall deadline
(``HVDTPU_KV_RETRIES`` / ``HVDTPU_KV_BACKOFF`` / ``HVDTPU_KV_DEADLINE``),
with errors classified retryable vs fatal:

- **retryable**: connection refused/reset/aborted, socket timeouts, DNS
  blips, mid-response disconnects, HTTP 408/425/429 and 5xx — the
  driver restarting, a dropped NAT flow, an overloaded store.
- **fatal**: every other HTTP status — 401/403 mean a bad or missing
  job token and would never succeed on retry; the raised
  ``KVFatalError`` names the op, scope and key.

Retry exhaustion raises ``KVRetryExhaustedError`` (a ``TimeoutError``
subclass, so elastic's reset-retry loop classifies it as transient).
Outcomes feed ``hvd_kv_retries_total{op,outcome}`` (docs/metrics.md);
``kv_get``/``kv_put``/``kv_delete``/``kv_wait`` are chaos injection
points (docs/fault_tolerance.md).
"""

import http.client
import random
import time
import urllib.error
import urllib.request

from ..analysis import sanitizer
from ..chaos import inject as _chaos_inject
from ..telemetry import core as telemetry
from ..utils import envparse
from .http_server import AUTH_HEADER

DEFAULT_RETRIES = 8
DEFAULT_BACKOFF_S = 0.05
DEFAULT_DEADLINE_S = 30.0
_BACKOFF_CAP_S = 2.0
# Transient-by-contract statuses: request timeout, too-early, throttled.
_RETRYABLE_HTTP = {408, 425, 429}


class KVError(RuntimeError):
    """Base for KV client failures; message names op, scope and key."""


class KVFatalError(KVError):
    """Non-retryable KV failure (auth rejection, client error)."""

    def __init__(self, message, code=None):
        super().__init__(message)
        self.code = code


class KVRetryExhaustedError(KVError, TimeoutError):
    """Retry budget or deadline exhausted on a retryable failure.
    Inherits TimeoutError (an OSError) so callers that treat transient
    transport trouble as recoverable — elastic's ``_retry_reset`` —
    classify it correctly without importing this module."""


def _m_retries():
    # Resolved at call time: NULL no-op when HOROVOD_TPU_METRICS is off.
    return telemetry.counter(
        "hvd_kv_retries_total",
        "KV client retry outcomes by operation",
        labelnames=("op", "outcome"))


def _url(addr, port, scope, key):
    return f"http://{addr}:{port}/{scope}/{key}"


def _request(method, url, data=None, token="", timeout=10):
    # hvd-sanitize tripwire: every KV verb funnels through this one
    # urlopen, so a collective-critical thread doing store I/O (outside
    # an explicitly bounded sanitizer.allowed() scope, e.g. the
    # guardian board's short-budget calls) is flagged here.
    sanitizer.check_blocking("urlopen", url)
    req = urllib.request.Request(url, data=data, method=method)
    if token:
        req.add_header(AUTH_HEADER, token)
    return urllib.request.urlopen(req, timeout=timeout)


def _fatal_http(code):
    return not (code in _RETRYABLE_HTTP or code >= 500)


def _retry_params(retries, backoff, deadline):
    if retries is None:
        retries = envparse.get_int(envparse.KV_RETRIES, DEFAULT_RETRIES)
    if backoff is None:
        backoff = envparse.get_float(envparse.KV_BACKOFF,
                                     DEFAULT_BACKOFF_S)
    if deadline is None:
        deadline = envparse.get_float(envparse.KV_DEADLINE,
                                      DEFAULT_DEADLINE_S)
    return retries, backoff, deadline


def _call(op, scope, key, attempt_fn, retries=None, backoff=None,
          deadline=None):
    """Run ``attempt_fn`` under the retry policy. HTTPError reaching
    here is already known non-404 (attempt_fn handles the existence
    contract); fatal statuses raise immediately with the op/scope/key
    named, retryable failures back off exponentially with jitter until
    the attempt budget or the overall deadline runs out."""
    retries, backoff, deadline_s = _retry_params(retries, backoff,
                                                 deadline)
    start = time.monotonic()
    deadline_t = start + deadline_s
    attempt = 0
    while True:
        try:
            out = attempt_fn()
        except urllib.error.HTTPError as e:
            if _fatal_http(e.code):
                _m_retries().labels(op=op, outcome="fatal").inc()
                hint = (" (bad or missing job token?)"
                        if e.code in (401, 403) else "")
                raise KVFatalError(
                    f"KV {op} {scope}/{key} failed: HTTP {e.code} "
                    f"{e.reason}{hint}", code=e.code) from e
            err = e
        except (http.client.HTTPException, OSError) as e:
            # URLError, ConnectionError, socket.timeout, DNS failures,
            # RemoteDisconnected/BadStatusLine — all worth retrying.
            err = e
        else:
            if attempt:
                _m_retries().labels(op=op, outcome="recovered").inc()
            return out
        attempt += 1
        sleep_s = min(backoff * (2 ** (attempt - 1)), _BACKOFF_CAP_S)
        sleep_s *= 0.5 + random.random() / 2  # jitter: [0.5x, 1.0x)
        if attempt > retries or time.monotonic() + sleep_s > deadline_t:
            _m_retries().labels(op=op, outcome="exhausted").inc()
            raise KVRetryExhaustedError(
                f"KV {op} {scope}/{key} failed after {attempt} "
                f"attempt(s) over {time.monotonic() - start:.1f}s: "
                f"{err}") from err
        _m_retries().labels(op=op, outcome="retried").inc()
        time.sleep(sleep_s)


def put_kv(addr, port, scope, key, value, token="", timeout=10,
           retries=None, backoff=None, deadline=None):
    if isinstance(value, str):
        value = value.encode()

    def attempt():
        _chaos_inject("kv_put", scope=scope, key=key)
        with _request("PUT", _url(addr, port, scope, key), data=value,
                      token=token, timeout=timeout):
            pass

    _call("put", scope, key, attempt, retries=retries, backoff=backoff,
          deadline=deadline)


def get_kv(addr, port, scope, key, token="", timeout=10, retries=None,
           backoff=None, deadline=None):
    """Returns bytes, or None when the key does not exist yet (404 is
    the store's existence contract, never retried)."""

    def attempt():
        _chaos_inject("kv_get", scope=scope, key=key)
        try:
            with _request("GET", _url(addr, port, scope, key),
                          token=token, timeout=timeout) as resp:
                return resp.read()
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return None
            raise

    return _call("get", scope, key, attempt, retries=retries,
                 backoff=backoff, deadline=deadline)


def delete_kv(addr, port, scope, key, token="", timeout=10,
              retries=None, backoff=None, deadline=None):
    def attempt():
        _chaos_inject("kv_delete", scope=scope, key=key)
        with _request("DELETE", _url(addr, port, scope, key),
                      token=token, timeout=timeout):
            pass

    _call("delete", scope, key, attempt, retries=retries,
          backoff=backoff, deadline=deadline)


def wait_for_kv(addr, port, scope, key, token="", deadline_s=120,
                poll_s=0.05):
    """Poll GET until the key appears; raises TimeoutError. Transient
    transport trouble mid-poll — even a whole inner retry budget
    exhausting — is swallowed until ``deadline_s``: the wait's own
    deadline is the only thing that ends it. Fatal errors (auth) still
    propagate immediately; waiting out a bad token would always time
    out anyway, with a worse message."""
    deadline = time.monotonic() + deadline_s
    last_err = None
    while True:
        left = deadline - time.monotonic()
        try:
            # The kv_wait chaos point is inside the try: an injected
            # transport error must be swallowed like any other transient
            # (only KVFatalError — a RuntimeError, uncaught below — may
            # end the wait early).
            _chaos_inject("kv_wait", scope=scope, key=key)
            value = get_kv(addr, port, scope, key, token=token,
                           deadline=max(poll_s,
                                        min(DEFAULT_DEADLINE_S, left)))
        except (http.client.HTTPException, OSError) as e:
            # KVRetryExhaustedError is an OSError too: the inner retry
            # budget spending does not end the wait.
            last_err = e
            value = None
        else:
            if value is not None:
                return value
        if time.monotonic() > deadline:
            detail = f" (last transport error: {last_err})" if last_err \
                else ""
            raise TimeoutError(
                f"rendezvous key {scope}/{key} not published within "
                f"{deadline_s}s{detail}")
        time.sleep(poll_s)
