"""Retrying HTTP KV client used by workers to talk to the launcher's
rendezvous store (reference: horovod/runner/http/http_client.py).

Every worker↔driver control-plane exchange — peer rendezvous, elastic
version polls, commit persistence, heartbeats, metric pushes — rides on
these four verbs, so a single transient connection error here used to
kill the very worker elastic mode was keeping alive. Each call now
retries with exponential backoff + jitter under an overall deadline
(``HVDTPU_KV_RETRIES`` / ``HVDTPU_KV_BACKOFF`` / ``HVDTPU_KV_DEADLINE``),
with errors classified retryable vs fatal:

- **retryable**: connection refused/reset/aborted, socket timeouts, DNS
  blips, mid-response disconnects, HTTP 408/425/429 and 5xx — the
  driver restarting, a dropped NAT flow, an overloaded store.
- **fatal**: every other HTTP status — 401/403 mean a bad or missing
  job token and would never succeed on retry; the raised
  ``KVFatalError`` names the op, scope and key.

Retry exhaustion raises ``KVRetryExhaustedError`` (a ``TimeoutError``
subclass, so elastic's reset-retry loop classifies it as transient).

**Control-plane HA** (docs/fault_tolerance.md "Control-plane HA"):
when ``HVDTPU_RENDEZVOUS_ADDRS`` carries an ordered endpoint list
(primary first, then standbys), a call whose per-endpoint retry budget
exhausts on connection-class errors *fails over* to the next endpoint
— counted in ``hvd_kv_endpoint_failover_total`` — and every later call
starts at the active endpoint. Responses carry the store's *term* and
an optional ``X-Hvd-Primary`` hint; the client adopts the highest term
it has seen, stamps it on writes, honors the hint, and surfaces a 409
term fence as ``TermFencedError`` naming both terms (after one retry
with the adopted term — a worker that merely lagged behind a failover
must succeed against the new primary, only a truly stale writer must
fail loud). ``on_new_primary`` registers re-registration hooks for
ephemeral keys (peer addresses, serving members) that are NOT
replicated through the journal and must be republished after a
takeover. With ``HVDTPU_RENDEZVOUS_ADDRS`` unset all of this is one
cached-None check per call.

Outcomes feed ``hvd_kv_retries_total{op,outcome}`` (docs/metrics.md);
``kv_get``/``kv_put``/``kv_delete``/``kv_wait`` are chaos injection
points (docs/fault_tolerance.md).
"""

import http.client
import random
import threading
import time
import urllib.error
import urllib.request

from ..analysis import sanitizer
from ..chaos import inject as _chaos_inject
from ..telemetry import core as telemetry
from ..utils import envparse
from ..utils.logging_util import get_logger
from .http_server import AUTH_HEADER, PRIMARY_HEADER, TERM_HEADER

DEFAULT_RETRIES = 8
DEFAULT_BACKOFF_S = 0.05
DEFAULT_DEADLINE_S = 30.0
_BACKOFF_CAP_S = 2.0
# Transient-by-contract statuses: request timeout, too-early, throttled.
_RETRYABLE_HTTP = {408, 425, 429}


class KVError(RuntimeError):
    """Base for KV client failures; message names op, scope and key."""


class KVFatalError(KVError):
    """Non-retryable KV failure (auth rejection, client error)."""

    def __init__(self, message, code=None):
        super().__init__(message)
        self.code = code


class TermFencedError(KVFatalError):
    """A write was rejected by the store's split-brain fence even
    after adopting the store's term — the writer's view of the control
    plane is authoritatively stale. Never retried."""

    def __init__(self, message, request_term=None, server_term=None):
        super().__init__(message, code=409)
        self.request_term = request_term
        self.server_term = server_term


class KVRetryExhaustedError(KVError, TimeoutError):
    """Retry budget or deadline exhausted on a retryable failure.
    Inherits TimeoutError (an OSError) so callers that treat transient
    transport trouble as recoverable — elastic's ``_retry_reset`` —
    classify it correctly without importing this module."""


def _m_retries():
    # Resolved at call time: NULL no-op when HOROVOD_TPU_METRICS is off.
    return telemetry.counter(
        "hvd_kv_retries_total",
        "KV client retry outcomes by operation",
        labelnames=("op", "outcome"))


def _m_failover():
    return telemetry.counter(
        "hvd_kv_endpoint_failover_total",
        "KV endpoint failovers (active rendezvous endpoint switched)")


# --------------------------------------------------------------------------
# Endpoint failover state (process-wide: the rendezvous store is one
# logical service no matter how many call sites hold its address).
# --------------------------------------------------------------------------

def parse_endpoints(text):
    """``host:port,host:port`` → ordered [(host, port)]; loud on a
    malformed element (a silently dropped standby would turn failover
    into a no-op exactly when it matters)."""
    endpoints = []
    for chunk in (text or "").split(","):
        chunk = chunk.strip()
        if not chunk:
            continue
        host, sep, port = chunk.rpartition(":")
        if not sep or not host:
            raise ValueError(
                f"HVDTPU_RENDEZVOUS_ADDRS element {chunk!r} is not "
                "host:port")
        try:
            endpoints.append((host, int(port)))
        except ValueError:
            raise ValueError(
                f"HVDTPU_RENDEZVOUS_ADDRS element {chunk!r} has a "
                "non-integer port")
    return endpoints


class _Failover:
    """Ordered endpoint list + active index + adopted term."""

    def __init__(self, endpoints):
        self.endpoints = endpoints
        self.active = 0
        self.callbacks = {}   # name -> fn, re-run on primary change

    def plan_for(self, addr, port):
        """Endpoint order for one call: active first, then the rest in
        ring order — or just the caller's endpoint when it is not part
        of the configured list (a serving/test store of its own)."""
        if (addr, port) not in self.endpoints:
            return [(addr, port)]
        n = len(self.endpoints)
        return [self.endpoints[(self.active + i) % n] for i in range(n)]


_STATE_LOCK = threading.RLock()
_FAILOVER = None          # tri-state: None = unresolved, False = off
_TERM = 0                 # highest store term observed by this process
_IN_CALLBACK = threading.local()


def _failover_state():
    global _FAILOVER
    with _STATE_LOCK:
        if _FAILOVER is None:
            text = envparse.get_str(envparse.RENDEZVOUS_ADDRS, "")
            _FAILOVER = _Failover(parse_endpoints(text)) if text \
                else False
        return _FAILOVER if _FAILOVER else None


def reset_failover():
    """Test hook: drop the endpoint list, adopted term and hooks so
    the next call re-resolves from the environment."""
    global _FAILOVER, _TERM
    with _STATE_LOCK:
        _FAILOVER = None
        _TERM = 0


def known_term():
    """The highest store term this process has observed (0 = none)."""
    with _STATE_LOCK:
        return _TERM


def note_term(term):
    global _TERM
    with _STATE_LOCK:
        if term > _TERM:
            _TERM = term


def active_endpoint(addr, port):
    """Where a call addressed to ``(addr, port)`` actually goes right
    now (identity unless that endpoint belongs to the failover list)."""
    fo = _failover_state()
    if fo is None:
        return addr, port
    with _STATE_LOCK:
        return fo.plan_for(addr, port)[0]


def on_new_primary(name, callback):
    """Register (idempotently, keyed by name) a hook run after the
    active endpoint changes — the re-registration path for *ephemeral*
    keys (peer addresses, serving members) the journal deliberately
    does not replicate. No-op when no endpoint list is configured."""
    fo = _failover_state()
    if fo is None:
        return
    with _STATE_LOCK:
        fo.callbacks[name] = callback


def _switch_active(fo, endpoint, reason):
    """Point the process at a new endpoint; fires the re-registration
    hooks (outside the lock, reentrancy-guarded: a hook's own KV write
    must not recurse into more hook runs)."""
    with _STATE_LOCK:
        try:
            idx = fo.endpoints.index(endpoint)
        except ValueError:
            return
        if idx == fo.active:
            return
        fo.active = idx
        callbacks = list(fo.callbacks.items())
    _m_failover().inc()
    get_logger().warning(
        "kv client: rendezvous endpoint failover to %s:%d (%s)",
        endpoint[0], endpoint[1], reason)
    if getattr(_IN_CALLBACK, "active", False):
        return
    _IN_CALLBACK.active = True
    try:
        for name, cb in callbacks:
            try:
                cb()
            except Exception as e:  # noqa: BLE001 — best-effort hooks
                get_logger().warning(
                    "kv client: re-registration hook %s failed after "
                    "failover: %s", name, e)
    finally:
        _IN_CALLBACK.active = False


def _note_headers(headers, fo):
    """Adopt term + primary hint from a response's HA headers."""
    if headers is None:
        return
    raw = headers.get(TERM_HEADER)
    if raw:
        try:
            note_term(int(raw))
        except ValueError:
            pass
    hint = headers.get(PRIMARY_HEADER)
    if hint and fo is not None:
        try:
            parsed = parse_endpoints(hint)
        except ValueError:
            return
        if parsed:
            _switch_active(fo, parsed[0], "primary hint")


def _url(addr, port, scope, key):
    return f"http://{addr}:{port}/{scope}/{key}"


def _request(method, url, data=None, token="", timeout=10, fo=None):
    # hvd-sanitize tripwire: every KV verb funnels through this one
    # urlopen, so a collective-critical thread doing store I/O (outside
    # an explicitly bounded sanitizer.allowed() scope, e.g. the
    # guardian board's short-budget calls) is flagged here.
    sanitizer.check_blocking("urlopen", url)
    req = urllib.request.Request(url, data=data, method=method)
    if token:
        req.add_header(AUTH_HEADER, token)
    if method in ("PUT", "DELETE"):
        term = known_term()
        if term > 0:
            req.add_header(TERM_HEADER, str(term))
    resp = urllib.request.urlopen(req, timeout=timeout)
    _note_headers(resp.headers, fo)
    return resp


def probe_term(addr, port, token="", timeout=2):
    """The store's current term as advertised on its response headers
    (every route carries ``X-Hvd-Term``; /clock is the cheapest), or
    None when unreachable. The one probe primaries and standbys share —
    they must never disagree on how terms are observed."""
    try:
        with _request("GET", f"http://{addr}:{port}/clock", token=token,
                      timeout=timeout) as resp:
            return int(resp.headers.get(TERM_HEADER, 0))
    except urllib.error.HTTPError as e:
        try:
            return int(e.headers.get(TERM_HEADER, 0))
        except (TypeError, ValueError, AttributeError):
            return None
    except Exception:  # noqa: BLE001 — unreachable/refused/timeout
        return None


def _fatal_http(code):
    return not (code in _RETRYABLE_HTTP or code >= 500)


def _retry_params(retries, backoff, deadline):
    if retries is None:
        retries = envparse.get_int(envparse.KV_RETRIES, DEFAULT_RETRIES)
    if backoff is None:
        backoff = envparse.get_float(envparse.KV_BACKOFF,
                                     DEFAULT_BACKOFF_S)
    if deadline is None:
        deadline = envparse.get_float(envparse.KV_DEADLINE,
                                      DEFAULT_DEADLINE_S)
    return retries, backoff, deadline


def _fence_info(err):
    """(request_term, server_term) from a 409 term-fence body, or None
    when the 409 is something else."""
    import json
    try:
        body = json.loads(err.read().decode())
    except Exception:  # noqa: BLE001 — any unreadable body: not a fence
        return None
    if body.get("error") != "term_fenced":
        return None
    return body.get("request_term"), body.get("server_term")


def _call(op, scope, key, attempt_fn, addr, port, retries=None,
          backoff=None, deadline=None):
    """Run ``attempt_fn(addr, port)`` under the retry policy.
    HTTPError reaching here is already known non-404 (attempt_fn
    handles the existence contract); fatal statuses raise immediately
    with the op/scope/key named; retryable failures back off
    exponentially with jitter, failing over along the configured
    endpoint list when one endpoint's budget exhausts, until the
    overall deadline runs out."""
    retries, backoff, deadline_s = _retry_params(retries, backoff,
                                                 deadline)
    fo = _failover_state()
    plan = fo.plan_for(addr, port) if fo is not None else [(addr, port)]
    start = time.monotonic()
    deadline_t = start + deadline_s
    attempt = 0
    ep_idx = 0
    fence_retried = False
    while True:
        ep_addr, ep_port = plan[ep_idx]
        try:
            out = attempt_fn(ep_addr, ep_port)
        except urllib.error.HTTPError as e:
            _note_headers(getattr(e, "headers", None), fo)
            if e.code == 409:
                fence = _fence_info(e)
                if fence is not None:
                    req_term, srv_term = fence
                    if srv_term is not None:
                        note_term(int(srv_term))
                    if not fence_retried:
                        # One immediate retry with the adopted term: a
                        # worker that only LAGGED the failover must
                        # succeed against the new primary.
                        fence_retried = True
                        _m_retries().labels(op=op,
                                            outcome="retried").inc()
                        continue
                    _m_retries().labels(op=op, outcome="fatal").inc()
                    raise TermFencedError(
                        f"KV {op} {scope}/{key} term-fenced by "
                        f"{ep_addr}:{ep_port}: request term "
                        f"{req_term} < store term {srv_term} — a newer "
                        "primary owns this control plane",
                        request_term=req_term,
                        server_term=srv_term) from e
            if _fatal_http(e.code):
                _m_retries().labels(op=op, outcome="fatal").inc()
                hint = (" (bad or missing job token?)"
                        if e.code in (401, 403) else "")
                raise KVFatalError(
                    f"KV {op} {scope}/{key} failed: HTTP {e.code} "
                    f"{e.reason}{hint}", code=e.code) from e
            err = e
        except (http.client.HTTPException, OSError) as e:
            # URLError, ConnectionError, socket.timeout, DNS failures,
            # RemoteDisconnected/BadStatusLine — all worth retrying.
            err = e
        else:
            if attempt or ep_idx:
                _m_retries().labels(op=op, outcome="recovered").inc()
            if ep_idx and fo is not None:
                # This endpoint answered after earlier ones failed:
                # make it the active primary for every later call.
                _switch_active(fo, (ep_addr, ep_port),
                               "answered after failover probe")
            return out
        attempt += 1
        sleep_s = min(backoff * (2 ** (attempt - 1)), _BACKOFF_CAP_S)
        sleep_s *= 0.5 + random.random() / 2  # jitter: [0.5x, 1.0x)
        if attempt > retries or time.monotonic() + sleep_s > deadline_t:
            if ep_idx + 1 < len(plan) \
                    and time.monotonic() < deadline_t:
                # Per-endpoint budget spent: try the next endpoint in
                # the configured order with a fresh attempt budget
                # (the overall deadline still bounds the whole call).
                ep_idx += 1
                attempt = 0
                _m_retries().labels(op=op, outcome="retried").inc()
                continue
            _m_retries().labels(op=op, outcome="exhausted").inc()
            raise KVRetryExhaustedError(
                f"KV {op} {scope}/{key} failed after {attempt} "
                f"attempt(s) over {time.monotonic() - start:.1f}s "
                f"across {ep_idx + 1} endpoint(s): {err}") from err
        _m_retries().labels(op=op, outcome="retried").inc()
        time.sleep(sleep_s)


def put_kv(addr, port, scope, key, value, token="", timeout=10,
           retries=None, backoff=None, deadline=None):
    if isinstance(value, str):
        value = value.encode()

    def attempt(ep_addr, ep_port):
        _chaos_inject("kv_put", scope=scope, key=key)
        with _request("PUT", _url(ep_addr, ep_port, scope, key),
                      data=value, token=token, timeout=timeout,
                      fo=_failover_state()):
            pass

    _call("put", scope, key, attempt, addr, port, retries=retries,
          backoff=backoff, deadline=deadline)


def get_kv(addr, port, scope, key, token="", timeout=10, retries=None,
           backoff=None, deadline=None):
    """Returns bytes, or None when the key does not exist yet (404 is
    the store's existence contract, never retried)."""

    def attempt(ep_addr, ep_port):
        _chaos_inject("kv_get", scope=scope, key=key)
        try:
            with _request("GET", _url(ep_addr, ep_port, scope, key),
                          token=token, timeout=timeout,
                          fo=_failover_state()) as resp:
                return resp.read()
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return None
            raise

    return _call("get", scope, key, attempt, addr, port,
                 retries=retries, backoff=backoff, deadline=deadline)


def delete_kv(addr, port, scope, key, token="", timeout=10,
              retries=None, backoff=None, deadline=None):
    def attempt(ep_addr, ep_port):
        _chaos_inject("kv_delete", scope=scope, key=key)
        with _request("DELETE", _url(ep_addr, ep_port, scope, key),
                      token=token, timeout=timeout,
                      fo=_failover_state()):
            pass

    _call("delete", scope, key, attempt, addr, port, retries=retries,
          backoff=backoff, deadline=deadline)


def wait_for_kv(addr, port, scope, key, token="", deadline_s=120,
                poll_s=0.05, heal=None, heal_every=1.0):
    """Poll GET until the key appears; raises TimeoutError. Transient
    transport trouble mid-poll — even a whole inner retry budget
    exhausting — is swallowed until ``deadline_s``: the wait's own
    deadline is the only thing that ends it. Fatal errors (auth) still
    propagate immediately; waiting out a bad token would always time
    out anyway, with a worse message.

    ``heal`` (optional) runs every ``heal_every`` seconds while
    waiting — the self-repair hook for waits whose *precondition* can
    be lost while they wait (rendezvous re-verifying its own published
    peer key against a restored/failed-over store). Transport errors
    from the hook are swallowed like any other transient."""
    deadline = time.monotonic() + deadline_s
    last_err = None
    last_heal = time.monotonic()
    while True:
        left = deadline - time.monotonic()
        try:
            # The kv_wait chaos point is inside the try: an injected
            # transport error must be swallowed like any other transient
            # (only KVFatalError — a RuntimeError, uncaught below — may
            # end the wait early).
            _chaos_inject("kv_wait", scope=scope, key=key)
            value = get_kv(addr, port, scope, key, token=token,
                           deadline=max(poll_s,
                                        min(DEFAULT_DEADLINE_S, left)))
        except (http.client.HTTPException, OSError) as e:
            # KVRetryExhaustedError is an OSError too: the inner retry
            # budget spending does not end the wait.
            last_err = e
            value = None
        else:
            if value is not None:
                return value
        now = time.monotonic()
        if heal is not None and now - last_heal >= heal_every:
            last_heal = now
            try:
                heal()
            except (http.client.HTTPException, OSError) as e:
                last_err = e
        if time.monotonic() > deadline:
            detail = f" (last transport error: {last_err})" if last_err \
                else ""
            raise TimeoutError(
                f"rendezvous key {scope}/{key} not published within "
                f"{deadline_s}s{detail}")
        time.sleep(poll_s)
