"""Shared machinery for cluster-framework launches (Spark / Ray).

The reference's Spark and Ray integrations both reduce to: the driver runs
a rendezvous, the framework places N opaque tasks, and each task derives
its rank/local/cross topology and connects back (reference:
horovod/spark/runner.py:197 task fn + gloo rendezvous;
horovod/ray/runner.py:45-130 Coordinator collecting hostnames -> ranks).
This module is that common core, framework-free and fully testable
without pyspark/ray: `ClusterJob` is the driver side, and
``cluster_task_bootstrap`` is what every placed task calls before
``hvd.init()``.
"""

import os
import socket

from . import http_client
from .http_server import RendezvousServer, new_job_token
from .rendezvous import _local_ip_towards

HOST_SCOPE = "cluster_hosts"


class ClusterJob:
    """Driver-side state for one cluster-framework job."""

    def __init__(self, num_proc, start_timeout=120):
        self.num_proc = num_proc
        self.start_timeout = start_timeout
        self.token = new_job_token()
        self.server = RendezvousServer(job_token=self.token)
        self.port = self.server.start()
        # Routable driver address: hostname resolution commonly yields
        # 127.0.0.1 on cluster nodes, which would make remote workers
        # rendezvous with themselves.
        self.addr = local_driver_ip()

    def task_args(self):
        """The picklable tuple a task needs to bootstrap."""
        return (self.num_proc, self.addr, self.port, self.token,
                self.start_timeout)

    def shutdown(self):
        self.server.stop()


def cluster_task_bootstrap(rank, num_proc, addr, port, token,
                           start_timeout=120):
    """Run inside a placed task BEFORE ``hvd.init()``: exchange hostnames
    through the driver's KV store, derive local/cross ranks (the analog of
    the reference Ray Coordinator's hostname->rank grouping,
    horovod/ray/runner.py:45-130), and export the topology env. Peer
    discovery then rides the normal rendezvous path inside init()."""
    my_host = socket.gethostname()
    http_client.put_kv(addr, port, HOST_SCOPE, str(rank), my_host,
                       token=token)
    hosts = []
    for r in range(num_proc):
        hosts.append(http_client.wait_for_kv(
            addr, port, HOST_SCOPE, str(r), token=token,
            deadline_s=start_timeout).decode())

    # Deterministic local/cross assignment from the (host, rank) pairs —
    # same semantics as the static launcher's slot math (runner/hosts.py).
    local_rank = sum(1 for r in range(rank) if hosts[r] == my_host)
    local_size = sum(1 for h in hosts if h == my_host)
    host_order = list(dict.fromkeys(hosts))
    hosts_at_lr = [h for h in host_order
                   if sum(1 for x in hosts if x == h) > local_rank]
    cross_rank = hosts_at_lr.index(my_host)
    cross_size = len(hosts_at_lr)

    os.environ.update({
        "HVDTPU_RANK": str(rank),
        "HVDTPU_SIZE": str(num_proc),
        "HVDTPU_LOCAL_RANK": str(local_rank),
        "HVDTPU_LOCAL_SIZE": str(local_size),
        "HVDTPU_CROSS_RANK": str(cross_rank),
        "HVDTPU_CROSS_SIZE": str(cross_size),
        "HVDTPU_RENDEZVOUS_ADDR": addr,
        "HVDTPU_RENDEZVOUS_PORT": str(port),
        "HVDTPU_JOB_TOKEN": token,
        "HVDTPU_START_TIMEOUT": str(start_timeout),
    })
    os.environ.pop("HVDTPU_PEERS", None)


def local_driver_ip():
    """Best-effort routable driver address (loopback jobs use 127.0.0.1;
    no packets are sent — UDP connect only performs routing)."""
    try:
        return _local_ip_towards("8.8.8.8", 53)
    except OSError:
        return "127.0.0.1"
