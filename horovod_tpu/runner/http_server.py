"""In-driver HTTP key-value store + rendezvous server.

The launcher runs one of these; workers discover each other through it
instead of receiving a hand-assembled peer list (reference:
horovod/runner/http/http_server.py:35-192 — ``KVStoreHandler`` GET/PUT,
``RendezvousServer``). The store is scoped (``/scope/key``) and
authenticated with a per-job token carried in a header, the analog of the
reference's HMAC-signed service messages
(horovod/runner/common/util/secret.py).

The server also exposes the metrics plane: ``GET /metrics`` serves the
local telemetry registry as Prometheus text plus the cluster roll-up of
worker-pushed rank snapshots, ``GET /metrics.json`` the raw snapshots —
both behind the same job token (docs/metrics.md).

The serving plane rides the same server (docs/serving.md): attaching a
``serving_router`` or ``serving_worker`` (``attach_serving``) enables
the token-gated ``POST /v1/generate``, ``GET /v1/serving/stats`` and
``POST /v1/serving/drain`` routes — the router and every serving
worker host their HTTP surface through this one handler.
"""

import secrets
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

AUTH_HEADER = "X-Hvdtpu-Job-Token"


def new_job_token():
    return secrets.token_hex(16)


class _KVStoreHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def _split(self):
        parts = [p for p in self.path.split("/") if p]
        if len(parts) != 2:
            return None, None
        return parts[0], parts[1]

    def _authorized(self):
        token = self.server.job_token
        if token and self.headers.get(AUTH_HEADER) != token:
            self.send_response(403)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return False
        return True

    def _serving_target(self):
        """The attached serving endpoint: the router when one is
        attached, else the worker, else None (routes answer 404)."""
        return (getattr(self.server, "serving_router", None)
                or getattr(self.server, "serving_worker", None))

    def _reply_json(self, code, obj):
        import json as _json
        body = _json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        if code == 429:
            # Backpressure contract (docs/serving.md): clients are told
            # when to come back instead of hammering the queue limit.
            self.send_header(
                "Retry-After",
                str(obj.get("retry_after", 1.0) if isinstance(obj, dict)
                    else 1.0))
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):  # noqa: N802
        """Serving-plane routes: /v1/generate, /v1/serving/drain."""
        if not self._authorized():
            return
        import json as _json
        target = self._serving_target()
        if self.path not in ("/v1/generate", "/v1/serving/drain") \
                or target is None:
            return self._reply(404, b"")
        length = int(self.headers.get("Content-Length", 0))
        raw = self.rfile.read(length)
        try:
            payload = _json.loads(raw) if raw else {}
        except ValueError:
            return self._reply_json(400, {"error": "bad JSON body"})
        if not isinstance(payload, dict):
            return self._reply_json(
                400, {"error": "bad JSON body: must be an object"})
        if self.path == "/v1/generate":
            code, body = target.handle_generate(payload)
        else:
            code, body = target.handle_drain(payload)
        self._reply_json(code, body)

    def do_GET(self):  # noqa: N802 (http.server API)
        if not self._authorized():
            return
        if self.path == "/v1/serving/stats":
            target = self._serving_target()
            if target is None:
                return self._reply(404, b"")
            return self._reply_json(200, target.stats())
        parts = [p for p in self.path.split("/") if p]
        if len(parts) == 1 and parts[0] in ("metrics", "metrics.json"):
            return self._serve_metrics(parts[0] == "metrics.json")
        if len(parts) == 1 and parts[0] == "clock":
            # Clock reference for cross-rank trace alignment
            # (tracing/clock.py): workers sample this with an NTP-style
            # round-trip to estimate their offset to the driver.
            import time
            return self._reply(200, repr(time.time()).encode())
        scope, key = self._split()
        if scope is None:
            return self._reply(400, b"")
        with self.server.store_lock:
            value = self.server.store.get(scope, {}).get(key)
        if value is None:
            return self._reply(404, b"")
        self._reply(200, value)

    def do_PUT(self):  # noqa: N802
        if not self._authorized():
            return
        scope, key = self._split()
        if scope is None:
            return self._reply(400, b"")
        length = int(self.headers.get("Content-Length", 0))
        value = self.rfile.read(length)
        with self.server.store_lock:
            self.server.store.setdefault(scope, {})[key] = value
        self._reply(200, b"")

    def do_DELETE(self):  # noqa: N802
        """Delete a key, or a whole scope when the path is ``/scope/_all``
        (the reference's scope-complete handling,
        horovod/runner/http/http_server.py:112-151)."""
        if not self._authorized():
            return
        scope, key = self._split()
        if scope is None:
            return self._reply(400, b"")
        with self.server.store_lock:
            if key == "_all":
                self.server.store.pop(scope, None)
            else:
                self.server.store.get(scope, {}).pop(key, None)
        self._reply(200, b"")

    def _serve_metrics(self, json_mode):
        """Token-gated metrics exposition (docs/metrics.md): the local
        process's registry as Prometheus v0.0.4 text plus, when workers
        have pushed rank snapshots into the ``metrics`` scope, the
        cluster roll-up (``*_cluster{stat=...}``). ``/metrics.json``
        returns ``{"local": ..., "ranks": {rank: snapshot}}``."""
        import json as _json

        from ..telemetry import (METRICS_SCOPE, PROMETHEUS_CONTENT_TYPE,
                                 aggregate_snapshots, parse_rank_snapshots,
                                 render_prometheus, snapshot)
        local = snapshot()
        with self.server.store_lock:
            raw = dict(self.server.store.get(METRICS_SCOPE, {}))
        snaps = parse_rank_snapshots(raw)
        if json_mode:
            body = _json.dumps({"local": local, "ranks": snaps}).encode()
            ctype = "application/json"
        else:
            text = render_prometheus(local)
            if snaps:
                text += render_prometheus(aggregate_snapshots(snaps))
            body = text.encode()
            ctype = PROMETHEUS_CONTENT_TYPE
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _reply(self, code, body):
        self.send_response(code)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        if body:
            self.wfile.write(body)

    def log_message(self, fmt, *args):  # quiet by default
        if self.server.verbose:
            super().log_message(fmt, *args)


class KVStoreServer:
    """Threaded HTTP KV store; binds an ephemeral port on start()."""

    def __init__(self, job_token="", verbose=False, addr="0.0.0.0"):
        self._addr = addr
        self._httpd = None
        self._thread = None
        self.job_token = job_token
        self.verbose = verbose
        self.serving_worker = None
        self.serving_router = None

    @property
    def port(self):
        return self._httpd.server_address[1] if self._httpd else None

    def attach_serving(self, worker=None, router=None):
        """Attach a serving worker/router; enables the /v1 routes
        (callable before or after start())."""
        if worker is not None:
            self.serving_worker = worker
        if router is not None:
            self.serving_router = router
        if self._httpd is not None:
            self._httpd.serving_worker = self.serving_worker
            self._httpd.serving_router = self.serving_router

    def start(self):
        self._httpd = ThreadingHTTPServer((self._addr, 0), _KVStoreHandler)
        self._httpd.store = {}
        self._httpd.store_lock = threading.Lock()
        self._httpd.job_token = self.job_token
        self._httpd.verbose = self.verbose
        self._httpd.serving_worker = self.serving_worker
        self._httpd.serving_router = self.serving_router
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="hvdtpu-kvstore")
        self._thread.start()
        return self.port

    def get(self, scope, key):
        with self._httpd.store_lock:
            return self._httpd.store.get(scope, {}).get(key)

    def put(self, scope, key, value):
        if isinstance(value, str):
            value = value.encode()
        with self._httpd.store_lock:
            self._httpd.store.setdefault(scope, {})[key] = value

    def delete(self, scope, key):
        with self._httpd.store_lock:
            self._httpd.store.get(scope, {}).pop(key, None)

    def scope_keys(self, scope):
        with self._httpd.store_lock:
            return sorted(self._httpd.store.get(scope, {}).keys())

    def clear_scope(self, scope):
        with self._httpd.store_lock:
            self._httpd.store.pop(scope, None)

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._thread.join(timeout=5)
            self._httpd = None


class RendezvousServer(KVStoreServer):
    """KV store pre-loaded with the job's slot table so each worker can
    fetch its assignment by rank (reference: RendezvousServer serving host
    allocations, horovod/runner/http/http_server.py:192)."""

    SLOT_SCOPE = "slots"

    def publish_assignments(self, slots):
        """Store each SlotInfo under slots/<rank> as a csv line."""
        self.clear_scope(self.SLOT_SCOPE)
        for s in slots:
            line = (f"{s.hostname},{s.rank},{s.size},{s.local_rank},"
                    f"{s.local_size},{s.cross_rank},{s.cross_size}")
            self.put(self.SLOT_SCOPE, str(s.rank), line)
        self.put(self.SLOT_SCOPE, "size", str(len(slots)))
