"""In-driver HTTP key-value store + rendezvous server.

The launcher runs one of these; workers discover each other through it
instead of receiving a hand-assembled peer list (reference:
horovod/runner/http/http_server.py:35-192 — ``KVStoreHandler`` GET/PUT,
``RendezvousServer``). The store is scoped (``/scope/key``) and
authenticated with a per-job token carried in a header, the analog of the
reference's HMAC-signed service messages
(horovod/runner/common/util/secret.py).

The server also exposes the metrics plane: ``GET /metrics`` serves the
local telemetry registry as Prometheus text plus the cluster roll-up of
worker-pushed rank snapshots, ``GET /metrics.json`` the raw snapshots —
both behind the same job token (docs/metrics.md).

The serving plane rides the same server (docs/serving.md): attaching a
``serving_router`` or ``serving_worker`` (``attach_serving``) enables
the token-gated ``POST /v1/generate``, ``GET /v1/serving/stats`` and
``POST /v1/serving/drain`` routes — the router and every serving
worker host their HTTP surface through this one handler. Workers
additionally answer ``POST /v1/serving/migrate_in`` (KV-cache live
migration, docs/serving.md "Live migration").
"""

import secrets
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

AUTH_HEADER = "X-Hvdtpu-Job-Token"
#: Control-plane HA headers (docs/fault_tolerance.md "Control-plane
#: HA"): every response advertises the store's current term and, when
#: known, the primary endpoint workers should prefer; PUT/DELETE
#: requests may carry the writer's term, and a term older than the
#: store's is rejected 409 instead of applied (split-brain fencing).
TERM_HEADER = "X-Hvd-Term"
PRIMARY_HEADER = "X-Hvd-Primary"


def new_job_token():
    return secrets.token_hex(16)


class _KVStoreHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def _split(self):
        parts = [p for p in self.path.split("/") if p]
        if len(parts) != 2:
            return None, None
        return parts[0], parts[1]

    def _authorized(self):
        if time.monotonic() < getattr(self.server, "paused_until", 0.0):
            # Simulated network partition (chaos `driver:partition`):
            # drop the request on the floor — the client sees a closed
            # connection, exactly what a partitioned store looks like.
            self.close_connection = True
            return False
        token = self.server.job_token
        if token and self.headers.get(AUTH_HEADER) != token:
            self.send_response(403)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return False
        return True

    def _ha_headers(self):
        """Advertise the store's term + primary hint on every reply."""
        self.send_header(TERM_HEADER, str(self.server.term))
        hint = getattr(self.server, "primary_hint", None)
        if hint:
            self.send_header(PRIMARY_HEADER, hint)

    def _fence_term(self):
        """Apply the request's term header against the store's term.
        Returns True when the mutation may proceed; replies 409 (with
        both terms) and returns False when the writer is stale. A
        NEWER term is adopted — that is how a failed-over worker's
        first write teaches a resurrected stale store that the world
        moved on."""
        raw = self.headers.get(TERM_HEADER)
        if raw is None:
            return True
        try:
            req_term = int(raw)
        except ValueError:
            return True
        from .journal import term_fences
        with self.server.store_lock:
            cur = self.server.term
            if term_fences(req_term, cur):
                stale = True
            else:
                stale = False
                if req_term > cur:
                    self.server.term = req_term
        if stale:
            self._reply_json(409, {"error": "term_fenced",
                                   "request_term": req_term,
                                   "server_term": cur})
            return False
        return True

    def _serving_target(self):
        """The attached serving endpoint: the router when one is
        attached, else the worker, else None (routes answer 404)."""
        return (getattr(self.server, "serving_router", None)
                or getattr(self.server, "serving_worker", None))

    def _reply_json(self, code, obj):
        import json as _json
        body = _json.dumps(obj).encode()
        self.send_response(code)
        self._ha_headers()
        self.send_header("Content-Type", "application/json")
        if code == 429:
            # Backpressure contract (docs/serving.md): clients are told
            # when to come back instead of hammering the queue limit.
            self.send_header(
                "Retry-After",
                str(obj.get("retry_after", 1.0) if isinstance(obj, dict)
                    else 1.0))
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):  # noqa: N802
        """Serving-plane routes: /v1/generate, /v1/serving/drain,
        /v1/serving/migrate_in (worker targets only — migration is
        host-to-host, the router never holds KV pages)."""
        if not self._authorized():
            return
        import json as _json
        target = self._serving_target()
        if self.path not in ("/v1/generate", "/v1/serving/drain",
                             "/v1/serving/migrate_in") \
                or target is None:
            return self._reply(404, b"")
        length = int(self.headers.get("Content-Length", 0))
        raw = self.rfile.read(length)
        try:
            payload = _json.loads(raw) if raw else {}
        except ValueError:
            return self._reply_json(400, {"error": "bad JSON body"})
        if not isinstance(payload, dict):
            return self._reply_json(
                400, {"error": "bad JSON body: must be an object"})
        if self.path == "/v1/generate":
            code, body = target.handle_generate(payload)
        elif self.path == "/v1/serving/migrate_in":
            worker = getattr(self.server, "serving_worker", None)
            if worker is None:
                return self._reply(404, b"")
            code, body = worker.handle_migrate_in(payload)
        else:
            code, body = target.handle_drain(payload)
        self._reply_json(code, body)

    def do_GET(self):  # noqa: N802 (http.server API)
        if not self._authorized():
            return
        if self.path == "/v1/serving/stats":
            target = self._serving_target()
            if target is None:
                return self._reply(404, b"")
            return self._reply_json(200, target.stats())
        if self.path.split("?")[0] == "/journal":
            return self._serve_journal()
        parts = [p for p in self.path.split("/") if p]
        if len(parts) == 1 and parts[0] in ("metrics", "metrics.json"):
            return self._serve_metrics(parts[0] == "metrics.json")
        if len(parts) == 1 and parts[0] == "clock":
            # Clock reference for cross-rank trace alignment
            # (tracing/clock.py): workers sample this with an NTP-style
            # round-trip to estimate their offset to the driver.
            import time
            return self._reply(200, repr(time.time()).encode())
        scope, key = self._split()
        if scope is None:
            return self._reply(400, b"")
        with self.server.store_lock:
            value = self.server.store.get(scope, {}).get(key)
        if value is None:
            return self._reply(404, b"")
        self._reply(200, value)

    def do_PUT(self):  # noqa: N802
        if not self._authorized():
            return
        scope, key = self._split()
        if scope is None:
            return self._reply(400, b"")
        length = int(self.headers.get("Content-Length", 0))
        value = self.rfile.read(length)
        if not self._fence_term():
            return
        with self.server.store_lock:
            self.server.store.setdefault(scope, {})[key] = value
            self._journal_write("kv_put", scope, key, value)
        self._reply(200, b"")

    def do_DELETE(self):  # noqa: N802
        """Delete a key, or a whole scope when the path is ``/scope/_all``
        (the reference's scope-complete handling,
        horovod/runner/http/http_server.py:112-151)."""
        if not self._authorized():
            return
        scope, key = self._split()
        if scope is None:
            return self._reply(400, b"")
        if not self._fence_term():
            return
        with self.server.store_lock:
            if key == "_all":
                self.server.store.pop(scope, None)
            else:
                self.server.store.get(scope, {}).pop(key, None)
            self._journal_write(
                "kv_clear" if key == "_all" else "kv_delete", scope,
                key, None)
        self._reply(200, b"")

    def _journal_write(self, op, scope, key, value):
        """Journal a worker's write when the scope is durable (commits,
        exit markers — docs/fault_tolerance.md). Called UNDER the store
        lock so journal order can never invert store order for racing
        same-key writes (a replayed replica must land on the same final
        value as the live store); durable writes are rare — one per
        worker per membership event — so the fsync under the lock does
        not sit on any hot path."""
        journal = getattr(self.server, "journal", None)
        if journal is None:
            return
        from .journal import durable_key
        if not durable_key(scope, key):
            return
        if op == "kv_put":
            journal.record("kv_put", scope=scope, key=key,
                           value=value.decode("latin-1"))
        elif op == "kv_delete":
            journal.record("kv_delete", scope=scope, key=key)
        else:
            journal.record("kv_clear", scope=scope)

    def _serve_journal(self):
        """Token-gated standby sync route: ``GET /journal?since=N`` →
        ``{"term", "seq", "snapshot", "entries"}`` (journal.py
        sync_payload). 404 when this store has no journal attached —
        the disabled-mode contract leaves no trace of the route."""
        journal = getattr(self.server, "journal", None)
        if journal is None:
            return self._reply(404, b"")
        from urllib.parse import parse_qs, urlparse
        query = parse_qs(urlparse(self.path).query)
        try:
            since = int(query.get("since", ["0"])[0])
        except ValueError:
            return self._reply(400, b"")
        self._reply_json(200, journal.sync_payload(since))

    def _serve_metrics(self, json_mode):
        """Token-gated metrics exposition (docs/metrics.md): the local
        process's registry as Prometheus v0.0.4 text plus, when workers
        have pushed rank snapshots into the ``metrics`` scope, the
        cluster roll-up (``*_cluster{stat=...}``). ``/metrics.json``
        returns ``{"local": ..., "ranks": {rank: snapshot}}``."""
        import json as _json

        from ..telemetry import (METRICS_SCOPE, PROMETHEUS_CONTENT_TYPE,
                                 aggregate_snapshots, parse_rank_snapshots,
                                 render_prometheus, snapshot)
        local = snapshot()
        with self.server.store_lock:
            raw = dict(self.server.store.get(METRICS_SCOPE, {}))
        snaps = parse_rank_snapshots(raw)
        if json_mode:
            body = _json.dumps({"local": local, "ranks": snaps}).encode()
            ctype = "application/json"
        else:
            text = render_prometheus(local)
            if snaps:
                text += render_prometheus(aggregate_snapshots(snaps))
            body = text.encode()
            ctype = PROMETHEUS_CONTENT_TYPE
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _reply(self, code, body):
        self.send_response(code)
        self._ha_headers()
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        if body:
            self.wfile.write(body)

    def log_message(self, fmt, *args):  # quiet by default
        if self.server.verbose:
            super().log_message(fmt, *args)


class KVStoreServer:
    """Threaded HTTP KV store; binds an ephemeral port on start()."""

    def __init__(self, job_token="", verbose=False, addr="0.0.0.0",
                 port=0):
        self._addr = addr
        self._port = port  # 0 = ephemeral; HA standbys bind fixed ports
        self._httpd = None
        self._thread = None
        self.job_token = job_token
        self.verbose = verbose
        self.serving_worker = None
        self.serving_router = None
        # Control-plane HA state (docs/fault_tolerance.md): the highest
        # term this store has observed, an optional journal (enables
        # the /journal route + durable-write journaling), and the
        # primary endpoint hint advertised on every response.
        self._term = 0
        self.journal = None
        self.primary_hint = None

    @property
    def port(self):
        return self._httpd.server_address[1] if self._httpd else None

    @property
    def term(self):
        return self._httpd.term if self._httpd is not None else self._term

    def set_term(self, term):
        """Raise the store's observed term (never lowers it)."""
        if self._httpd is None:
            self._term = max(self._term, int(term))
            return
        with self._httpd.store_lock:
            self._httpd.term = max(self._httpd.term, int(term))

    def set_primary_hint(self, hint):
        self.primary_hint = hint
        if self._httpd is not None:
            self._httpd.primary_hint = hint

    def attach_journal(self, journal):
        """Attach a DriverJournal: enables ``GET /journal`` and the
        durable-scope write-through (callable before or after start)."""
        self.journal = journal
        if self._httpd is not None:
            self._httpd.journal = journal

    def pause_for(self, seconds):
        """Black-hole every request for ``seconds`` — the chaos
        ``driver:partition`` effect (clients see closed connections)."""
        self._httpd.paused_until = time.monotonic() + seconds

    def paused(self):
        return (self._httpd is not None
                and time.monotonic() < self._httpd.paused_until)

    def _check_write_term(self, mutation, writer_term):
        """In-process analog of the HTTP fence: the driver stamps its
        own writes with its term; once the store has observed a newer
        one (a failed-over worker wrote through), the stale driver's
        mutation raises instead of applying. ``None`` = unfenced
        (HA off)."""
        if writer_term is None:
            return
        cur = self._httpd.term
        from .journal import StaleTermError, term_fences
        if term_fences(writer_term, cur):
            raise StaleTermError(mutation, writer_term, cur)
        if writer_term > cur:
            self._httpd.term = writer_term

    def attach_serving(self, worker=None, router=None):
        """Attach a serving worker/router; enables the /v1 routes
        (callable before or after start())."""
        if worker is not None:
            self.serving_worker = worker
        if router is not None:
            self.serving_router = router
        if self._httpd is not None:
            self._httpd.serving_worker = self.serving_worker
            self._httpd.serving_router = self.serving_router

    def start(self):
        self._httpd = ThreadingHTTPServer((self._addr, self._port),
                                          _KVStoreHandler)
        self._httpd.store = {}
        self._httpd.store_lock = threading.Lock()
        self._httpd.job_token = self.job_token
        self._httpd.verbose = self.verbose
        self._httpd.serving_worker = self.serving_worker
        self._httpd.serving_router = self.serving_router
        self._httpd.term = self._term
        self._httpd.journal = self.journal
        self._httpd.primary_hint = self.primary_hint
        self._httpd.paused_until = 0.0
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="hvdtpu-kvstore")
        self._thread.start()
        return self.port

    def get(self, scope, key):
        with self._httpd.store_lock:
            return self._httpd.store.get(scope, {}).get(key)

    def put(self, scope, key, value, term=None):
        if isinstance(value, str):
            value = value.encode()
        with self._httpd.store_lock:
            self._check_write_term(f"put {scope}/{key}", term)
            self._httpd.store.setdefault(scope, {})[key] = value

    def delete(self, scope, key, term=None):
        with self._httpd.store_lock:
            self._check_write_term(f"delete {scope}/{key}", term)
            self._httpd.store.get(scope, {}).pop(key, None)

    def scope_keys(self, scope):
        with self._httpd.store_lock:
            return sorted(self._httpd.store.get(scope, {}).keys())

    def scopes(self):
        with self._httpd.store_lock:
            return sorted(self._httpd.store.keys())

    def clear_scope(self, scope, term=None):
        with self._httpd.store_lock:
            self._check_write_term(f"clear {scope}", term)
            self._httpd.store.pop(scope, None)

    def load_state(self, kv_state):
        """Pre-load durable KV scopes (a journal replica's ``kv``
        partition) — the promotion path re-serving a dead primary's
        commits and assignment table. Existing keys win: anything a
        worker wrote here directly after the primary died is NEWER
        than the replica's journal-replayed value."""
        with self._httpd.store_lock:
            for scope, keys in kv_state.items():
                bucket = self._httpd.store.setdefault(scope, {})
                for key, value in keys.items():
                    bucket.setdefault(key, value.encode("latin-1"))

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._thread.join(timeout=5)
            self._httpd = None


class RendezvousServer(KVStoreServer):
    """KV store pre-loaded with the job's slot table so each worker can
    fetch its assignment by rank (reference: RendezvousServer serving host
    allocations, horovod/runner/http/http_server.py:192)."""

    SLOT_SCOPE = "slots"

    def publish_assignments(self, slots):
        """Store each SlotInfo under slots/<rank> as a csv line."""
        self.clear_scope(self.SLOT_SCOPE)
        for s in slots:
            line = (f"{s.hostname},{s.rank},{s.size},{s.local_rank},"
                    f"{s.local_size},{s.cross_rank},{s.cross_size}")
            self.put(self.SLOT_SCOPE, str(s.rank), line)
        self.put(self.SLOT_SCOPE, "size", str(len(slots)))
