"""Per-host serving worker: the continuous-batching loop, its HTTP
surface, and KV-plane registration + stats push.

One ``ServingWorker`` per serving host: it owns a
:class:`~.scheduler.Scheduler`, steps it on a dedicated loop thread,
and exposes ``POST /v1/generate`` / ``GET /v1/serving/stats`` /
``POST /v1/serving/drain`` through the runner HTTP server
(``serve_http``). Requests block their HTTP handler thread until the
stream completes — the *scheduler's* bounded queue is the only wait
station; a full queue answers 429 immediately (backpressure, never
buffering).

On the control plane the worker registers itself in the launcher KV
store (``serving`` scope, ``member.<cohort>.<wid>`` = ``host:port``)
and pushes a stats snapshot every ``stats_interval`` seconds
(``stats.<cohort>.<wid>``), which is what the router's cohort view and
the autoscaler consume. The same pump polls the cohort drain flag
(``drain.<cohort>``), so ``hvd-serve drain`` reaches workers through
the KV plane alone. Push/poll errors are swallowed — a KV blackout
degrades stats to stale, it never stops serving (the chaos matrix row
pins that).

Live migration (docs/serving.md "Live migration"): a registered worker
wires a :class:`~.migration.Migrator` into its scheduler, accepts
verified KV pages from peers through the token-gated
``POST /v1/serving/migrate_in`` route, and on drain or SIGTERM
hand-off pushes every live sequence to a peer so chip-return latency
decouples from stream length. A stream that migrated away finishes
locally with a ``handoff`` record; the router (or this worker, for
direct clients) follows it to the new host, where the continuation is
token-exact with zero re-prefill.
"""

import collections
import itertools
import json
import threading
import time

from .. import chaos
from ..exceptions import ChaosInjectedError
from ..utils import envparse
from ..utils.logging_util import get_logger
from . import metrics as _m
from . import migration
from .kv_cache import DigestMismatch, MigrationError, NoHeadroom
from .model import ToyLM
from .router import WorkerClient, _TRANSPORT_ERRORS, retry_after_jitter
from .scheduler import Request, Scheduler

#: serving control-plane scope in the launcher KV store.
SERVING_SCOPE = "serving"
#: loop sleep when there is nothing to schedule.
_IDLE_SLEEP_S = 0.002
#: default seconds between stats pushes / drain-flag polls.
STATS_INTERVAL_S = 0.5
#: bound of the attach registry (migrated-in streams awaiting their
#: follower); completed entries are evicted oldest-first at the cap.
ATTACH_CAP = 512
#: handoff hops a worker follows for a direct (router-less) client.
HANDOFF_HOPS = 4


def knob_defaults():
    """The serving knob family resolved through envparse
    (docs/knobs.md)."""
    return {
        "max_batch_tokens": envparse.get_int(
            envparse.SERVING_MAX_BATCH_TOKENS, 256),
        "queue_limit": envparse.get_int(envparse.SERVING_QUEUE_LIMIT, 64),
        "num_pages": envparse.get_int(envparse.SERVING_KV_PAGES, 256),
        "page_size": envparse.get_int(envparse.SERVING_KV_PAGE_SIZE, 16),
        "drain_timeout": envparse.get_float(
            envparse.SERVING_DRAIN_TIMEOUT, 30.0),
    }


class ServingWorker:
    """One serving host: scheduler loop + HTTP + KV registration."""

    def __init__(self, model=None, cohort="c0", wid=0, *,
                 scheduler=None, max_batch_tokens=None, queue_limit=None,
                 num_pages=None, page_size=None, watermark=None,
                 request_timeout_s=120.0, migrate=True):
        knobs = knob_defaults()
        self.model = model if model is not None else ToyLM()
        self.cohort = str(cohort)
        self.wid = int(wid)
        if scheduler is None:
            scheduler = Scheduler(
                self.model,
                max_batch_tokens=(max_batch_tokens
                                  or knobs["max_batch_tokens"]),
                queue_limit=queue_limit or knobs["queue_limit"],
                num_pages=num_pages or knobs["num_pages"],
                page_size=page_size or knobs["page_size"],
                watermark=watermark)
        self.scheduler = scheduler
        self.request_timeout_s = float(request_timeout_s)
        self.drain_timeout_s = knobs["drain_timeout"]
        self._stop = threading.Event()
        self._reqno = itertools.count(1)
        self._loop_thread = None
        self._pump_thread = None
        self._server = None
        self._kv = None      # (addr, port, token) once registered
        self._log = get_logger()
        # -- live migration ------------------------------------------------
        self._migrate = bool(migrate)
        self.migrator = None         # wired at register()
        self.elastic_version = envparse.get_str(
            envparse.ELASTIC_VERSION, "0")
        self.scheduler.elastic_version = self.elastic_version
        self._token = ""             # job token, for peer hand-offs
        self._staging = migration.InboundStaging(
            ttl_s=max(10.0, 2 * migration.knobs()["deadline"]))
        self._attached = collections.OrderedDict()  # rid -> result
        self._attached_lock = threading.Lock()

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        if self._loop_thread is not None:
            return self
        self._loop_thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"hvd-serving-{self.cohort}.{self.wid}")
        self._loop_thread.start()
        return self

    def _loop(self):
        while not self._stop.is_set():
            composition = self.scheduler.step()
            if not composition:
                # Nothing running: wait for arrivals without burning
                # a core (bounded sleep, not a blocking get — drain
                # and stop must stay responsive).
                self._stop.wait(_IDLE_SLEEP_S)

    def stop(self):
        self._stop.set()
        if self._loop_thread is not None:
            self._loop_thread.join(timeout=5)
            self._loop_thread = None
        if self._pump_thread is not None:
            self._pump_thread.join(timeout=5)
            self._pump_thread = None
        if self._server is not None:
            self._server.stop()
            self._server = None

    # -- HTTP surface ------------------------------------------------------
    def serve_http(self, addr="0.0.0.0", token=""):
        """Start a runner HTTP server with this worker attached;
        returns the bound port."""
        from ..runner.http_server import KVStoreServer
        self._server = KVStoreServer(job_token=token, addr=addr)
        self._server.serving_worker = self
        self._token = token or self._token
        port = self._server.start()
        return port

    def handle_generate(self, payload):
        """``(status, body)`` for one request — called from an HTTP
        handler thread (or directly by InProcClient). Blocks until the
        stream completes; 429 body carries a per-request-jittered
        ``retry_after``. A ``{"attach": id}`` payload claims the stream
        of a migrated-in sequence instead of submitting a new one."""
        if not isinstance(payload, dict):
            # A JSON array/scalar body must be a 400, not an
            # AttributeError that resets the connection (the router
            # would read that as a dead worker).
            return 400, {"error": "bad request: body must be a JSON "
                                  "object"}
        if payload.get("attach") is not None:
            return self._handle_attach(payload)
        client_id = str(payload.get("id") or f"r{next(self._reqno)}")
        try:
            # Scheduler ids must be unique per worker lifetime — a
            # client-chosen id re-routed here after a retry must not
            # collide with an in-flight sequence's table entry.
            req = Request(f"{client_id}#{next(self._reqno)}",
                          payload["prompt"],
                          payload.get("max_new_tokens", 16))
        except (KeyError, TypeError, ValueError) as e:
            return 400, {"error": f"bad request: {e}"}
        result = self.scheduler.submit(req)
        if result is None:
            reason = "draining" if self.scheduler.draining \
                else "queue_full"
            _m.rejected_total(reason).inc()
            status = 503 if reason == "draining" else 429
            # Deterministic per-request jitter: synchronized client
            # retries de-herd instead of arriving at the same tick.
            return status, {"error": reason,
                            "retry_after": retry_after_jitter(client_id)}
        try:
            tokens = result.tokens(timeout=self.request_timeout_s)
        except TimeoutError:
            self._log.warning(
                "serving %s.%d: request %s exceeded %.0fs; answering "
                "504", self.cohort, self.wid, client_id,
                self.request_timeout_s)
            return 504, {"error": "generation timed out",
                         "id": client_id}
        summary = dict(result.summary)
        summary["id"] = client_id  # report the caller's id, not the
        #                            suffixed scheduler-unique one
        if summary.get("state") == "migrated":
            return self._reply_migrated(payload, summary, client_id)
        if summary.get("state") != "done":
            # A request the pool/budget can never serve is the
            # client's error (413) — the router must hand it back, not
            # retry it on every member. Runtime failures stay 500.
            status = 413 if summary.get("reason") == "too_large" \
                else 500
            return status, {"error": summary.get("error", "failed"),
                            "id": client_id,
                            "state": summary.get("state")}
        summary["worker"] = f"{self.cohort}.{self.wid}"
        summary["tokens"] = tokens
        return 200, summary

    # -- live migration ----------------------------------------------------
    def _reply_migrated(self, payload, summary, client_id):
        """The stream moved to a peer mid-request. The router asks for
        the raw handoff (``handoff: "return"``) and follows it itself;
        a direct client gets transparency — this worker follows the
        chain and returns the final tokens."""
        handoff = summary.get("handoff") or {}
        if payload.get("handoff") == "return":
            return 200, {"id": client_id, "state": "migrated",
                         "handoff": handoff,
                         "migrations": summary.get("migrations", 1)}
        return self._follow_handoff(handoff, client_id)

    def _follow_handoff(self, handoff, client_id):
        """Chase a migrated stream to its new host (bounded hops);
        ``(status, body)``. A 502 tells the router/client to fall back
        to replaying the request (recompute — never worse than the
        status quo)."""
        url, rid = handoff.get("url"), handoff.get("id")
        for _ in range(HANDOFF_HOPS):
            if not url or not rid:
                break
            client = WorkerClient(url, token=self._token,
                                  timeout_s=self.request_timeout_s)
            try:
                status, body = client.generate(
                    {"attach": rid, "handoff": "return"})
            except _TRANSPORT_ERRORS as e:
                self._log.warning(
                    "serving %s.%d: migrated peer %s unreachable (%s); "
                    "caller falls back to re-route", self.cohort,
                    self.wid, url, e)
                return 502, {"error": "migrated peer unreachable",
                             "id": client_id}
            if status == 200 and body.get("state") == "migrated":
                nxt = body.get("handoff") or {}
                url, rid = nxt.get("url"), nxt.get("id")
                continue
            if status == 200 and isinstance(body, dict):
                body["id"] = client_id
            return status, body
        return 502, {"error": "handoff chain unresolved",
                     "id": client_id}

    def _handle_attach(self, payload):
        """Claim the continuation stream of a migrated-in sequence."""
        rid = str(payload["attach"])
        with self._attached_lock:
            result = self._attached.get(rid)
        if result is None:
            return 404, {"error": f"unknown attach id {rid!r}"}
        try:
            tokens = result.tokens(timeout=self.request_timeout_s)
        except TimeoutError:
            self._log.warning(
                "serving %s.%d: attached stream %s exceeded %.0fs; "
                "answering 504", self.cohort, self.wid, rid,
                self.request_timeout_s)
            return 504, {"error": "generation timed out", "id": rid}
        summary = dict(result.summary)
        client_id = rid.split("#", 1)[0]
        summary["id"] = client_id
        if summary.get("state") == "migrated":
            return self._reply_migrated(payload, summary, client_id)
        if summary.get("state") != "done":
            return 500, {"error": summary.get("error", "failed"),
                         "id": client_id,
                         "state": summary.get("state")}
        summary["worker"] = f"{self.cohort}.{self.wid}"
        summary["tokens"] = tokens
        return 200, summary

    def _attach_put(self, rid, result):
        """Register a migrated-in stream for its follower; bounded —
        completed entries are evicted oldest-first at the cap."""
        with self._attached_lock:
            while len(self._attached) >= ATTACH_CAP:
                done = [k for k, r in self._attached.items()
                        if r.done.is_set()]
                if not done:
                    break
                del self._attached[done[0]]
            self._attached[rid] = result

    def handle_migrate_in(self, payload):
        """``(status, body)`` for one inbound migrate chunk (the
        token-gated ``POST /v1/serving/migrate_in`` route). Chunks
        stage in a bounded buffer; the commit chunk verifies the
        elastic-version fence, then places pages all-or-nothing
        against the watermark (scheduler.import_remote). Every refusal
        is counted in ``hvd_serving_migrations_total{outcome}``."""
        if not isinstance(payload, dict):
            return 400, {"error": "bad request: body must be a JSON "
                                  "object"}
        try:
            chaos.inject("migrate_in", key=str(payload.get("mid", "")),
                         name=f"{self.cohort}.{self.wid}")
        except chaos.ChaosSignal as sig:
            if sig.action == "corrupt":
                migration._corrupt_payload(payload.get("pages") or [])
        except ChaosInjectedError as e:
            return 503, {"error": f"chaos: {e}", "retry_after": 0.05}
        if self.scheduler.draining:
            # A draining worker is shedding sequences, not absorbing
            # them — a deterministic refusal, the source tries the
            # next peer.
            _m.migrations_total("draining").inc()
            return 409, {"error": "draining"}
        try:
            record = self._staging.offer(payload)
        except migration.StagingFull as e:
            return 429, {"error": "migrate staging full",
                         "detail": str(e),
                         "retry_after": retry_after_jitter(
                             payload.get("mid", ""), base=0.1)}
        except (KeyError, TypeError, ValueError) as e:
            return 400, {"error": f"bad migrate chunk: {e}"}
        if record is None:
            return 200, {"staged": payload.get("chunk")}
        if str(record.get("elastic_version", "0")) \
                != str(self.elastic_version):
            _m.migrations_total("version_fence").inc()
            self._log.warning(
                "serving %s.%d: migrate-in of %s fenced: record "
                "version %r vs worker version %r", self.cohort,
                self.wid, record.get("id"),
                record.get("elastic_version"), self.elastic_version)
            return 409, {"error": "version_fenced",
                         "record_version": record.get(
                             "elastic_version"),
                         "worker_version": self.elastic_version}
        try:
            rid, result = self.scheduler.import_remote(record)
        except NoHeadroom as e:
            _m.migrations_total("no_headroom").inc()
            self._log.warning(
                "serving %s.%d: migrate-in of %s refused: %s",
                self.cohort, self.wid, record.get("id"), e)
            return 409, {"error": "no_headroom", "detail": str(e)}
        except DigestMismatch as e:
            _m.migrations_total("digest_mismatch").inc()
            self._log.warning(
                "serving %s.%d: migrate-in of %s REJECTED on digest: "
                "%s", self.cohort, self.wid, record.get("id"), e)
            return 422, {"error": "digest_mismatch", "detail": str(e)}
        except MigrationError as e:
            _m.migrations_total("refused").inc()
            self._log.warning(
                "serving %s.%d: migrate-in of %s refused: %s",
                self.cohort, self.wid, record.get("id"), e)
            return 422, {"error": "geometry_mismatch",
                         "detail": str(e)}
        self._attach_put(rid, result)
        self._log.info(
            "serving %s.%d: imported %s (%d pages, %d tokens done) "
            "from a peer", self.cohort, self.wid, rid,
            len(record.get("pages", ())),
            len(record.get("generated", ())))
        return 200, {"state": "imported", "id": rid,
                     "cohort": self.cohort, "wid": self.wid}

    def migrate_all_out(self):
        """Push every live sequence to a peer (drain / SIGTERM
        hand-off); the count moved — 0 when migration is not wired or
        every transfer fell back."""
        if self.scheduler.migrator is None:
            return 0
        return self.scheduler.migrate_all_out()

    def _kick_migrate_out(self):
        """Start the drain hand-off without blocking the caller (HTTP
        handler / stats pump)."""
        if self.scheduler.migrator is None:
            return
        threading.Thread(
            target=self.scheduler.migrate_all_out, daemon=True,
            name=f"hvd-serving-migrate-{self.cohort}.{self.wid}"
        ).start()

    def handoff(self):
        """SIGTERM hand-off: stop admitting, migrate everything live
        to peers, leave the recompute fallback to finish the rest.
        Returns the number migrated."""
        self.scheduler.drain()
        moved = self.migrate_all_out()
        self._log.warning(
            "serving %s.%d: hand-off migrated %d live sequence(s)",
            self.cohort, self.wid, moved)
        return moved

    def handle_drain(self, payload=None):
        self.scheduler.drain()
        self._kick_migrate_out()
        return 200, {"draining": True,
                     "cohort": self.cohort, "wid": self.wid}

    def stats(self):
        s = self.scheduler.stats()
        s.update(cohort=self.cohort, wid=self.wid, role="worker")
        return s

    # -- drain -------------------------------------------------------------
    def drain(self, timeout=None):
        """Stop admitting, migrate live sequences to peers where the
        migration plane is wired, and wait for what remains to
        complete. Returns True when fully drained within the
        timeout."""
        self.scheduler.drain()
        self.migrate_all_out()
        deadline = time.monotonic() + (timeout if timeout is not None
                                       else self.drain_timeout_s)
        while time.monotonic() < deadline:
            if self.scheduler.idle():
                return True
            time.sleep(0.01)
        return self.scheduler.idle()

    # -- KV-plane registration + stats push --------------------------------
    def register(self, kv_addr, kv_port, token="", advertise=None):
        """Announce this worker under ``serving/member.<cohort>.<wid>``
        and start the stats/drain pump."""
        from ..runner import http_client
        self._kv = (kv_addr, int(kv_port), token)
        self._token = token or self._token
        if self._migrate and self.migrator is None:
            # Peers authenticate with the same job token; discovery
            # rides the member keys this very registration writes.
            self.migrator = migration.Migrator(
                self.cohort, self.wid, kv=self._kv, token=token)
            self.scheduler.migrator = self.migrator
        if advertise:
            member_key = f"member.{self.cohort}.{self.wid}"
            http_client.put_kv(
                kv_addr, kv_port, SERVING_SCOPE, member_key, advertise,
                token=token)

            def _reregister():
                # Serving membership is EPHEMERAL on the HA contract
                # (docs/fault_tolerance.md): after a control-plane
                # failover the journal deliberately carries no member
                # keys, so each worker re-announces itself against the
                # new primary (the stats pump self-heals on its own).
                addr, port, tok = self._kv
                http_client.put_kv(addr, port, SERVING_SCOPE,
                                   member_key, advertise, token=tok,
                                   retries=2, deadline=5.0)

            http_client.on_new_primary(
                f"serving.member.{self.cohort}.{self.wid}", _reregister)
        if self._pump_thread is None:
            self._pump_thread = threading.Thread(
                target=self._stats_pump, daemon=True,
                name=f"hvd-serving-stats-{self.cohort}.{self.wid}")
            self._pump_thread.start()

    def push_stats_once(self):
        """One stats push + drain-flag poll; KV trouble is swallowed
        (stale stats beat a dead worker). Returns True on success."""
        from ..runner import http_client
        if self._kv is None:
            return False
        addr, port, token = self._kv
        try:
            http_client.put_kv(
                addr, port, SERVING_SCOPE,
                f"stats.{self.cohort}.{self.wid}",
                json.dumps(self.stats()), token=token,
                retries=0, deadline=2.0)
            flag = http_client.get_kv(
                addr, port, SERVING_SCOPE, f"drain.{self.cohort}",
                token=token, retries=0, deadline=2.0)
            if not (flag and flag.strip() == b"1"):
                # Per-worker drain: the fleet arbiter ebbs chips back
                # to training one worker at a time, which must not
                # drain the survivors of the same cohort.
                flag = http_client.get_kv(
                    addr, port, SERVING_SCOPE,
                    f"drain.{self.cohort}.{self.wid}",
                    token=token, retries=0, deadline=2.0)
            if flag and flag.strip() == b"1" \
                    and not self.scheduler.draining:
                self._log.warning(
                    "serving %s.%d: drain flag set on the KV plane; "
                    "admission stopped", self.cohort, self.wid)
                self.scheduler.drain()
                # Drain-via-migration: live sequences move to peers so
                # the fleet arbiter gets its chips back in transfer
                # time, not longest-stream time (fallback: they finish
                # locally as before).
                self._kick_migrate_out()
            return True
        except Exception as e:  # noqa: BLE001 — stats are best-effort
            self._log.debug("serving stats push failed: %s", e)
            return False

    def _stats_pump(self):
        while not self._stop.is_set():
            self.push_stats_once()
            self._stop.wait(STATS_INTERVAL_S)
