"""Per-host serving worker: the continuous-batching loop, its HTTP
surface, and KV-plane registration + stats push.

One ``ServingWorker`` per serving host: it owns a
:class:`~.scheduler.Scheduler`, steps it on a dedicated loop thread,
and exposes ``POST /v1/generate`` / ``GET /v1/serving/stats`` /
``POST /v1/serving/drain`` through the runner HTTP server
(``serve_http``). Requests block their HTTP handler thread until the
stream completes — the *scheduler's* bounded queue is the only wait
station; a full queue answers 429 immediately (backpressure, never
buffering).

On the control plane the worker registers itself in the launcher KV
store (``serving`` scope, ``member.<cohort>.<wid>`` = ``host:port``)
and pushes a stats snapshot every ``stats_interval`` seconds
(``stats.<cohort>.<wid>``), which is what the router's cohort view and
the autoscaler consume. The same pump polls the cohort drain flag
(``drain.<cohort>``), so ``hvd-serve drain`` reaches workers through
the KV plane alone. Push/poll errors are swallowed — a KV blackout
degrades stats to stale, it never stops serving (the chaos matrix row
pins that).
"""

import itertools
import json
import threading
import time

from ..utils import envparse
from ..utils.logging_util import get_logger
from . import metrics as _m
from .model import ToyLM
from .scheduler import Request, Scheduler

#: serving control-plane scope in the launcher KV store.
SERVING_SCOPE = "serving"
#: loop sleep when there is nothing to schedule.
_IDLE_SLEEP_S = 0.002
#: default seconds between stats pushes / drain-flag polls.
STATS_INTERVAL_S = 0.5


def knob_defaults():
    """The serving knob family resolved through envparse
    (docs/knobs.md)."""
    return {
        "max_batch_tokens": envparse.get_int(
            envparse.SERVING_MAX_BATCH_TOKENS, 256),
        "queue_limit": envparse.get_int(envparse.SERVING_QUEUE_LIMIT, 64),
        "num_pages": envparse.get_int(envparse.SERVING_KV_PAGES, 256),
        "page_size": envparse.get_int(envparse.SERVING_KV_PAGE_SIZE, 16),
        "drain_timeout": envparse.get_float(
            envparse.SERVING_DRAIN_TIMEOUT, 30.0),
    }


class ServingWorker:
    """One serving host: scheduler loop + HTTP + KV registration."""

    def __init__(self, model=None, cohort="c0", wid=0, *,
                 scheduler=None, max_batch_tokens=None, queue_limit=None,
                 num_pages=None, page_size=None, watermark=None,
                 request_timeout_s=120.0):
        knobs = knob_defaults()
        self.model = model if model is not None else ToyLM()
        self.cohort = str(cohort)
        self.wid = int(wid)
        if scheduler is None:
            scheduler = Scheduler(
                self.model,
                max_batch_tokens=(max_batch_tokens
                                  or knobs["max_batch_tokens"]),
                queue_limit=queue_limit or knobs["queue_limit"],
                num_pages=num_pages or knobs["num_pages"],
                page_size=page_size or knobs["page_size"],
                watermark=watermark)
        self.scheduler = scheduler
        self.request_timeout_s = float(request_timeout_s)
        self.drain_timeout_s = knobs["drain_timeout"]
        self._stop = threading.Event()
        self._reqno = itertools.count(1)
        self._loop_thread = None
        self._pump_thread = None
        self._server = None
        self._kv = None      # (addr, port, token) once registered
        self._log = get_logger()

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        if self._loop_thread is not None:
            return self
        self._loop_thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"hvd-serving-{self.cohort}.{self.wid}")
        self._loop_thread.start()
        return self

    def _loop(self):
        while not self._stop.is_set():
            composition = self.scheduler.step()
            if not composition:
                # Nothing running: wait for arrivals without burning
                # a core (bounded sleep, not a blocking get — drain
                # and stop must stay responsive).
                self._stop.wait(_IDLE_SLEEP_S)

    def stop(self):
        self._stop.set()
        if self._loop_thread is not None:
            self._loop_thread.join(timeout=5)
            self._loop_thread = None
        if self._pump_thread is not None:
            self._pump_thread.join(timeout=5)
            self._pump_thread = None
        if self._server is not None:
            self._server.stop()
            self._server = None

    # -- HTTP surface ------------------------------------------------------
    def serve_http(self, addr="0.0.0.0", token=""):
        """Start a runner HTTP server with this worker attached;
        returns the bound port."""
        from ..runner.http_server import KVStoreServer
        self._server = KVStoreServer(job_token=token, addr=addr)
        self._server.serving_worker = self
        port = self._server.start()
        return port

    def handle_generate(self, payload):
        """``(status, body)`` for one request — called from an HTTP
        handler thread (or directly by InProcClient). Blocks until the
        stream completes; 429 body carries ``retry_after``."""
        if not isinstance(payload, dict):
            # A JSON array/scalar body must be a 400, not an
            # AttributeError that resets the connection (the router
            # would read that as a dead worker).
            return 400, {"error": "bad request: body must be a JSON "
                                  "object"}
        client_id = str(payload.get("id") or f"r{next(self._reqno)}")
        try:
            # Scheduler ids must be unique per worker lifetime — a
            # client-chosen id re-routed here after a retry must not
            # collide with an in-flight sequence's table entry.
            req = Request(f"{client_id}#{next(self._reqno)}",
                          payload["prompt"],
                          payload.get("max_new_tokens", 16))
        except (KeyError, TypeError, ValueError) as e:
            return 400, {"error": f"bad request: {e}"}
        result = self.scheduler.submit(req)
        if result is None:
            reason = "draining" if self.scheduler.draining \
                else "queue_full"
            _m.rejected_total(reason).inc()
            status = 503 if reason == "draining" else 429
            return status, {"error": reason, "retry_after": 1.0}
        try:
            tokens = result.tokens(timeout=self.request_timeout_s)
        except TimeoutError:
            return 504, {"error": "generation timed out",
                         "id": client_id}
        summary = dict(result.summary)
        summary["id"] = client_id  # report the caller's id, not the
        #                            suffixed scheduler-unique one
        if summary.get("state") != "done":
            # A request the pool/budget can never serve is the
            # client's error (413) — the router must hand it back, not
            # retry it on every member. Runtime failures stay 500.
            status = 413 if summary.get("reason") == "too_large" \
                else 500
            return status, {"error": summary.get("error", "failed"),
                            "id": client_id,
                            "state": summary.get("state")}
        summary["worker"] = f"{self.cohort}.{self.wid}"
        summary["tokens"] = tokens
        return 200, summary

    def handle_drain(self, payload=None):
        self.scheduler.drain()
        return 200, {"draining": True,
                     "cohort": self.cohort, "wid": self.wid}

    def stats(self):
        s = self.scheduler.stats()
        s.update(cohort=self.cohort, wid=self.wid, role="worker")
        return s

    # -- drain -------------------------------------------------------------
    def drain(self, timeout=None):
        """Stop admitting, wait for in-flight sequences to complete.
        Returns True when fully drained within the timeout."""
        self.scheduler.drain()
        deadline = time.monotonic() + (timeout if timeout is not None
                                       else self.drain_timeout_s)
        while time.monotonic() < deadline:
            if self.scheduler.idle():
                return True
            time.sleep(0.01)
        return self.scheduler.idle()

    # -- KV-plane registration + stats push --------------------------------
    def register(self, kv_addr, kv_port, token="", advertise=None):
        """Announce this worker under ``serving/member.<cohort>.<wid>``
        and start the stats/drain pump."""
        from ..runner import http_client
        self._kv = (kv_addr, int(kv_port), token)
        if advertise:
            member_key = f"member.{self.cohort}.{self.wid}"
            http_client.put_kv(
                kv_addr, kv_port, SERVING_SCOPE, member_key, advertise,
                token=token)

            def _reregister():
                # Serving membership is EPHEMERAL on the HA contract
                # (docs/fault_tolerance.md): after a control-plane
                # failover the journal deliberately carries no member
                # keys, so each worker re-announces itself against the
                # new primary (the stats pump self-heals on its own).
                addr, port, tok = self._kv
                http_client.put_kv(addr, port, SERVING_SCOPE,
                                   member_key, advertise, token=tok,
                                   retries=2, deadline=5.0)

            http_client.on_new_primary(
                f"serving.member.{self.cohort}.{self.wid}", _reregister)
        if self._pump_thread is None:
            self._pump_thread = threading.Thread(
                target=self._stats_pump, daemon=True,
                name=f"hvd-serving-stats-{self.cohort}.{self.wid}")
            self._pump_thread.start()

    def push_stats_once(self):
        """One stats push + drain-flag poll; KV trouble is swallowed
        (stale stats beat a dead worker). Returns True on success."""
        from ..runner import http_client
        if self._kv is None:
            return False
        addr, port, token = self._kv
        try:
            http_client.put_kv(
                addr, port, SERVING_SCOPE,
                f"stats.{self.cohort}.{self.wid}",
                json.dumps(self.stats()), token=token,
                retries=0, deadline=2.0)
            flag = http_client.get_kv(
                addr, port, SERVING_SCOPE, f"drain.{self.cohort}",
                token=token, retries=0, deadline=2.0)
            if not (flag and flag.strip() == b"1"):
                # Per-worker drain: the fleet arbiter ebbs chips back
                # to training one worker at a time, which must not
                # drain the survivors of the same cohort.
                flag = http_client.get_kv(
                    addr, port, SERVING_SCOPE,
                    f"drain.{self.cohort}.{self.wid}",
                    token=token, retries=0, deadline=2.0)
            if flag and flag.strip() == b"1" \
                    and not self.scheduler.draining:
                self._log.warning(
                    "serving %s.%d: drain flag set on the KV plane; "
                    "admission stopped", self.cohort, self.wid)
                self.scheduler.drain()
            return True
        except Exception as e:  # noqa: BLE001 — stats are best-effort
            self._log.debug("serving stats push failed: %s", e)
            return False

    def _stats_pump(self):
        while not self._stop.is_set():
            self.push_stats_once()
            self._stop.wait(STATS_INTERVAL_S)
