"""The model contract the serving plane schedules, plus the
deterministic ``ToyLM`` stand-in tests and bench serve.

A :class:`ModelAdapter` sees the world in the two phases continuous
batching interleaves:

- :meth:`prefill`: the prompt's KV vectors in one shot (the
  compute-bound phase — its token count is what the scheduler's
  ``max_batch_tokens`` budget meters);
- :meth:`decode`: one token per running sequence given each sequence's
  KV context *as read back through its page table* — decode consumes
  the paged cache, so an adapter never holds per-sequence state of its
  own and preemption/re-routing cannot strand anything inside it.

``ToyLM`` is the CPU-backend stand-in: next token and KV vectors are
pure functions of (params, context), so two hosts loaded with the same
``load_for_inference`` shards provably produce identical streams, a
preempted sequence resumed via prefill recompute provably continues
exactly where it left off, and a re-routed request completes with the
same tokens on the surviving worker.
"""

import numpy as np


class ModelAdapter:
    """Duck-typed contract (ToyLM is the reference implementation).

    Attributes: ``kv_dim`` (per-token KV vector width), ``eos_id``
    (generation stops early on this token; None disables).
    """

    kv_dim = 0
    eos_id = None

    def prefill(self, tokens):
        """``(len(tokens), kv_dim)`` KV vectors for a prompt."""
        raise NotImplementedError

    def decode(self, contexts):
        """One decode step over the running batch: ``contexts`` is a
        list of ``(n_i, kv_dim)`` KV arrays (each gathered through a
        page table); returns ``(next_tokens, next_kv)`` — a list of
        ints and a list of ``(kv_dim,)`` vectors to append."""
        raise NotImplementedError


def toy_params(vocab=97, kv_dim=4):
    """The ToyLM parameter pytree — shaped like a real checkpoint (an
    embedding table + a projection) so the ZeRO-sharded
    ``load_for_inference`` path has something honest to transform.
    Deterministic in (vocab, kv_dim)."""
    emb = np.zeros((vocab, kv_dim), np.float32)
    emb[:, 0] = np.arange(vocab)                       # token identity
    for j in range(1, kv_dim):
        emb[:, j] = (np.arange(vocab) * (j + 3)) % 17  # mixing planes
    proj = np.arange(1, kv_dim + 1, dtype=np.float32)
    return {"emb": emb, "proj": proj}


class ToyLM(ModelAdapter):
    """Deterministic integer LM over ``vocab`` tokens.

    KV vector of token t = ``emb[t]``; the next token is a fixed
    mixing function of the summed KV context and the context length.
    Everything routes through the page-table gather, so the KV pages
    carry the actual information decode needs.
    """

    def __init__(self, params=None, vocab=97, eos_id=None):
        if params is None:
            params = toy_params(vocab=vocab)
        self.params = {k: np.asarray(v, np.float32)
                       for k, v in params.items()}
        self.vocab = int(self.params["emb"].shape[0])
        self.kv_dim = int(self.params["emb"].shape[1])
        self.eos_id = eos_id

    def prefill(self, tokens):
        toks = np.asarray(tokens, np.int64) % self.vocab
        return self.params["emb"][toks]

    def _next(self, context):
        s = float(context.sum(axis=0) @ self.params["proj"]) \
            if context.shape[0] else 0.0
        return int(round(s) + 7 * context.shape[0]) % self.vocab

    def decode(self, contexts):
        next_tokens = [self._next(c) for c in contexts]
        next_kv = [self.params["emb"][t] for t in next_tokens]
        return next_tokens, next_kv

    def reference_completion(self, prompt, max_new_tokens):
        """The exact token stream serving must produce for ``prompt`` —
        the oracle e2e/chaos tests compare re-routed and resumed
        streams against. Runs the same prefill/decode math without any
        paging."""
        ctx = self.prefill(prompt)
        out = []
        for _ in range(int(max_new_tokens)):
            t = self._next(ctx)
            out.append(t)
            if self.eos_id is not None and t == self.eos_id:
                break
            ctx = np.concatenate([ctx, self.params["emb"][t][None]])
        return out
