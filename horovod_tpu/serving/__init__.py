"""horovod_tpu.serving: multi-host continuous-batching inference.

The "millions of users" pillar of the north star (ROADMAP item 1): a
request router on the existing runner HTTP/KV plane feeding per-host
continuous-batching workers, with bounded queues and backpressure end
to end, a paged KV cache with watermark admission and preemption +
recompute-on-resume, sharded model state loaded via the ZeRO-1 plan
geometry (``load_for_inference``), elastic autoscaling of serving
cohorts from queue-depth/latency signals, and SLO telemetry
(``hvd_serving_*`` families, docs/metrics.md).

Layers (docs/serving.md has the full architecture):

- :mod:`kv_cache`   — fixed-size page pool per host, page tables per
  sequence, watermark admission, preemption frees pages.
- :mod:`scheduler`  — continuous batching: prefill admission interleaved
  with in-flight decode steps, the batch recomposed every step.
- :mod:`model`      — the ``ModelAdapter`` contract + the deterministic
  ``ToyLM`` stand-in tests/bench serve.
- :mod:`worker`     — per-host serving loop, HTTP surface, KV-plane
  registration + stats push.
- :mod:`router`     — assigns requests to host cohorts, 429 +
  Retry-After past the queue limit, re-routes streams off dead workers.
- :mod:`state`      — ``load_for_inference``: train (mesh, layout) →
  inference layout on the ZeRO plan geometry, gather-free where shapes
  allow (the 2112.01075 redistribution paving stone).
- :mod:`autoscale`  — queue-depth/latency driven cohort scale-up and
  drain-first scale-down.

Enable with ``HVDTPU_SERVING=1`` (all knobs: docs/knobs.md). CLI:
``hvd-serve route|stats|drain``.
"""

from .kv_cache import PagePool, PageTable, PoolExhausted  # noqa: F401
from .model import ModelAdapter, ToyLM  # noqa: F401
from .scheduler import Request, Scheduler, SequenceResult  # noqa: F401
from .worker import ServingWorker  # noqa: F401
from .router import Router, WorkerClient, InProcClient  # noqa: F401
from .autoscale import Autoscaler  # noqa: F401


def load_for_inference(*args, **kwargs):
    """Lazy re-export of :func:`state.load_for_inference` (the state
    module imports jax; the serving hot path does not need it)."""
    from .state import load_for_inference as _impl
    return _impl(*args, **kwargs)
