"""Train layout → inference layout: ``load_for_inference``.

The serving plane consumes the same parameters ZeRO-1 training
produced, but in a different geometry: training's portable layout is
*flat bucket shards* — each of ``n`` train ranks owns contiguous
``shard_len`` slices of the padded fusion buckets ``ops.zero.plan_zero``
derived — while inference wants *per-leaf* arrays, replicated across
the serving cohort or row-sharded over ``serving_world`` hosts.

This module is the first concrete consumer of the portable
redistribution direction (PAPERS.md 2112.01075, ROADMAP item 3): the
transform is expressed as a source-spec × target-spec range program
(:func:`plan_inference_ranges`) — for every (serving host, leaf), the
exact ``(bucket, src_rank, src_offset, length)`` ranges that assemble
it — executed host-side over whichever shards are addressable. A
(host, leaf) pair whose ranges all land in ONE source shard is
**gather-free**: the leaf is a copy out of a single rank's shard, no
cross-rank assembly at all (shapes allow this whenever a leaf's flat
extent does not straddle a shard boundary).

Two entry points:

- :func:`load_for_inference` — from a live params pytree (single-
  controller meshes; leaves must be fully addressable, the same
  contract as ``zero.reshard_state``);
- :func:`load_from_shards` — from per-rank flat bucket shards (the
  checkpointed form), running the range program directly.
"""

import numpy as np

REPLICATED = "replicated"
ROWS = "rows"


class _Range:
    """One copy instruction: ``length`` elements from
    ``shards[src_rank][bucket]`` at ``src_offset`` into the assembled
    leaf at ``dst_offset``."""

    __slots__ = ("bucket", "src_rank", "src_offset", "length",
                 "dst_offset")

    def __init__(self, bucket, src_rank, src_offset, length, dst_offset):
        self.bucket = bucket
        self.src_rank = src_rank
        self.src_offset = src_offset
        self.length = length
        self.dst_offset = dst_offset

    def __repr__(self):
        return (f"_Range(b{self.bucket} r{self.src_rank}"
                f"[{self.src_offset}:{self.src_offset + self.length}] "
                f"-> dst[{self.dst_offset}])")


def row_slice(dim0, world, host):
    """Contiguous near-even row range [lo, hi) of host ``host``."""
    dim0, world, host = int(dim0), int(world), int(host)
    return (dim0 * host) // world, (dim0 * (host + 1)) // world


def plan_inference_ranges(plan, serving_world, layout=REPLICATED):
    """The redistribution program: ``ranges[host][leaf]`` = list of
    :class:`_Range`, plus ``gather_free[host][leaf]`` flags (True when
    the leaf assembles from a single source shard).

    A thin wrapper over the redistribution planner
    (``horovod_tpu/resharding/``): source = the ZeRO flat-shard layout
    of ``plan``, destination = replicated or near-even dim-0 rows over
    ``serving_world`` hosts; the planner's copies — adjacent windows
    re-merged, since serving consumes whole ranges — ARE the ranges
    this module used to derive by hand."""
    from .. import resharding
    serving_world = int(serving_world)
    if serving_world < 1:
        raise ValueError("serving_world must be >= 1")
    if layout not in (REPLICATED, ROWS):
        raise ValueError(f"unknown inference layout {layout!r}")
    meta = list(zip(plan.leaf_shapes, plan.leaf_dtypes))
    src = resharding.zero_flat_spec(plan, axis="z")
    if layout == ROWS:
        dst = resharding.Spec(
            {"s": serving_world},
            [resharding.Sharded("s", 0, even=False) for _ in meta])
    else:
        dst = resharding.replicated_spec(len(meta),
                                         {"s": serving_world})
    program = resharding.plan_redistribution(src, dst, meta)
    per_host = [[[] for _ in meta] for _ in range(serving_world)]
    for step in program.steps:
        for c in step.copies:
            per_host[c.dst_rank][c.leaf].append(c)
    ranges, gather_free = [], []
    for host in range(serving_world):
        host_ranges, host_free = [], []
        for i in range(len(meta)):
            leaf_ranges = []
            for c in sorted(per_host[host][i],
                            key=lambda c: c.dst_off):
                k = c.src_buf[1]
                prev = leaf_ranges[-1] if leaf_ranges else None
                if prev is not None and prev.bucket == k \
                        and prev.src_rank == c.src_rank \
                        and prev.src_offset + prev.length \
                        == c.src_off \
                        and prev.dst_offset + prev.length \
                        == c.dst_off:
                    prev.length += c.length
                else:
                    leaf_ranges.append(_Range(k, c.src_rank,
                                              c.src_off, c.length,
                                              c.dst_off))
            host_ranges.append(leaf_ranges)
            host_free.append(len({rg.src_rank for rg in leaf_ranges})
                             <= 1)
        ranges.append(host_ranges)
        gather_free.append(host_free)
    return ranges, gather_free


def _leaf_from_ranges(leaf_ranges, shards, dtype):
    total = sum(r.length for r in leaf_ranges)
    out = np.empty((total,), dtype)
    for r in leaf_ranges:
        src = np.asarray(shards[r.src_rank][r.bucket]).reshape(-1)
        out[r.dst_offset:r.dst_offset + r.length] = \
            src[r.src_offset:r.src_offset + r.length]
    return out


def load_from_shards(shards, plan, serving_world=1, serving_rank=0,
                     layout=REPLICATED, treedef=None):
    """Assemble THIS serving host's parameter leaves from per-rank flat
    bucket shards.

    ``shards``: mapping ``src_rank -> [per-bucket (shard_len,) arrays]``
    (only the ranks the range program touches need to be present — a
    gather-free host passes exactly one). Returns ``(leaves_or_tree,
    report)``; leaves are reshaped to the (possibly row-sliced) leaf
    shapes, and ``report['gather_free']`` lists the per-leaf flags.
    """
    ranges, free = plan_inference_ranges(plan, serving_world, layout)
    host_ranges = ranges[int(serving_rank)]
    host_free = free[int(serving_rank)]
    leaves = []
    for i, (leaf_ranges, shape) in enumerate(
            zip(host_ranges, plan.leaf_shapes)):
        needed = {r.src_rank for r in leaf_ranges}
        missing = needed - set(shards)
        if missing:
            raise KeyError(
                f"leaf {i} needs source shard(s) from rank(s) "
                f"{sorted(missing)} which were not provided")
        flat = _leaf_from_ranges(leaf_ranges, shards,
                                 np.dtype(plan.leaf_dtypes[i]))
        if layout == ROWS and len(shape) >= 1 and shape[0] >= 1:
            lo, hi = row_slice(shape[0], serving_world, serving_rank)
            out_shape = (hi - lo,) + tuple(shape[1:])
        else:
            out_shape = tuple(shape)
        leaves.append(flat.reshape(out_shape))
    report = {
        "layout": layout,
        "serving_world": int(serving_world),
        "serving_rank": int(serving_rank),
        "gather_free": list(host_free),
        "gather_free_leaves": sum(bool(f) for f in host_free),
        "total_leaves": len(host_free),
    }
    if treedef is not None:
        import jax
        return jax.tree.unflatten(treedef, leaves), report
    return leaves, report


def load_for_inference(params, serving_world=1, serving_rank=0,
                       layout=REPLICATED):
    """Transform a live (train-layout) params pytree into this serving
    host's inference layout.

    ``replicated``: every host gets the full tree (host-side numpy —
    inference frameworks feed from host memory). ``rows``: dim-0
    contiguous row slices per host, gather-free by construction (a row
    slice is a view of the addressable array — no collective, no
    assembly). Multi-process global meshes whose leaves this process
    cannot address are refused with the checkpoint route, mirroring
    ``zero.reshard_state``.
    """
    import jax
    if layout not in (REPLICATED, ROWS):
        raise ValueError(f"unknown inference layout {layout!r}")
    serving_world = int(serving_world)
    serving_rank = int(serving_rank)
    if not 0 <= serving_rank < serving_world:
        raise ValueError(
            f"serving_rank {serving_rank} outside world {serving_world}")

    def to_host(leaf):
        if not getattr(leaf, "is_fully_addressable", True):
            raise RuntimeError(
                "serving: cannot read train-layout params in place — a "
                "leaf lives on non-addressable devices (multi-process "
                "global mesh). Checkpoint the train state and "
                "load_from_shards on the serving hosts instead "
                "(docs/serving.md).")
        arr = np.asarray(jax.device_get(leaf))
        if layout == ROWS and arr.ndim >= 1 and arr.shape[0] >= 1:
            lo, hi = row_slice(arr.shape[0], serving_world,
                               serving_rank)
            return arr[lo:hi]
        return arr

    return jax.tree.map(to_host, params)
