"""Paged KV cache: fixed-size page pool per host, page tables per
sequence.

The KV cache is the scarce resource of continuous batching: every
in-flight sequence owns ceil(tokens / page_size) pages of attention
state, and admission control is what keeps the pool from thrashing. The
design is the paged-attention formulation — a fixed pool of fixed-size
pages, per-sequence page tables mapping logical token positions to
physical pages — with two policies layered on top:

- **watermark admission**: a prefill is admitted only when the pool
  would keep ``watermark`` free pages after allocating the prompt; the
  reserve is what lets already-running sequences keep growing during
  decode instead of deadlocking against new arrivals.
- **preemption**: when decode growth does exhaust the pool, the
  scheduler frees a victim sequence's pages wholesale
  (:meth:`PageTable.release`) and re-runs its prefill when pages free
  up — recompute-on-resume, cheaper in page-pool pressure than swapping
  KV state to host memory and exact for deterministic models.

The pool optionally carries real per-token payload (``kv_dim`` > 0):
:meth:`PageTable.append` writes KV vectors into page slots and
:meth:`PageTable.gather` reads the sequence's context back in token
order. Tests and the ToyLM decode through this path, so paging is data
movement, not just bookkeeping.

Live migration (docs/serving.md "Live migration") exports a sequence's
pages in table order — each page carrying a sha256 digest of its used
slots — and imports them on another host all-or-nothing against that
host's watermark: every digest is verified *before* a single page is
allocated, so a refused import leaves the target pool untouched.
"""

import base64
import hashlib
import threading

import numpy as np

# The watermark admission predicate is the protocol spec's (one
# function for prefill admission, import placement, and the hvd-model
# checker's invariant; tests/test_protocol_model.py asserts the
# delegation).
from ..analysis.protocol.migration_spec import admits
from . import metrics as _m

#: Default reserve fraction: admission keeps 1/16 of the pool free.
WATERMARK_FRACTION = 16


class PoolExhausted(RuntimeError):
    """Raised by :meth:`PagePool.alloc` when the pool cannot satisfy an
    allocation; the scheduler catches it and preempts."""


class MigrationError(RuntimeError):
    """Base of every export/import refusal. Every subtype is raised
    *before* the target pool is mutated (all-or-nothing), so a failed
    migration leaves the importer exactly as it was and the caller
    falls back to recompute (the graceful-degradation contract)."""


class DigestMismatch(MigrationError):
    """A page payload does not match its sha256 digest — corruption in
    transit. Import refuses the whole record."""


class GeometryMismatch(MigrationError):
    """The record's page_size/kv_dim/page-count does not fit this
    pool — migrating between incompatible serving configurations."""


class NoHeadroom(MigrationError):
    """Placing the record would dip below this pool's admission
    watermark; the target has no room to host a *growing* sequence."""


class PagePool:
    """Fixed pool of ``num_pages`` pages, ``page_size`` token slots
    each. Thread-safe; the free list is LIFO so hot pages stay hot."""

    def __init__(self, num_pages, page_size, kv_dim=0, watermark=None):
        num_pages = int(num_pages)
        page_size = int(page_size)
        if num_pages < 1 or page_size < 1:
            raise ValueError(
                f"page pool needs >=1 pages of >=1 tokens, got "
                f"{num_pages} x {page_size}")
        self.num_pages = num_pages
        self.page_size = page_size
        self.kv_dim = int(kv_dim)
        if watermark is None:
            watermark = max(1, num_pages // WATERMARK_FRACTION)
        if watermark >= num_pages:
            raise ValueError(
                f"watermark {watermark} leaves no usable pages of "
                f"{num_pages}")
        self.watermark = int(watermark)
        self._lock = threading.Lock()
        self._free = list(range(num_pages - 1, -1, -1))
        self.data = (np.zeros((num_pages, page_size, self.kv_dim),
                              np.float32)
                     if self.kv_dim else None)
        _m.kv_pages_free().set(num_pages)

    # -- accounting --------------------------------------------------------
    @property
    def free_pages(self):
        with self._lock:
            return len(self._free)

    def pages_needed(self, tokens):
        return -(-int(tokens) // self.page_size)  # ceil div

    def can_admit(self, tokens):
        """Watermark admission check: would allocating ``tokens`` worth
        of pages keep the reserve intact?"""
        with self._lock:
            return admits(len(self._free), self.pages_needed(tokens),
                          self.watermark)

    # -- alloc/free --------------------------------------------------------
    def alloc(self, n):
        """``n`` page ids, or :class:`PoolExhausted` (allocation is
        all-or-nothing so a failed grab never strands partial pages)."""
        n = int(n)
        with self._lock:
            if n > len(self._free):
                raise PoolExhausted(
                    f"need {n} pages, {len(self._free)} free "
                    f"(pool {self.num_pages})")
            pages = [self._free.pop() for _ in range(n)]
            free_now = len(self._free)
        _m.kv_pages_free().set(free_now)
        return pages

    def alloc_admit(self, n):
        """``n`` page ids, refused with :class:`NoHeadroom` when the
        grab would dip below the admission watermark. The check and the
        allocation are one critical section — an import can never race
        another allocator into the reserve."""
        n = int(n)
        with self._lock:
            if not admits(len(self._free), n, self.watermark):
                raise NoHeadroom(
                    f"import needs {n} pages but only "
                    f"{len(self._free)} free over a watermark of "
                    f"{self.watermark} (pool {self.num_pages})")
            pages = [self._free.pop() for _ in range(n)]
            free_now = len(self._free)
        _m.kv_pages_free().set(free_now)
        return pages

    def free(self, pages):
        with self._lock:
            self._free.extend(pages)
            free_now = len(self._free)
        _m.kv_pages_free().set(free_now)

    # -- live migration ----------------------------------------------------
    def _page_bytes(self, page, used):
        """Raw payload of one page's first ``used`` slots (b"" for a
        bookkeeping-only pool)."""
        if self.data is None:
            return b""
        return np.ascontiguousarray(
            self.data[page, :used], np.float32).tobytes()

    def export_sequence(self, table):
        """One sequence's KV state as a wire record: page payloads in
        table order, each with a sha256 digest, plus the pool geometry
        the importer must match. Sequence metadata (prompt, generated
        tokens, next position) is layered on by the scheduler."""
        n_tokens = table.num_tokens
        ps = self.page_size
        pages = []
        for idx, page in enumerate(table.pages):
            used = min(ps, n_tokens - idx * ps)
            raw = self._page_bytes(page, used)
            pages.append({
                "payload": base64.b64encode(raw).decode("ascii"),
                "digest": hashlib.sha256(raw).hexdigest(),
            })
        return {"num_tokens": n_tokens, "page_size": ps,
                "kv_dim": self.kv_dim, "pages": pages}

    def import_sequence(self, record):
        """Place an exported record into this pool; returns the new
        :class:`PageTable`. All-or-nothing: geometry and every page
        digest are verified *before* any page is allocated, and the
        allocation itself is watermark-fenced (:meth:`alloc_admit`) —
        any raise leaves the pool's free count exactly as it was."""
        if (int(record["page_size"]) != self.page_size
                or int(record["kv_dim"]) != self.kv_dim):
            raise GeometryMismatch(
                f"record pages are {record['page_size']} slots x "
                f"kv_dim {record['kv_dim']}; this pool is "
                f"{self.page_size} x {self.kv_dim}")
        n_tokens = int(record["num_tokens"])
        pages_meta = record["pages"]
        if self.pages_needed(n_tokens) != len(pages_meta):
            raise GeometryMismatch(
                f"{n_tokens} tokens need "
                f"{self.pages_needed(n_tokens)} pages, record carries "
                f"{len(pages_meta)}")
        ps = self.page_size
        payloads = []
        for idx, pg in enumerate(pages_meta):
            raw = base64.b64decode(pg["payload"])
            if hashlib.sha256(raw).hexdigest() != pg["digest"]:
                raise DigestMismatch(
                    f"page {idx}/{len(pages_meta)} payload does not "
                    f"match its sha256 digest")
            used = min(ps, n_tokens - idx * ps)
            if self.kv_dim and len(raw) != used * self.kv_dim * 4:
                raise GeometryMismatch(
                    f"page {idx} carries {len(raw)} bytes, expected "
                    f"{used * self.kv_dim * 4}")
            payloads.append((raw, used))
        pages = self.alloc_admit(len(pages_meta))   # NoHeadroom
        table = PageTable(self)
        table.pages = pages
        table.num_tokens = n_tokens
        if self.data is not None:
            for page, (raw, used) in zip(pages, payloads):
                self.data[page, :used] = np.frombuffer(
                    raw, np.float32).reshape(used, self.kv_dim)
        return table


class PageTable:
    """One sequence's mapping of logical token positions to physical
    pages. Owned by a single scheduler thread — not itself locked (the
    pool it allocates from is)."""

    __slots__ = ("pool", "pages", "num_tokens")

    def __init__(self, pool):
        self.pool = pool
        self.pages = []
        self.num_tokens = 0

    @property
    def capacity(self):
        return len(self.pages) * self.pool.page_size

    def ensure_capacity(self, total_tokens):
        """Grow the table to hold ``total_tokens``; raises
        :class:`PoolExhausted` (all-or-nothing) when the pool can't."""
        need = self.pool.pages_needed(total_tokens) - len(self.pages)
        if need > 0:
            self.pages.extend(self.pool.alloc(need))

    def append(self, vecs):
        """Write ``(k, kv_dim)`` KV vectors at the next ``k`` token
        slots, allocating pages as needed."""
        vecs = np.asarray(vecs, np.float32)
        k = vecs.shape[0]
        self.ensure_capacity(self.num_tokens + k)
        if self.pool.data is not None:
            ps = self.pool.page_size
            for i in range(k):
                pos = self.num_tokens + i
                self.pool.data[self.pages[pos // ps], pos % ps] = vecs[i]
        self.num_tokens += k

    def gather(self):
        """The sequence's KV context, ``(num_tokens, kv_dim)``, read
        back through the page table in token order."""
        if self.pool.data is None:
            raise ValueError("pool carries no KV payload (kv_dim=0)")
        ps = self.pool.page_size
        full, rem = divmod(self.num_tokens, ps)
        parts = [self.pool.data[p] for p in self.pages[:full]]
        if rem:
            parts.append(self.pool.data[self.pages[full], :rem])
        if not parts:
            return np.zeros((0, self.pool.kv_dim), np.float32)
        return np.concatenate(parts, axis=0)

    def release(self):
        """Free every page (preemption / completion). The table resets
        to empty so a resume re-appends from position 0."""
        if self.pages:
            self.pool.free(self.pages)
        self.pages = []
        self.num_tokens = 0
