"""Paged KV cache: fixed-size page pool per host, page tables per
sequence.

The KV cache is the scarce resource of continuous batching: every
in-flight sequence owns ceil(tokens / page_size) pages of attention
state, and admission control is what keeps the pool from thrashing. The
design is the paged-attention formulation — a fixed pool of fixed-size
pages, per-sequence page tables mapping logical token positions to
physical pages — with two policies layered on top:

- **watermark admission**: a prefill is admitted only when the pool
  would keep ``watermark`` free pages after allocating the prompt; the
  reserve is what lets already-running sequences keep growing during
  decode instead of deadlocking against new arrivals.
- **preemption**: when decode growth does exhaust the pool, the
  scheduler frees a victim sequence's pages wholesale
  (:meth:`PageTable.release`) and re-runs its prefill when pages free
  up — recompute-on-resume, cheaper in page-pool pressure than swapping
  KV state to host memory and exact for deterministic models.

The pool optionally carries real per-token payload (``kv_dim`` > 0):
:meth:`PageTable.append` writes KV vectors into page slots and
:meth:`PageTable.gather` reads the sequence's context back in token
order. Tests and the ToyLM decode through this path, so paging is data
movement, not just bookkeeping.
"""

import threading

import numpy as np

from . import metrics as _m

#: Default reserve fraction: admission keeps 1/16 of the pool free.
WATERMARK_FRACTION = 16


class PoolExhausted(RuntimeError):
    """Raised by :meth:`PagePool.alloc` when the pool cannot satisfy an
    allocation; the scheduler catches it and preempts."""


class PagePool:
    """Fixed pool of ``num_pages`` pages, ``page_size`` token slots
    each. Thread-safe; the free list is LIFO so hot pages stay hot."""

    def __init__(self, num_pages, page_size, kv_dim=0, watermark=None):
        num_pages = int(num_pages)
        page_size = int(page_size)
        if num_pages < 1 or page_size < 1:
            raise ValueError(
                f"page pool needs >=1 pages of >=1 tokens, got "
                f"{num_pages} x {page_size}")
        self.num_pages = num_pages
        self.page_size = page_size
        self.kv_dim = int(kv_dim)
        if watermark is None:
            watermark = max(1, num_pages // WATERMARK_FRACTION)
        if watermark >= num_pages:
            raise ValueError(
                f"watermark {watermark} leaves no usable pages of "
                f"{num_pages}")
        self.watermark = int(watermark)
        self._lock = threading.Lock()
        self._free = list(range(num_pages - 1, -1, -1))
        self.data = (np.zeros((num_pages, page_size, self.kv_dim),
                              np.float32)
                     if self.kv_dim else None)
        _m.kv_pages_free().set(num_pages)

    # -- accounting --------------------------------------------------------
    @property
    def free_pages(self):
        with self._lock:
            return len(self._free)

    def pages_needed(self, tokens):
        return -(-int(tokens) // self.page_size)  # ceil div

    def can_admit(self, tokens):
        """Watermark admission check: would allocating ``tokens`` worth
        of pages keep the reserve intact?"""
        with self._lock:
            return (len(self._free) - self.pages_needed(tokens)
                    >= self.watermark)

    # -- alloc/free --------------------------------------------------------
    def alloc(self, n):
        """``n`` page ids, or :class:`PoolExhausted` (allocation is
        all-or-nothing so a failed grab never strands partial pages)."""
        n = int(n)
        with self._lock:
            if n > len(self._free):
                raise PoolExhausted(
                    f"need {n} pages, {len(self._free)} free "
                    f"(pool {self.num_pages})")
            pages = [self._free.pop() for _ in range(n)]
            free_now = len(self._free)
        _m.kv_pages_free().set(free_now)
        return pages

    def free(self, pages):
        with self._lock:
            self._free.extend(pages)
            free_now = len(self._free)
        _m.kv_pages_free().set(free_now)


class PageTable:
    """One sequence's mapping of logical token positions to physical
    pages. Owned by a single scheduler thread — not itself locked (the
    pool it allocates from is)."""

    __slots__ = ("pool", "pages", "num_tokens")

    def __init__(self, pool):
        self.pool = pool
        self.pages = []
        self.num_tokens = 0

    @property
    def capacity(self):
        return len(self.pages) * self.pool.page_size

    def ensure_capacity(self, total_tokens):
        """Grow the table to hold ``total_tokens``; raises
        :class:`PoolExhausted` (all-or-nothing) when the pool can't."""
        need = self.pool.pages_needed(total_tokens) - len(self.pages)
        if need > 0:
            self.pages.extend(self.pool.alloc(need))

    def append(self, vecs):
        """Write ``(k, kv_dim)`` KV vectors at the next ``k`` token
        slots, allocating pages as needed."""
        vecs = np.asarray(vecs, np.float32)
        k = vecs.shape[0]
        self.ensure_capacity(self.num_tokens + k)
        if self.pool.data is not None:
            ps = self.pool.page_size
            for i in range(k):
                pos = self.num_tokens + i
                self.pool.data[self.pages[pos // ps], pos % ps] = vecs[i]
        self.num_tokens += k

    def gather(self):
        """The sequence's KV context, ``(num_tokens, kv_dim)``, read
        back through the page table in token order."""
        if self.pool.data is None:
            raise ValueError("pool carries no KV payload (kv_dim=0)")
        ps = self.pool.page_size
        full, rem = divmod(self.num_tokens, ps)
        parts = [self.pool.data[p] for p in self.pages[:full]]
        if rem:
            parts.append(self.pool.data[self.pages[full], :rem])
        if not parts:
            return np.zeros((0, self.pool.kv_dim), np.float32)
        return np.concatenate(parts, axis=0)

    def release(self):
        """Free every page (preemption / completion). The table resets
        to empty so a resume re-appends from position 0."""
        if self.pages:
            self.pool.free(self.pages)
        self.pages = []
        self.num_tokens = 0
