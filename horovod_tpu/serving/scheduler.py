"""Continuous-batching scheduler: prefill admission interleaved with
in-flight decode, the batch recomposed every step.

Static batching pads every request to the slowest member and leaves the
accelerator idle in the gaps; continuous batching re-forms the running
batch at every decode step — finished sequences leave immediately, new
prompts prefill into the freed budget, and the decode batch is whatever
is alive *right now*. The scheduler owns that loop:

1. **resume** preempted sequences (LRU order) whose pages fit again —
   recompute-on-resume: the prompt *and* everything generated so far
   re-prefill into fresh pages, which is exact because a ModelAdapter's
   prefill is defined to reproduce the per-token KV appends;
2. **admit** new requests from the bounded queue while the per-step
   prefill token budget (``max_batch_tokens`` minus one slot per
   running sequence) holds and the page pool stays above its admission
   watermark — otherwise admission *blocks* (the request stays queued;
   ``admission_blocked`` counts every refusal so tests can prove the
   watermark engaged);
3. **decode** one token for every running sequence through its page
   table; KV growth that exhausts the pool preempts the
   least-recently-(re)admitted sequence and retries — with a Migrator
   wired (serving/migration.py) the victim is first offered to a peer
   as a verified page transfer, and only a refused/failed transfer
   falls back to the recompute preemption above;
4. **retire** finished sequences (max tokens or EOS), freeing pages and
   completing their streams.

Bounded end to end: the admission queue is a ``queue.Queue(maxsize=
queue_limit)`` — ``submit`` never buffers past it (rule HVD210 exists
to keep it that way) — and each sequence's token stream is bounded by
its own ``max_new_tokens``.

Threading: ``submit``/``stats`` are called from HTTP handler threads,
``step`` from the single worker loop thread; ``_lock`` guards the
shared tables. The scheduler never sleeps — pacing belongs to the
worker loop.
"""

import collections
import itertools
import queue
import threading
import time

from . import metrics as _m
from .kv_cache import PagePool, PageTable, PoolExhausted

#: recent step compositions kept for stats/debug (bounded).
STEP_LOG = 256

QUEUED, PREFILL, RUNNING, PREEMPTED, DONE, FAILED, MIGRATED = (
    "queued", "prefill", "running", "preempted", "done", "failed",
    "migrated")


class Request:
    """One generation request as the scheduler sees it."""

    __slots__ = ("id", "prompt", "max_new_tokens")

    def __init__(self, id, prompt, max_new_tokens):
        self.id = str(id)
        self.prompt = [int(t) for t in prompt]
        self.max_new_tokens = int(max_new_tokens)
        if not self.prompt:
            raise ValueError("empty prompt")
        if self.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")


class SequenceResult:
    """Completion surface of one request: a bounded token stream (one
    slot per possible token + the terminal None) plus a done event and
    the final summary dict."""

    def __init__(self, max_new_tokens):
        self.stream = queue.Queue(maxsize=max_new_tokens + 1)
        self.done = threading.Event()
        self.summary = None

    def finish(self, summary):
        self.summary = summary
        try:
            self.stream.put_nowait(None)
        except queue.Full:  # stream already carries the terminal slot
            pass
        self.done.set()

    def tokens(self, timeout=None):
        """Block until completion; the full generated token list (or
        raises TimeoutError)."""
        if not self.done.wait(timeout):
            raise TimeoutError("generation did not complete in time")
        return list(self.summary["tokens"])


class _Seq:
    __slots__ = ("req", "result", "table", "generated", "state",
                 "t_submit", "t_admit", "t_prefill_done", "t_done",
                 "admit_stamp", "preempts", "migrations")

    def __init__(self, req, result):
        self.req = req
        self.result = result
        self.table = None
        self.generated = []
        self.state = QUEUED
        self.t_submit = time.monotonic()
        self.t_admit = None
        self.t_prefill_done = None
        self.t_done = None
        self.admit_stamp = 0     # LRU key: last (re)admission order
        self.preempts = 0
        self.migrations = 0      # hops this sequence arrived through

    def tokens_alive(self):
        return self.req.prompt + self.generated


class Scheduler:
    """One host's continuous-batching scheduler over a page pool."""

    def __init__(self, model, pool=None, *, max_batch_tokens=256,
                 queue_limit=64, num_pages=256, page_size=16,
                 watermark=None):
        self.model = model
        if pool is None:
            pool = PagePool(num_pages, page_size,
                            kv_dim=model.kv_dim, watermark=watermark)
        self.pool = pool
        self.max_batch_tokens = int(max_batch_tokens)
        self.queue_limit = int(queue_limit)
        # The one place requests wait: bounded, so a flood turns into
        # submit()=False -> 429 at the HTTP layer, never into memory.
        self._admit_q = queue.Queue(maxsize=self.queue_limit)
        self._lock = threading.Lock()
        self._running = {}            # id -> _Seq, decode set
        self._preempted = collections.OrderedDict()  # id -> _Seq, LRU
        self._stamp = itertools.count(1)
        self.step_log = collections.deque(maxlen=STEP_LOG)
        # End-to-end (submit -> done) latencies of recent completions,
        # bounded so stats() can report a rolling p99 without the
        # window itself becoming an unbounded buffer (HVD210).
        self._latency_window = collections.deque(maxlen=256)
        self.draining = False
        self.steps = 0
        self.completed = 0
        self.failed = 0
        self.admission_blocked = 0
        self.tokens_out = 0
        self.preemptions = 0
        # -- live migration (docs/serving.md) --------------------------
        # A Migrator (serving/migration.py) set by the worker once it
        # knows the KV member plane; None = pure recompute, the
        # pre-migration behavior.
        self.migrator = None
        self.elastic_version = "0"   # stamped into exported records
        self.migrated_out = 0
        self.migrated_in = 0
        self.migrate_failed = 0

    # -- intake (HTTP handler threads) -------------------------------------
    def submit(self, req):
        """Queue a request for admission. Returns the
        :class:`SequenceResult` or ``None`` when the bounded queue is
        full / the host is draining (caller answers 429/503)."""
        if self.draining:
            return None
        total = len(req.prompt) + req.max_new_tokens
        if self.pool.pages_needed(total) > self.pool.num_pages \
                - self.pool.watermark:
            res = SequenceResult(req.max_new_tokens)
            res.finish({"id": req.id, "tokens": [], "state": FAILED,
                        "reason": "too_large",
                        "error": "request exceeds KV pool capacity"})
            return res
        if len(req.prompt) > self.max_batch_tokens:
            # A prompt the per-step budget can never prefill would sit
            # at the queue head forever and head-of-line-block every
            # request behind it — reject loudly instead.
            res = SequenceResult(req.max_new_tokens)
            res.finish({"id": req.id, "tokens": [], "state": FAILED,
                        "reason": "too_large",
                        "error": "prompt exceeds the per-step batch "
                                 "budget (HVDTPU_SERVING_MAX_BATCH_"
                                 "TOKENS)"})
            return res
        seq = _Seq(req, SequenceResult(req.max_new_tokens))
        try:
            self._admit_q.put_nowait(seq)
        except queue.Full:
            return None
        _m.queue_depth().set(self._admit_q.qsize())
        return seq.result

    def drain(self):
        """Stop admitting; in-flight and already-queued sequences run
        to completion (docs/serving.md drain semantics)."""
        self.draining = True

    def idle(self):
        with self._lock:
            busy = self._running or self._preempted
        return not busy and self._admit_q.empty()

    # -- admission ---------------------------------------------------------
    def _try_place(self, seq, budget, force=False):
        """Prefill ``seq`` into fresh pages if the watermark and the
        prefill token budget allow. Returns tokens spent (0 = blocked).
        ``force`` waives the token budget (NOT the watermark) — used
        only to resume a preempted sequence into an otherwise-empty
        batch, where pool capacity is the real bound."""
        toks = seq.tokens_alive()
        if len(toks) > budget and not force:
            return 0
        if not self.pool.can_admit(len(toks)):
            self.admission_blocked += 1
            return 0
        table = PageTable(self.pool)
        try:
            table.append(self.model.prefill(toks))
        except PoolExhausted:      # raced below watermark: stay queued
            table.release()
            self.admission_blocked += 1
            return 0
        now = time.monotonic()
        if seq.t_admit is None:
            seq.t_admit = now
            _m.latency("queue").observe(now - seq.t_submit)
        seq.table = table
        seq.state = RUNNING
        seq.admit_stamp = next(self._stamp)
        seq.t_prefill_done = time.monotonic()
        _m.latency("prefill").observe(seq.t_prefill_done - now)
        self._running[seq.req.id] = seq
        return len(toks)

    def _admit(self):
        budget = self.max_batch_tokens - len(self._running)
        # Preempted sequences first, least-recently-admitted order:
        # they already consumed queue latency once and hold completed
        # work worth resuming before fresh prompts pile in.
        for sid in list(self._preempted):
            if budget <= 0:
                break
            seq = self._preempted[sid]
            spent = self._try_place(seq, budget)
            if not spent and not self._running:
                # Nothing else is running and the LRU head still does
                # not fit the step budget (its prompt+generated grew
                # past max_batch_tokens while it was running). One
                # oversized re-prefill step beats a permanent stall:
                # pool capacity (checked at submit) is the real bound.
                spent = self._try_place(seq, budget, force=True)
            if spent:
                del self._preempted[sid]
                budget -= spent
            else:
                break  # LRU head blocked: keep resume order FIFO
        while budget > 0:
            try:
                seq = self._admit_q.get_nowait()
            except queue.Empty:
                break
            spent = self._try_place(seq, budget)
            if spent:
                budget -= spent
            else:
                # Blocked at the watermark/budget: the queue is the
                # wait station — put it back at the front by using a
                # side slot (order preserved for everything behind it).
                self._requeue_front(seq)
                break
        _m.queue_depth().set(self._admit_q.qsize())

    def _requeue_front(self, seq):
        # queue.Queue has no push-front; splice via the internal deque
        # under its own mutex (documented CPython attribute).
        with self._admit_q.mutex:
            self._admit_q.queue.appendleft(seq)
            self._admit_q.unfinished_tasks += 1
            self._admit_q.not_empty.notify()

    # -- preemption --------------------------------------------------------
    def _preempt_lru(self, exclude_id):
        """Free the least-recently-(re)admitted running sequence's
        pages. Migration first when a Migrator is wired: the victim's
        verified KV pages move to a peer with headroom and its stream
        completes there with **zero recompute**; any migration failure
        falls back to the status-quo recompute-on-resume path. Returns
        True when a victim was found (pages freed either way)."""
        victims = [s for s in self._running.values()
                   if s.req.id != exclude_id]
        if not victims:
            return False
        victim = min(victims, key=lambda s: s.admit_stamp)
        if self._try_migrate_out(victim):
            return True
        victim.table.release()
        victim.table = None
        victim.state = PREEMPTED
        victim.preempts += 1
        del self._running[victim.req.id]
        self._preempted[victim.req.id] = victim
        self.preemptions += 1
        _m.preempted_total().inc()
        return True

    # -- live migration ----------------------------------------------------
    def _export_record(self, seq):
        """``seq`` as a migration wire record: KV pages in table order
        (hot) or none at all (a preempted sequence migrates cold and
        resumes by recompute on the target), plus the sequence
        metadata — prompt, generated tokens, next position
        (num_tokens) — and the elastic-version fence."""
        rec = {"v": 1, "id": seq.req.id,
               "prompt": list(seq.req.prompt),
               "generated": list(seq.generated),
               "max_new_tokens": seq.req.max_new_tokens,
               "preempts": seq.preempts,
               "migrations": seq.migrations + 1,
               "elastic_version": str(self.elastic_version)}
        if seq.table is not None:
            rec.update(self.pool.export_sequence(seq.table))
        else:
            rec.update({"num_tokens": 0,
                        "page_size": self.pool.page_size,
                        "kv_dim": self.pool.kv_dim, "pages": []})
        return rec

    def _try_migrate_out(self, seq):
        """Export + hand ``seq`` to a peer through the migrator. True
        when the sequence now lives elsewhere: pages freed, stream
        finished locally with state ``migrated`` and the handoff record
        the router (or the worker itself) follows. False = caller
        falls back to recompute; the migrator has already counted and
        logged why (graceful degradation, never silent)."""
        if self.migrator is None:
            return False
        handoff = self.migrator.migrate_seq(self._export_record(seq))
        if handoff is None:
            self.migrate_failed += 1
            return False
        if seq.table is not None:
            seq.table.release()
            seq.table = None
        self._running.pop(seq.req.id, None)
        self._preempted.pop(seq.req.id, None)
        seq.state = MIGRATED
        seq.t_done = time.monotonic()
        self.migrated_out += 1
        seq.result.finish({
            "id": seq.req.id, "tokens": list(seq.generated),
            "state": MIGRATED, "handoff": handoff,
            "preempts": seq.preempts,
            "migrations": seq.migrations + 1,
        })
        return True

    def migrate_all_out(self):
        """Drain the accelerator by moving every live sequence to a
        peer (worker drain / SIGTERM hand-off) — chip-return latency
        decouples from stream length. Sequences whose migration falls
        back stay local and finish through the normal decode/recompute
        path. Returns the number migrated."""
        if self.migrator is None:
            return 0
        moved = 0
        with self._lock:
            live = (list(self._running.values())
                    + list(self._preempted.values()))
            for seq in live:
                if self._try_migrate_out(seq):
                    moved += 1
        return moved

    def import_remote(self, record):
        """Place a migrated-in sequence; ``(local_id, SequenceResult)``.
        Hot records (pages present) resume decoding from the imported
        KV with no prefill; cold records re-enter through the normal
        recompute admission. Raises kv_cache.MigrationError subtypes —
        always before anything is placed, so a refusal leaves this
        scheduler untouched (all-or-nothing)."""
        req = Request(f"{record['id']}~m{next(self._stamp)}",
                      record["prompt"], record["max_new_tokens"])
        generated = [int(t) for t in record.get("generated", ())]
        with self._lock:
            table = None
            if int(record.get("num_tokens", 0)):
                table = self.pool.import_sequence(record)
            seq = _Seq(req, SequenceResult(req.max_new_tokens))
            seq.generated = generated
            seq.preempts = int(record.get("preempts", 0))
            seq.migrations = int(record.get("migrations", 1))
            now = time.monotonic()
            seq.t_admit = now
            if table is not None:
                seq.table = table
                seq.state = RUNNING
                seq.t_prefill_done = now
                seq.admit_stamp = next(self._stamp)
                self._running[req.id] = seq
            else:
                seq.state = PREEMPTED
                self._preempted[req.id] = seq
            self.migrated_in += 1
        _m.migrations_total("imported").inc()
        return req.id, seq.result

    # -- completion --------------------------------------------------------
    def _finish(self, seq, state=DONE, error=None):
        if seq.table is not None:
            seq.table.release()
            seq.table = None
        seq.state = state
        seq.t_done = time.monotonic()
        if state == DONE:
            self.completed += 1
            self._latency_window.append(seq.t_done - seq.t_submit)
            if seq.t_prefill_done is not None:
                _m.latency("decode").observe(
                    seq.t_done - seq.t_prefill_done)
        else:
            self.failed += 1
        summary = {
            "id": seq.req.id, "tokens": list(seq.generated),
            "state": state, "preempts": seq.preempts,
            "migrations": seq.migrations,
            "latency": {
                "queue": (seq.t_admit or seq.t_done) - seq.t_submit,
                "prefill": ((seq.t_prefill_done - seq.t_admit)
                            if seq.t_prefill_done else 0.0),
                "decode": ((seq.t_done - seq.t_prefill_done)
                           if seq.t_prefill_done else 0.0),
            },
        }
        if error:
            summary["error"] = error
        seq.result.finish(summary)

    # -- the step ----------------------------------------------------------
    def step(self):
        """One continuous-batching step. Returns the step's batch
        composition (tuple of sequence ids) — empty when idle."""
        with self._lock:
            self._admit()
            batch = list(self._running.values())
            if not batch:
                # Idle ticks are not steps: logging them would wash the
                # recent-composition window out with () entries.
                return ()
            contexts = [s.table.gather() for s in batch]
            next_tokens, next_kv = self.model.decode(contexts)
            for seq, tok, kv in zip(batch, next_tokens, next_kv):
                if seq.state == MIGRATED:
                    # An earlier sequence's exhaustion migrated this
                    # one away mid-step. Its exported KV (and token
                    # list) predate THIS step's token, so the target
                    # regenerates it deterministically as its first
                    # continuation step — recording it here too would
                    # double it.
                    continue
                seq.generated.append(int(tok))
                self.tokens_out += 1
                _m.tokens_total().inc()
                try:
                    seq.result.stream.put_nowait(int(tok))
                except queue.Full:
                    pass  # stream bound == max_new_tokens: can't happen
                done = (len(seq.generated) >= seq.req.max_new_tokens
                        or (self.model.eos_id is not None
                            and int(tok) == self.model.eos_id))
                if done:
                    if seq.req.id in self._running:
                        del self._running[seq.req.id]
                    else:
                        self._preempted.pop(seq.req.id, None)
                    self._finish(seq)
                    continue
                if seq.state == PREEMPTED:
                    # An earlier sequence's KV growth preempted this one
                    # mid-step. Its token for THIS step is already
                    # recorded (computed from the pre-preemption
                    # context); the resume prefill reconstructs the KV
                    # including it, so nothing is lost — just don't
                    # touch the released table.
                    continue
                # Grow the KV table by one token; exhaustion preempts
                # the LRU sequence and retries (recompute-on-resume).
                while True:
                    try:
                        seq.table.append(kv[None] if kv.ndim == 1
                                         else kv)
                        break
                    except PoolExhausted:
                        if not self._preempt_lru(seq.req.id):
                            del self._running[seq.req.id]
                            self._finish(
                                seq, state=FAILED,
                                error="KV pool exhausted with no "
                                      "preemption victim")
                            break
            self.steps += 1
            composition = tuple(sorted(s.req.id for s in batch))
            self.step_log.append(composition)
            return composition

    # -- stats -------------------------------------------------------------
    def stats(self):
        with self._lock:
            return {
                "queue_depth": self._admit_q.qsize(),
                "running": len(self._running),
                "preempted_waiting": len(self._preempted),
                "steps": self.steps,
                "completed": self.completed,
                "failed": self.failed,
                "tokens_out": self.tokens_out,
                "preemptions": self.preemptions,
                "admission_blocked": self.admission_blocked,
                "migrated_out": self.migrated_out,
                "migrated_in": self.migrated_in,
                "migrate_failed": self.migrate_failed,
                "pages_free": self.pool.free_pages,
                "pages_total": self.pool.num_pages,
                "draining": self.draining,
                "p99_latency": self._p99_locked(),
                "recent_steps": [list(c) for c in
                                 list(self.step_log)[-32:]],
            }

    def _p99_locked(self):
        """p99 of the recent end-to-end latency window (0.0 until the
        first completion). Holds self._lock via stats()."""
        if not self._latency_window:
            return 0.0
        ordered = sorted(self._latency_window)
        return ordered[min(len(ordered) - 1,
                           int(0.99 * len(ordered)))]
