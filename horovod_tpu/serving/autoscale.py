"""Elastic autoscaling of serving cohorts from queue-depth/latency
signals.

The autoscaler closes the loop between the KV-plane stats workers push
(queue depth, running count — the backpressure signals) and the
elastic machinery that owns process lifecycles:

- **scale-up**: total cohort pressure (queued + running) at or above
  ``HVDTPU_SERVING_SCALE_UP_DEPTH`` for ``window`` consecutive
  observations fires the ``scale_up`` hook (once per cooldown);
- **scale-down**: a cohort idle for ``idle_s`` fires ``drain`` first —
  in-flight and queued sequences complete, workers reject new
  admissions — and only a cohort that *reports drained-and-idle* (or
  exceeds ``HVDTPU_SERVING_DRAIN_TIMEOUT``) reaches the ``scale_down``
  hook. Scale-down never drops accepted requests.

The hooks are deliberately thin callables so the same policy core
drives any actuator. The stock actuator is the existing elastic
machinery itself: :func:`write_target` maintains a desired-host-count
file and :func:`discovery_script_lines` renders the standard elastic
discovery script that reads it — an ``ElasticDriver`` pointed at that
script reconciles the serving cohort to the autoscaler's target through
the exact spawn/stop/blacklist paths training uses
(docs/serving.md "Autoscaling").
"""

import os
import time

from ..utils import envparse
from ..utils.logging_util import get_logger


def scale_knobs():
    return {
        "scale_up_depth": envparse.get_int(
            envparse.SERVING_SCALE_UP_DEPTH, 32),
        "drain_timeout": envparse.get_float(
            envparse.SERVING_DRAIN_TIMEOUT, 30.0),
        "slo_p99": envparse.get_float(envparse.SERVING_SLO_P99, 0.0),
    }


class Autoscaler:
    """Policy core: observe cohort stats, fire scale hooks."""

    def __init__(self, scale_up, scale_down=None, drain=None, *,
                 scale_up_depth=None, drain_timeout=None, slo_p99=None,
                 window=3, cooldown_s=10.0, idle_s=30.0):
        knobs = scale_knobs()
        self.scale_up = scale_up
        self.scale_down = scale_down
        self.drain = drain
        self.scale_up_depth = (scale_up_depth
                               if scale_up_depth is not None
                               else knobs["scale_up_depth"])
        self.drain_timeout = (drain_timeout
                              if drain_timeout is not None
                              else knobs["drain_timeout"])
        self.slo_p99 = (slo_p99 if slo_p99 is not None
                        else knobs["slo_p99"])
        self.window = int(window)
        self.cooldown_s = float(cooldown_s)
        self.idle_s = float(idle_s)
        self._breaches = 0
        self._last_scale_up = float("-inf")  # no scale-up yet
        self._idle_since = {}     # cohort -> monotonic idle start
        self._draining = {}       # cohort -> drain start
        self.events = []          # (kind, cohort-or-depth) audit log
        self._log = get_logger()

    def _pressure(self, cohort_stats):
        return int(cohort_stats.get("queue_depth", 0)) \
            + int(cohort_stats.get("running", 0))

    def observe(self, cohorts, now=None):
        """One control tick over the router's cohort view
        (``Router.stats()['cohorts']``). Returns the events fired this
        tick (also appended to ``self.events``)."""
        now = time.monotonic() if now is None else now
        fired = []
        total = sum(self._pressure(s) for s in cohorts.values())
        worst_p99 = max(
            (float(s.get("p99_latency") or 0.0)
             for s in cohorts.values()), default=0.0)
        # -- scale-up ------------------------------------------------------
        # Two breach conditions feed one window-smoothed counter: queue
        # pressure (the fast signal) and a p99 SLO violation (the
        # slow-but-not-queued overload the depth trigger misses — every
        # request admitted, each one crawling).
        slo_breach = self.slo_p99 > 0 and worst_p99 >= self.slo_p99
        if total >= self.scale_up_depth or slo_breach:
            self._breaches += 1
        else:
            self._breaches = 0
        if (self._breaches >= self.window
                and now - self._last_scale_up >= self.cooldown_s):
            self._breaches = 0
            self._last_scale_up = now
            if slo_breach and total < self.scale_up_depth:
                self._log.warning(
                    "serving autoscale: p99 %.3fs >= SLO %.3fs for %d "
                    "ticks (queue shallow at %d); scaling up",
                    worst_p99, self.slo_p99, self.window, total)
            else:
                self._log.warning(
                    "serving autoscale: pressure %d >= %d for %d "
                    "ticks; scaling up", total, self.scale_up_depth,
                    self.window)
            self.scale_up()
            fired.append(("scale_up", total))
        # -- scale-down (drain first) --------------------------------------
        for cohort, s in cohorts.items():
            if cohort in self._draining:
                started = self._draining[cohort]
                drained = (self._pressure(s) == 0
                           and s.get("queue_depth", 0) == 0)
                if drained or now - started > self.drain_timeout:
                    del self._draining[cohort]
                    if self.scale_down is not None:
                        if not drained:
                            self._log.warning(
                                "serving autoscale: cohort %s drain "
                                "timed out after %.0fs; scaling down "
                                "anyway", cohort, self.drain_timeout)
                        self.scale_down(cohort)
                        fired.append(("scale_down", cohort))
                continue
            if self._pressure(s) == 0:
                since = self._idle_since.setdefault(cohort, now)
                if (now - since >= self.idle_s
                        and self.drain is not None
                        and self.scale_down is not None
                        and len(cohorts) > 1):
                    # Never drain the last cohort: scale-to-zero is an
                    # operator decision, not an idle-timer one.
                    del self._idle_since[cohort]
                    self._draining[cohort] = now
                    self._log.warning(
                        "serving autoscale: cohort %s idle %.0fs; "
                        "draining before scale-down", cohort,
                        now - since)
                    self.drain(cohort)
                    fired.append(("drain", cohort))
            else:
                self._idle_since.pop(cohort, None)
        self.events.extend(fired)
        return fired


# --------------------------------------------------------------------------
# The stock actuator: desired-host-count file + elastic discovery script
# --------------------------------------------------------------------------

def write_target(path, hosts_per_line):
    """Atomically + durably write the desired host list (one
    ``host:slots`` per line) the discovery script serves to the
    elastic driver. fsync before the rename: a rename alone is atomic
    against concurrent readers but not against power loss — a crash
    could surface an *empty* target file, which the discovery script
    would faithfully report as "cohort of zero" and the driver would
    obediently tear everything down."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write("\n".join(hosts_per_line) + "\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    dir_fd = os.open(os.path.dirname(os.path.abspath(path)) or ".",
                     os.O_RDONLY)
    try:
        os.fsync(dir_fd)
    finally:
        os.close(dir_fd)


def discovery_script_lines(target_file):
    """The elastic discovery script body that reconciles the serving
    cohort to the autoscaler's target file — scale-up is
    ``write_target`` + the driver's own discovery/spawn cycle, the
    same machinery that replaces failed training workers."""
    return ["#!/bin/sh", f'cat "{target_file}" 2>/dev/null || true']
