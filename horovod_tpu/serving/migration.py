"""KV-cache live migration: verified page transfer between serving
hosts (docs/serving.md "Live migration").

Recompute-on-preempt (scheduler.py) and reroute-on-death (router.py)
both re-prefill the victim's whole prompt+generation, so recovery cost
grows with context length and drain time is bounded by the longest
in-flight stream. Migration moves the state instead of rebuilding it:
the source exports a sequence's KV pages (kv_cache.export_sequence,
one sha256 digest per page), ships them to a capacity-bearing peer
over ``POST /v1/serving/migrate_in`` — chunked to
``HVDTPU_SERVING_MIGRATE_MAX_BYTES``, each chunk retried on the
runner's exp-backoff/deadline machinery, the whole transfer fenced by
elastic version — and the target places them all-or-nothing against
its own watermark before resuming decode from the migrated position.

**Graceful degradation is the contract**: every failure leg — digest
mismatch, timeout, no peer headroom, version fence — is counted in
``hvd_serving_migrations_total{outcome}`` and falls back loudly to the
status-quo recompute/reroute path, so a broken migration plane can
slow recovery but never lose an accepted request. Chaos points
``migrate_out``/``migrate_in`` (fail/delay/corrupt) make each leg
injectable.

Wire protocol (one migration = 1..N chunk POSTs, same ``mid``)::

    {"mid": m, "chunk": i, "total": N, "pages": [{payload, digest}..]}
    ... last chunk additionally: {"meta": {...}, "commit": true}

Non-final chunks ack ``{"staged": i}``; the commit chunk answers
``{"state": "imported", "id": <local id>, ...}`` — the handoff the
router follows — or a refusal: 409 ``no_headroom``/``version_fenced``/
``draining`` (structural: try another peer or fall back), 422
``digest_mismatch``/``geometry_mismatch`` (the payload is bad), 413
``too_large`` (a single chunk over the byte bound), 429/5xx retryable.
"""

import itertools
import json
import os
import threading
import time
import urllib.error

from .. import chaos
# The migration handshake's state machine lives in the protocol spec
# (spec-is-implementation — analysis/protocol/migration_spec.py is the
# module the hvd-model checker explores, and this module executes the
# exact same chunking/staging/refusal functions;
# tests/test_protocol_model.py asserts the delegation). This file owns
# everything impure: sockets, retries, locks, the real clock, metrics.
from ..analysis.protocol import migration_spec
from ..exceptions import ChaosInjectedError
from ..utils import envparse
from ..utils.logging_util import get_logger
from . import metrics as _m
from .kv_cache import MigrationError

#: token-gated route on the runner HTTP server (worker targets only).
MIGRATE_PATH = "/v1/serving/migrate_in"
#: member slots probed per cohort during peer discovery.
MAX_MEMBERS = 32

DEFAULT_RETRIES = 3
DEFAULT_DEADLINE_S = 5.0
DEFAULT_MAX_BYTES = 4 * 1024 * 1024

_midno = itertools.count(1)


class VersionFenced(MigrationError):
    """The record was exported under a different elastic version than
    the target is serving — membership changed mid-flight; the source
    falls back to recompute rather than resume against a stale view."""


class MigrationRefused(MigrationError):
    """The target refused the transfer with a deterministic 4xx; the
    ``outcome`` attribute names the leg for the metrics/fallback."""

    def __init__(self, outcome, message):
        super().__init__(message)
        self.outcome = str(outcome)


class StagingFull(MigrationError):
    """Inbound staging is at its bound — the target answers 429 and
    the source's chunk retry (or fallback) takes it from there."""


def knobs():
    """The migration knob family resolved through envparse
    (docs/knobs.md)."""
    return {
        "retries": envparse.get_int(
            envparse.SERVING_MIGRATE_RETRIES, DEFAULT_RETRIES),
        "deadline": envparse.get_float(
            envparse.SERVING_MIGRATE_DEADLINE, DEFAULT_DEADLINE_S),
        "max_bytes": envparse.get_int(
            envparse.SERVING_MIGRATE_MAX_BYTES, DEFAULT_MAX_BYTES),
    }


# -- wire helpers ----------------------------------------------------------
def _parse_url(url):
    """(addr, port) of an ``http://host:port`` worker base URL."""
    rest = url.split("//", 1)[-1].rstrip("/")
    host, _, port = rest.partition(":")
    return host, int(port or 80)


#: Greedy page packing — the spec function, re-exported for the wire
#: layer and tests.
chunk_pages = migration_spec.chunk_pages


def _corrupt_payload(pages):
    """Chaos ``corrupt`` effect: flip one character of the first
    non-empty page payload (the digest was computed before the flip,
    so verification must refuse the import)."""
    for pg in pages:
        payload = pg.get("payload", "")
        if payload:
            flipped = ("B" if payload[0] != "B" else "C") + payload[1:]
            pg["payload"] = flipped
            return True
    return False


def migrate_out(url, record, token="", retries=None, deadline=None,
                max_bytes=None):
    """Ship one exported sequence record to the worker at ``url``;
    returns the target's commit body (the handoff the router follows).

    Each chunk POST rides the runner retry engine (exp backoff +
    jitter, per-chunk ``deadline``); deterministic 4xx refusals raise
    :class:`MigrationRefused` immediately, retry exhaustion raises
    ``KVRetryExhaustedError`` (a TimeoutError). Callers map both to
    the recompute fallback."""
    from ..runner import http_client
    cfg = knobs()
    retries = cfg["retries"] if retries is None else int(retries)
    deadline = cfg["deadline"] if deadline is None else float(deadline)
    max_bytes = (cfg["max_bytes"] if max_bytes is None
                 else int(max_bytes))
    addr, port = _parse_url(url)
    meta = {k: v for k, v in record.items() if k != "pages"}
    chunks = chunk_pages(record.get("pages", []), max_bytes)
    mid = f"{record.get('id', '?')}@{os.getpid()}.{next(_midno)}"
    out = None
    for ci, chunk in enumerate(chunks):
        body = {"mid": mid, "chunk": ci, "total": len(chunks),
                "pages": chunk}
        if ci == len(chunks) - 1:
            body["meta"] = meta
            body["commit"] = True

        def attempt(a, p, _body=body, _ci=ci):
            try:
                chaos.inject("migrate_out", key=str(record.get("id")),
                             name=mid, kind=f"chunk{_ci}")
            except chaos.ChaosSignal as sig:
                if sig.action == "corrupt":
                    _corrupt_payload(_body["pages"])
                else:
                    raise ChaosInjectedError(str(sig))
            data = json.dumps(_body).encode()
            if len(data) > max_bytes * 2:
                # One page alone blew the byte bound: deterministic,
                # shipping it anyway would just bounce off the target.
                raise MigrationRefused(
                    "too_large",
                    f"migrate chunk {_ci} is {len(data)} bytes against "
                    f"a {max_bytes} bound")
            try:
                resp = http_client._request(
                    "POST", f"http://{a}:{p}{MIGRATE_PATH}", data=data,
                    token=token, timeout=max(deadline, 1.0))
            except urllib.error.HTTPError as e:
                if 400 <= e.code < 500 and e.code not in (408, 425,
                                                          429):
                    raw = e.read()
                    try:
                        parsed = json.loads(raw) if raw else {}
                    except ValueError:
                        parsed = {}
                    outcome = parsed.get("error") or f"http_{e.code}"
                    raise MigrationRefused(
                        outcome,
                        f"peer {a}:{p} refused migrate chunk {_ci}: "
                        f"HTTP {e.code} {outcome}") from e
                raise
            with resp:
                return json.loads(resp.read() or b"{}")

        out = http_client._call(
            "migrate", "serving", f"{record.get('id', '?')}/{ci}",
            attempt, addr, port, retries=retries, deadline=deadline)
    return out


# -- target side -----------------------------------------------------------
class InboundStaging:
    """Bounded reassembly buffers for in-flight inbound migrations —
    at most ``max_staged`` concurrent transfers, each bounded by the
    sender's chunk size (HVD210: this is a fixed-size wait station,
    not a queue). Stale entries (an aborted sender) expire after
    ``ttl_s``."""

    def __init__(self, max_staged=8, ttl_s=30.0):
        self.max_staged = int(max_staged)
        self.ttl_s = float(ttl_s)
        self._lock = threading.Lock()
        self._entries = {}   # mid -> {chunks, total, meta, t}

    def offer(self, payload):
        """Stage one chunk; the assembled record when the migration is
        complete, else None. Raises KeyError/ValueError on a malformed
        chunk and :class:`StagingFull` at the bound. The transition
        itself is migration_spec.stage_chunk — this wrapper adds the
        lock and the real clock."""
        with self._lock:
            try:
                return migration_spec.stage_chunk(
                    self._entries, payload,
                    max_staged=self.max_staged, ttl_s=self.ttl_s,
                    now=time.monotonic())
            except migration_spec.StagingLimit as exc:
                raise StagingFull(str(exc)) from exc

    def depth(self):
        with self._lock:
            return len(self._entries)


# -- source-side policy ----------------------------------------------------
class Migrator:
    """Source-side migrate-out policy: peer discovery over the KV
    member plane plus the graceful-fallback transfer loop. One per
    worker; the scheduler calls :meth:`migrate_seq` with an exported
    record and falls back to recompute whenever it returns None."""

    #: seconds a discovered peer list stays cached.
    PEER_TTL_S = 1.0

    def __init__(self, cohort, wid, kv=None, token="", peers=None):
        self.cohort = str(cohort)
        self.wid = int(wid)
        self.kv = kv                  # (addr, port, token) or None
        self.token = token            # worker-auth token for migrate_in
        self._static_peers = list(peers) if peers is not None else None
        self._peer_cache = (0.0, [])
        self._log = get_logger()

    def peers(self):
        """[(wid, url)] of live cohort peers, self excluded — the KV
        member plane when configured, else the static test list."""
        if self._static_peers is not None:
            return list(self._static_peers)
        if self.kv is None:
            return []
        t, cached = self._peer_cache
        now = time.monotonic()
        if now - t < self.PEER_TTL_S:
            return list(cached)
        from ..runner import http_client
        addr, port, token = self.kv
        found = []
        for i in range(MAX_MEMBERS):
            if i == self.wid:
                continue
            try:
                raw = http_client.get_kv(
                    addr, port, "serving",
                    f"member.{self.cohort}.{i}", token=token,
                    retries=0, deadline=2.0)
            except Exception as e:  # noqa: BLE001 — KV blackout: no peers
                self._log.warning(
                    "serving migrate: peer discovery failed (%s); "
                    "falling back to recompute", e)
                return []
            if raw is None:
                continue
            url = raw.decode()
            found.append((i, url if url.startswith("http")
                          else f"http://{url}"))
        self._peer_cache = (now, found)
        return list(found)

    def migrate_seq(self, record):
        """Try every peer in turn; the handoff dict on success, None
        on fallback (every leg logged + counted — loud, never
        silent)."""
        t0 = time.monotonic()
        peers = self.peers()
        if not peers:
            _m.migrations_total("no_peer").inc()
            self._log.warning(
                "serving migrate: no peer for %s; falling back to "
                "recompute", record.get("id"))
            return None
        for wid, url in peers:
            try:
                body = migrate_out(url, record, token=self.token)
            except MigrationRefused as e:
                outcome, try_next = migration_spec.classify_refusal(
                    e.outcome)
                _m.migrations_total(outcome).inc()
                self._log.warning(
                    "serving migrate: peer %s refused %s (%s)",
                    url, record.get("id"), e)
                if try_next:
                    continue          # structural: another peer may fit
                return None           # payload/version: fallback now
            except TimeoutError as e:
                _m.migrations_total("timeout").inc()
                self._log.warning(
                    "serving migrate: transfer of %s to %s timed out "
                    "(%s); trying next peer", record.get("id"), url, e)
                continue
            except Exception as e:  # noqa: BLE001 — any other failure:
                #                     loud fallback, never worse than
                #                     the recompute status quo
                _m.migrations_total("error").inc()
                self._log.warning(
                    "serving migrate: transfer of %s to %s failed "
                    "(%s); trying next peer", record.get("id"), url, e)
                continue
            _m.migrations_total("complete").inc()
            _m.migrated_pages_total().inc(len(record.get("pages", ())))
            _m.migration_seconds().observe(time.monotonic() - t0)
            return {"url": url, "wid": wid,
                    "id": body.get("id"),
                    "cohort": body.get("cohort", self.cohort)}
        return None
