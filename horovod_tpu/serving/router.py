"""Request router: assigns requests to host cohorts over the runner
HTTP/KV plane, with backpressure and dead-worker re-routing.

The router is deliberately *stateless about requests*: it holds no
queue of its own (every wait station in the serving plane is a bounded
scheduler queue on some host — rule HVD210), forwards each request
synchronously on its handler thread, and answers **429 + Retry-After**
the moment every candidate worker reports backpressure. A cohort's
queue depth crossing its limit therefore propagates to clients
immediately instead of accumulating anywhere.

Routing policy: cohorts ordered by last-known total queue depth (from
the KV-plane stats snapshots workers push; direct worker stats when no
KV store is configured), members round-robin within a cohort. A
transport failure mid-request — the worker died with streams in
flight — marks the member dead for a grace period and **re-routes the
request to the next candidate**; generation is deterministic given the
prompt, so the surviving worker completes the identical stream and an
accepted request is never lost (chaos row (a) pins this end to end).

A KV blackout degrades reads to the last-known / direct-local view
(``stats()['source']`` flips ``kv`` → ``local``) and recovery re-syncs
the cohort roll-up — the router never stops routing because the
control plane blinked (chaos row (b)).
"""

import http.client
import itertools
import json
import threading
import time
import urllib.error
import urllib.request
import zlib

from ..utils.logging_util import get_logger
from . import metrics as _m

#: how long a transport-failed member stays deprioritized.
DEAD_GRACE_S = 5.0
#: member slots probed per cohort during KV discovery.
MAX_MEMBERS = 32
#: Retry-After base seconds for router 429s (jittered per request).
RETRY_AFTER_S = 1.0
#: handoff hops the router follows for one migrated stream.
HANDOFF_HOPS = 4


def retry_after_jitter(request_id, base=RETRY_AFTER_S):
    """Deterministic per-request ``Retry-After``: ``base`` scaled into
    [0.5, 1.5) by a hash of the request id. Synchronized clients that
    all hit a full queue de-herd — each backs off a *different* but
    *reproducible* amount (same id, same value), so chaos/backpressure
    tests stay deterministic while the thundering herd disperses."""
    h = zlib.crc32(str(request_id).encode())
    return round(float(base) * (0.5 + (h % 4096) / 4096.0), 3)

# RemoteDisconnected is a ConnectionResetError, but BadStatusLine (a
# half-written response from a dying worker) is only an HTTPException.
_TRANSPORT_ERRORS = (urllib.error.URLError, ConnectionError, OSError,
                     http.client.HTTPException)


class WorkerClient:
    """HTTP client for one serving worker endpoint."""

    def __init__(self, base_url, token="", timeout_s=120.0):
        self.base_url = base_url.rstrip("/")
        self.token = token
        self.timeout_s = float(timeout_s)

    def __repr__(self):
        return f"WorkerClient({self.base_url})"

    def _req(self, path, data=None, timeout=None):
        from ..runner.http_server import AUTH_HEADER
        req = urllib.request.Request(
            self.base_url + path,
            data=(json.dumps(data).encode() if data is not None
                  else None),
            method="POST" if data is not None else "GET")
        if self.token:
            req.add_header(AUTH_HEADER, self.token)
        try:
            with urllib.request.urlopen(
                    req, timeout=timeout or self.timeout_s) as resp:
                return resp.status, json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as e:
            body = e.read()
            try:
                parsed = json.loads(body) if body else {}
            except ValueError:
                parsed = {"error": body.decode(errors="replace")}
            return e.code, parsed

    def generate(self, payload):
        return self._req("/v1/generate", data=payload)

    def stats(self):
        return self._req("/v1/serving/stats", timeout=5.0)[1]

    def drain(self):
        return self._req("/v1/serving/drain", data={}, timeout=5.0)


class InProcClient:
    """Direct in-process client (bench, unit tests, single-host)."""

    def __init__(self, worker):
        self.worker = worker
        self.wid = worker.wid
        self.base_url = f"inproc:{worker.cohort}.{worker.wid}"

    def generate(self, payload):
        return self.worker.handle_generate(payload)

    def stats(self):
        return self.worker.stats()

    def drain(self):
        return self.worker.handle_drain()


class Router:
    """Routes ``/v1/generate`` to the least-loaded cohort member."""

    def __init__(self, members=None, kv=None, queue_limit=None):
        #: cohort -> list of clients (insertion order = member order).
        self.members = {c: list(ms) for c, ms in (members or {}).items()}
        #: (addr, port, token) of the launcher KV store, or None.
        self.kv = kv
        self.queue_limit = queue_limit
        self._lock = threading.Lock()
        self._rr = itertools.count()
        self._dead = {}          # base_url -> dead-until monotonic
        self._stats_cache = {}   # (cohort, idx) -> last stats dict
        self._source = "local"
        self.accepted = 0
        self.completed = 0
        self.rerouted = 0
        self.rejected = 0
        self.handoffs = 0        # migrated streams followed to a peer
        self._log = get_logger()

    # -- membership --------------------------------------------------------
    @staticmethod
    def _wid_of(client, fallback):
        """The worker id a client's stats live under on the KV plane
        (`stats.<cohort>.<wid>`). Discovery stamps it; wids need NOT
        be contiguous (a replacement host takes the next free slot)."""
        return getattr(client, "wid", fallback)

    def add_member(self, cohort, client, wid=None):
        with self._lock:
            members = self.members.setdefault(cohort, [])
            if wid is not None:
                client.wid = int(wid)
            elif not hasattr(client, "wid"):
                client.wid = len(members)
            members.append(client)

    def refresh_from_kv(self, cohorts, timeout_s=5.0):
        """Discover cohort members from ``serving/member.<cohort>.<i>``
        keys (workers register themselves there)."""
        from ..runner import http_client
        if self.kv is None:
            raise ValueError("router has no KV store configured")
        addr, port, token = self.kv
        found = {}
        for cohort in cohorts:
            urls = []
            for i in range(MAX_MEMBERS):
                raw = http_client.get_kv(
                    addr, port, "serving", f"member.{cohort}.{i}",
                    token=token, retries=0, deadline=timeout_s)
                if raw is None:
                    continue
                urls.append((i, raw.decode()))
            found[cohort] = urls
        with self._lock:
            for cohort, urls in found.items():
                have = {c.base_url for c in self.members.get(cohort, [])}
                for wid, url in urls:
                    base = url if url.startswith("http") \
                        else f"http://{url}"
                    if base not in have:
                        client = WorkerClient(base, token=token)
                        client.wid = wid
                        self.members.setdefault(cohort, []).append(
                            client)
        return {c: len(self.members.get(c, ())) for c in cohorts}

    # -- routing -----------------------------------------------------------
    def _cohort_depth(self, cohort):
        depth = 0
        for (c, _), s in self._stats_cache.items():
            if c == cohort:
                depth += int(s.get("queue_depth", 0)) \
                    + int(s.get("running", 0))
        return depth

    def _candidates(self, cohort=None):
        with self._lock:
            cohorts = ([cohort] if cohort is not None
                       else sorted(self.members,
                                   key=self._cohort_depth))
            now = time.monotonic()
            rr = next(self._rr)
            alive, dead = [], []
            for c in cohorts:
                ms = self.members.get(c, [])
                for i in range(len(ms)):
                    client = ms[(i + rr) % len(ms)]
                    if self._dead.get(client.base_url, 0) > now:
                        dead.append(client)
                    else:
                        alive.append(client)
            # Dead members are last-resort candidates, not excluded:
            # if everyone else backpressures we still try them (they
            # may have recovered inside the grace window).
            return alive + dead

    def _mark_dead(self, client):
        with self._lock:
            self._dead[client.base_url] = time.monotonic() + DEAD_GRACE_S

    def generate(self, payload):
        """Forward one request; ``(status, body)``. Transport failures
        re-route; a ``migrated`` response is followed to the new host
        (the stream continues there with zero re-prefill); uniform
        backpressure returns 429 + a per-request-jittered
        Retry-After."""
        request_id = None
        if isinstance(payload, dict):
            request_id = payload.get("id")
            # Ask workers for the raw handoff record instead of having
            # them proxy a migrated stream — the router follows it and
            # keeps the fallback ladder (replay on the next candidate)
            # in one place.
            payload["handoff"] = "return"
        candidates = self._candidates(payload.pop("cohort", None)
                                      if isinstance(payload, dict)
                                      else None)
        if not candidates:
            return 503, {"error": "no serving workers registered"}
        backpressured = failed = draining = False
        retry_hint = 0.0
        for client in candidates:
            try:
                status, body = client.generate(payload)
            except _TRANSPORT_ERRORS as e:
                # The worker vanished — possibly with this request
                # already decoding. Deterministic generation makes the
                # retry exact; re-route to the next candidate.
                self._log.warning(
                    "serving router: %s failed mid-request (%s); "
                    "re-routing", client.base_url, e)
                self._mark_dead(client)
                failed = True
                continue
            if status == 200 and body.get("state") == "migrated":
                status, body = self._follow_handoff(body)
                if status != 200:
                    # Handoff lost (the peer died before the stream
                    # was claimed): fall back to replaying the request
                    # on the next candidate — recompute, the status
                    # quo.
                    failed = True
                    continue
            if status == 200:
                with self._lock:
                    self.accepted += 1
                    self.completed += 1
                    if failed:
                        self.rerouted += 1
                if failed:
                    _m.rerouted_total().inc()
                return status, body
            if status in (429, 503):
                if body.get("error") == "draining":
                    draining = True
                else:
                    backpressured = True
                    # Honor the most conservative worker-supplied
                    # (already jittered) Retry-After hint.
                    try:
                        retry_hint = max(
                            retry_hint,
                            float(body.get("retry_after") or 0.0))
                    except (TypeError, ValueError):
                        pass
                continue
            if 400 <= status < 500:
                # Deterministic client errors (400 malformed, 413 too
                # large for the pool/budget) — retrying the identical
                # doomed request on other members only multiplies the
                # failure; hand it straight back.
                return status, body
            failed = True            # 5xx: try the next member
        if backpressured:
            with self._lock:
                self.rejected += 1
            _m.rejected_total("overload").inc()
            return 429, {"error": "all serving cohorts at queue limit",
                         "retry_after": retry_hint
                         or retry_after_jitter(request_id)}
        if draining:
            with self._lock:
                self.rejected += 1
            _m.rejected_total("draining").inc()
            return 503, {"error": "all serving cohorts draining"}
        return 503, {"error": "no serving worker reachable"}

    # -- migration handoff -------------------------------------------------
    def _client_for(self, url):
        """A client for a handoff target: the known member with that
        base URL when we have one (keeps its dead-marking state), else
        a fresh WorkerClient on the KV token."""
        base = url.rstrip("/")
        with self._lock:
            for clients in self.members.values():
                for client in clients:
                    if client.base_url == base:
                        return client
        token = self.kv[2] if self.kv is not None else ""
        return WorkerClient(base, token=token)

    def _follow_handoff(self, body, hops=HANDOFF_HOPS):
        """Chase a migrated stream to the host now decoding it; the
        final ``(status, body)``. The continuation is the *same*
        sequence — imported KV pages, zero re-prefill — so the client
        stream completes token-exact without replaying the prompt.
        Any failure returns non-200 and the caller falls back to the
        replay (recompute) ladder."""
        for _ in range(hops):
            handoff = body.get("handoff") or {}
            url, rid = handoff.get("url"), handoff.get("id")
            if not url or not rid:
                return 502, {"error": "malformed handoff record"}
            client = self._client_for(url)
            try:
                status, body = client.generate(
                    {"attach": rid, "handoff": "return"})
            except _TRANSPORT_ERRORS as e:
                self._log.warning(
                    "serving router: handoff target %s unreachable "
                    "(%s); falling back to re-route", url, e)
                self._mark_dead(client)
                return 502, {"error": "handoff target unreachable"}
            if status == 200 and body.get("state") == "migrated":
                continue             # moved again: follow the chain
            if status == 200:
                with self._lock:
                    self.handoffs += 1
            return status, body
        return 502, {"error": "handoff chain unresolved"}

    # HTTP-surface aliases (the runner server dispatches on these).
    def handle_generate(self, payload):
        return self.generate(payload)

    def handle_drain(self, payload=None):
        cohort = (payload or {}).get("cohort")
        if not cohort:
            return 400, {"error": "drain needs a cohort"}
        return 200, self.drain_cohort(cohort)

    # -- stats / cohort view -----------------------------------------------
    def _kv_stats(self):
        from ..runner import http_client
        addr, port, token = self.kv
        fresh = {}
        for cohort, clients in list(self.members.items()):
            wids = sorted({self._wid_of(c, i)
                           for i, c in enumerate(clients)}) or [0]
            for wid in wids:
                raw = http_client.get_kv(
                    addr, port, "serving", f"stats.{cohort}.{wid}",
                    token=token, retries=0, deadline=2.0)
                if raw is not None:
                    fresh[(cohort, wid)] = json.loads(raw)
        return fresh

    def refresh_stats(self):
        """Refresh the cohort view: KV-plane snapshots when available,
        direct member scrapes otherwise; on KV trouble, keep serving
        from the last-known view (``source`` = ``local``)."""
        if self.kv is not None:
            try:
                fresh = self._kv_stats()
            except Exception as e:  # noqa: BLE001 — KV blackout: degrade
                self._log.warning(
                    "serving router: KV stats unavailable (%s); "
                    "serving from local view", e)
                with self._lock:
                    self._source = "local"
                return self._source
            with self._lock:
                self._stats_cache.update(fresh)
                self._source = "kv"
            return self._source
        fresh = {}
        for cohort, clients in list(self.members.items()):
            for i, client in enumerate(clients):
                try:
                    fresh[(cohort, self._wid_of(client, i))] = \
                        client.stats()
                except _TRANSPORT_ERRORS as e:
                    # Stale beats absent, but never silently (HVD213):
                    # an operator watching the log can tell a scrape
                    # gap from a healthy idle worker.
                    self._log.debug(
                        "serving router: stats scrape of %s failed "
                        "(%s); serving last-known view",
                        client.base_url, e)
                    continue
        with self._lock:
            self._stats_cache.update(fresh)
            self._source = "local"
        return self._source

    def stats(self):
        self.refresh_stats()
        with self._lock:
            cohorts = {}
            for (cohort, i), s in self._stats_cache.items():
                c = cohorts.setdefault(
                    cohort, {"members": {}, "queue_depth": 0,
                             "running": 0, "completed": 0,
                             "tokens_out": 0})
                c["members"][str(i)] = s
                c["queue_depth"] += int(s.get("queue_depth", 0))
                c["running"] += int(s.get("running", 0))
                c["completed"] += int(s.get("completed", 0))
                c["tokens_out"] += int(s.get("tokens_out", 0))
            return {
                "role": "router", "source": self._source,
                "cohorts": cohorts,
                "accepted": self.accepted, "completed": self.completed,
                "rerouted": self.rerouted, "rejected": self.rejected,
                "handoffs": self.handoffs,
            }

    # -- drain -------------------------------------------------------------
    def drain_cohort(self, cohort):
        """Set the KV drain flag (workers poll it) and tell reachable
        members directly; returns per-member acks."""
        acks = {}
        if self.kv is not None:
            from ..runner import http_client
            addr, port, token = self.kv
            try:
                http_client.put_kv(addr, port, "serving",
                                   f"drain.{cohort}", "1", token=token,
                                   retries=0, deadline=2.0)
                acks["kv_flag"] = True
            except Exception:  # noqa: BLE001 — direct drains still go out
                acks["kv_flag"] = False
        for i, client in enumerate(self.members.get(cohort, [])):
            try:
                status, _ = client.drain()
                acks[str(i)] = status == 200
            except _TRANSPORT_ERRORS as e:
                self._log.warning(
                    "serving router: direct drain of %s failed (%s); "
                    "the KV drain flag still reaches it",
                    client.base_url, e)
                acks[str(i)] = False
        return {"cohort": cohort, "acks": acks}

    # -- HTTP hosting ------------------------------------------------------
    def serve_http(self, addr="0.0.0.0", token=""):
        from ..runner.http_server import KVStoreServer
        self._server = KVStoreServer(job_token=token, addr=addr)
        self._server.serving_router = self
        return self._server.start()

    def stop_http(self):
        server = getattr(self, "_server", None)
        if server is not None:
            server.stop()
            self._server = None
