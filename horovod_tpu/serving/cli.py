"""``hvd-serve``: console client for the serving plane.

    hvd-serve route --kv HOST:PORT --token T --cohorts c0,c1  # start router
    hvd-serve stats --url http://router:port --token T        # cohort view
    hvd-serve stats --url ... --watch --interval 2            # live
    hvd-serve drain c0 --url http://router:port --token T     # drain cohort

``route`` starts a :class:`~.router.Router` HTTP surface: it discovers
cohort members from the launcher KV store (``serving/member.*`` keys
workers register), serves ``POST /v1/generate`` + ``GET
/v1/serving/stats``, and keeps membership + stats refreshed.
``stats`` polls a router's (or a single worker's) stats route.
``drain`` stops a cohort's admission — in-flight sequences complete,
new requests are rejected — through the router (which also sets the
KV drain flag workers poll). Exit codes: 0 ok, 2 usage/fetch error.
"""

import argparse
import json
import sys
import time
import urllib.error
import urllib.request


def _hostport(s):
    host, _, port = s.rpartition(":")
    if not host or not port.isdigit():
        raise argparse.ArgumentTypeError(
            f"expected HOST:PORT, got {s!r}")
    return host, int(port)


def _get_json(url, token, path):
    from ..runner.http_server import AUTH_HEADER
    req = urllib.request.Request(url.rstrip("/") + path)
    if token:
        req.add_header(AUTH_HEADER, token)
    with urllib.request.urlopen(req, timeout=10) as resp:
        return json.loads(resp.read())


def _post_json(url, token, path, payload):
    from ..runner.http_server import AUTH_HEADER
    req = urllib.request.Request(
        url.rstrip("/") + path, data=json.dumps(payload).encode(),
        method="POST")
    if token:
        req.add_header(AUTH_HEADER, token)
    with urllib.request.urlopen(req, timeout=30) as resp:
        return resp.status, json.loads(resp.read() or b"{}")


def _cmd_route(args):
    from .router import Router
    addr, port = args.kv
    router = Router(kv=(addr, port, args.token))
    cohorts = [c for c in args.cohorts.split(",") if c]
    try:
        found = router.refresh_from_kv(cohorts)
    except Exception as e:  # noqa: BLE001 — startup discovery is fatal
        print(f"hvd-serve: cannot reach KV store {addr}:{port}: {e}",
              file=sys.stderr)
        return 2
    http_port = router.serve_http(addr=args.bind, token=args.token)
    print(f"serving router on :{http_port} "
          f"(cohorts: {', '.join(f'{c}={n}' for c, n in found.items())})",
          flush=True)
    deadline = (time.monotonic() + args.serve_seconds
                if args.serve_seconds else None)
    try:
        while deadline is None or time.monotonic() < deadline:
            time.sleep(min(args.refresh_interval,
                           1.0 if deadline else args.refresh_interval))
            try:
                router.refresh_from_kv(cohorts)
            except Exception:  # noqa: BLE001 — KV blackout: keep serving
                pass
            router.refresh_stats()
    except KeyboardInterrupt:
        pass
    finally:
        router.stop_http()
    return 0


def _print_stats(stats):
    if stats.get("role") == "router":
        print(f"source={stats['source']} accepted={stats['accepted']} "
              f"completed={stats['completed']} "
              f"rerouted={stats['rerouted']} "
              f"rejected={stats['rejected']}")
        for cohort, c in sorted(stats.get("cohorts", {}).items()):
            print(f"  cohort {cohort}: depth={c['queue_depth']} "
                  f"running={c['running']} completed={c['completed']} "
                  f"tokens={c['tokens_out']} "
                  f"members={len(c['members'])}")
    else:
        print(json.dumps(stats, indent=1, sort_keys=True))


def _cmd_stats(args):
    try:
        while True:
            stats = _get_json(args.url, args.token, "/v1/serving/stats")
            if args.json:
                print(json.dumps(stats, indent=1, sort_keys=True))
            else:
                _print_stats(stats)
            if not args.watch:
                return 0
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0
    except (urllib.error.URLError, OSError, ValueError) as e:
        print(f"hvd-serve: stats fetch failed: {e}", file=sys.stderr)
        return 2


def _cmd_drain(args):
    try:
        status, body = _post_json(args.url, args.token,
                                  "/v1/serving/drain",
                                  {"cohort": args.cohort})
    except (urllib.error.URLError, OSError) as e:
        print(f"hvd-serve: drain failed: {e}", file=sys.stderr)
        return 2
    print(json.dumps(body, indent=1, sort_keys=True))
    return 0 if status == 200 else 2


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="hvd-serve",
        description="Serving-plane console client (docs/serving.md)")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("route", help="start a request router")
    p.add_argument("--kv", type=_hostport, required=True,
                   metavar="HOST:PORT",
                   help="launcher KV store the workers registered with")
    p.add_argument("--token", default="", help="job token")
    p.add_argument("--cohorts", default="c0",
                   help="comma-separated cohort names to route")
    p.add_argument("--bind", default="0.0.0.0")
    p.add_argument("--serve-seconds", type=float, default=0,
                   help="exit after this long (0 = run until ^C)")
    p.add_argument("--refresh-interval", type=float, default=2.0)
    p.set_defaults(fn=_cmd_route)

    p = sub.add_parser("stats", help="poll /v1/serving/stats")
    p.add_argument("--url", required=True,
                   help="router or worker base URL")
    p.add_argument("--token", default="")
    p.add_argument("--watch", action="store_true")
    p.add_argument("--interval", type=float, default=2.0)
    p.add_argument("--json", action="store_true",
                   help="raw JSON instead of the summary lines")
    p.set_defaults(fn=_cmd_stats)

    p = sub.add_parser("drain",
                       help="drain a cohort (finish in-flight, "
                            "reject new)")
    p.add_argument("cohort")
    p.add_argument("--url", required=True, help="router base URL")
    p.add_argument("--token", default="")
    p.set_defaults(fn=_cmd_drain)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
