"""Framework exceptions.

Mirrors the exception taxonomy of the reference framework
(reference: horovod/common/exceptions.py) so elastic training loops can be
written the same way: a recoverable collective failure raises
``HorovodInternalError`` and a membership change raises
``HostsUpdatedInterrupt``; both are caught by ``elastic.run``.
"""


# Process exit code a worker uses to request a fresh respawn of its slot
# (elastic exit-restart on the compiled data plane — see elastic.py).
# Defined here so the launcher/driver can import it without dragging the
# jax-importing elastic module into the supervisor process.
RESTART_EXIT_CODE = 79

# Exit code for a graceful preemption hand-off: the worker caught
# SIGTERM, persisted its last commit at a commit boundary, and left.
# The elastic driver treats this as a membership change, NOT a failure
# (no blacklist count) — see docs/fault_tolerance.md.
PREEMPT_EXIT_CODE = 83


class HorovodInternalError(RuntimeError):
    """Internal error raised when a collective routine fails.

    Recoverable via elastic mode: the training loop restores the last
    committed state and re-initializes (reference: horovod/common/elastic.py:151).
    """


class HostsUpdatedInterrupt(Exception):
    """Raised when the set of participating hosts/devices changed.

    In elastic mode the driver notifies workers of host-set changes; the
    worker raises this at the next commit/state-check boundary
    (reference: horovod/common/exceptions.py, horovod/common/elastic.py:57).
    """

    def __init__(self, skip_sync=False):
        super().__init__()
        self.skip_sync = skip_sync


class HorovodVersionMismatchError(ImportError):
    """Library/extension version mismatch (reference: horovod/common/exceptions.py)."""


class NotInitializedError(RuntimeError):
    """An API that requires ``init()`` was called before initialization."""

    def __init__(self, what="Collective operations"):
        super().__init__(
            f"{what} called before init(); call horovod_tpu.init() first.")


class DuplicateNameError(ValueError):
    """Two in-flight tensors share a name within one process set.

    Matches the reference's DUPLICATE_NAME_ERROR surfaced by the tensor queue
    (reference: horovod/common/common.h:229, tensor_queue.cc).
    """


class StalledTensorError(RuntimeError):
    """A named tensor was submitted by some ranks but not all within the stall
    timeout (reference: horovod/common/stall_inspector.cc:26)."""


class CollectiveAbortError(HorovodInternalError):
    """The stuck-collective watchdog aborted every in-flight operation
    after ``HVDTPU_COLLECTIVE_TIMEOUT`` (guardian.py; the enforcement
    analog of the reference's stall inspector + STALL_SHUTDOWN_TIME,
    horovod/common/stall_inspector.cc). The message carries the
    watchdog's diagnostic — which ops stalled and which ranks never
    submitted them. A ``HorovodInternalError`` on purpose: under
    elastic the abort converts into a restore-and-reset instead of an
    eternal hang or a job death."""


class CollectiveMismatchError(RuntimeError):
    """Ranks submitted the same named collective with divergent metadata
    (kind, op, dtype, shapes, process set, or scale factors), detected
    by the pre-dispatch consistency check (``HVDTPU_CONSISTENCY_CHECK``;
    guardian.py — the analog of the reference controller's message-table
    mismatch errors, horovod/common/controller.cc).

    Deliberately NOT a ``HorovodInternalError``: like
    ``SubmissionOrderError``, the divergence is a deterministic program
    bug — the elastic restore/retry loop must surface it instead of
    retrying into the same mismatch forever. ``self.divergences`` holds
    ``(rank, field, theirs, ours)`` tuples."""

    def __init__(self, message, divergences=()):
        super().__init__(message)
        self.divergences = list(divergences)


class CheckpointCorruptError(RuntimeError):
    """A checkpoint file failed its integrity check (truncated payload,
    checksum mismatch, or foreign format) and no intact fallback was
    available (checkpoint.py; docs/fault_tolerance.md)."""


class SubmissionOrderError(RuntimeError):
    """Ranks submitted collectives in divergent orders (or with divergent
    auto-generated names), detected by the opt-in runtime order guard
    (``HOROVOD_TPU_ORDER_CHECK=1``; analysis/order_guard.py). The static
    analog is hvd-lint rule HVD203.

    Deliberately NOT a ``HorovodInternalError``: the divergence is a
    deterministic program bug, so the elastic restore/retry loop (which
    catches internal errors as recoverable) must surface it instead of
    retrying into the same divergence forever."""


class LockOrderError(RuntimeError):
    """hvd-sanitize detected a lock-acquisition-order cycle: acquiring
    this lock while holding another reverses an order recorded earlier
    in the process, so two threads interleaving the two paths can
    deadlock (ABBA). The message carries BOTH acquisition stacks — the
    current one and the first recorded reverse-order one
    (``HVDTPU_SANITIZE``; analysis/sanitizer.py, docs/lint.md).

    Deliberately NOT a ``HorovodInternalError``: like
    ``SubmissionOrderError``, a lock-order inversion is a deterministic
    program bug — elastic retry would deadlock (or trip) again."""


class ChaosInjectedError(RuntimeError):
    """A chaos ``fail`` injection fired at a point with no more specific
    error type (``HVDTPU_CHAOS``; docs/fault_tolerance.md). KV points
    raise transport errors and collective points raise
    ``HorovodInternalError`` instead, so recovery paths see exactly the
    exceptions real faults produce."""


class CollectiveLintError(ValueError):
    """Static analysis (hvd-lint) found error-severity collective hazards
    and ``verify=`` asked for enforcement. ``self.diagnostics`` carries
    the structured findings (analysis/diagnostics.py)."""

    def __init__(self, diagnostics):
        self.diagnostics = list(diagnostics)
        lines = "\n".join(d.format() for d in self.diagnostics)
        super().__init__(
            f"hvd-lint found {len(self.diagnostics)} collective-"
            f"correctness finding(s):\n{lines}")
