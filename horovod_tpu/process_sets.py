"""Process sets: concurrent sub-communicators.

API mirrors the reference (reference: horovod/common/process_sets.py:18-145,
horovod/common/process_set.cc). On TPU a process set maps onto a subset of
the replica mesh: in single-controller mode the "ranks" are virtual ranks
(device indices into the global replica mesh) and each set owns its own
sub-mesh, so collectives on disjoint sets compile into independent XLA
programs over disjoint ICI domains.
"""

import threading

import numpy as np

from .exceptions import NotInitializedError


class ProcessSet:
    """A set of ranks able to run collectives among themselves."""

    process_set_id = None

    def __init__(self, ranks_or_comm):
        self.ranks = sorted(int(r) for r in ranks_or_comm)
        if len(set(self.ranks)) != len(self.ranks):
            raise ValueError("Duplicate ranks in process set")
        self.mesh = None        # sub-mesh, attached on materialization

    def _invalidate(self):
        self.process_set_id = None
        self.mesh = None

    def size(self):
        if self.process_set_id is None:
            return None
        return len(self.ranks)

    def rank(self):
        """This process's rank within the set, or None if not included.

        In single-controller mode the controlling process is a member of
        every set (it owns all virtual ranks) and this returns 0.
        """
        if self.process_set_id is None:
            return None
        from . import basics
        rt = basics.runtime()
        if rt.mode == basics.MODE_SINGLE:
            return 0
        try:
            return self.ranks.index(rt.topology.rank)
        except ValueError:
            return None

    def included(self):
        if self.process_set_id is None:
            return None
        from . import basics
        rt = basics.runtime()
        if rt.mode == basics.MODE_SINGLE:
            return True
        return rt.topology.rank in self.ranks

    def __eq__(self, other):
        return (type(self) == type(other)
                and self.process_set_id == other.process_set_id
                and self.ranks == other.ranks)

    def __hash__(self):
        return hash((self.process_set_id, tuple(self.ranks)))

    def __str__(self):
        return f"ProcessSet(process_set_id={self.process_set_id}, ranks={self.ranks})"


class _ProcessSetTable:
    """Id-indexed registry (reference: horovod/common/process_set.h:89-171)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._by_id = {}
        self._next_id = 0

    def register(self, ps, runtime):
        with self._lock:
            for existing in self._by_id.values():
                if existing.ranks == ps.ranks:
                    raise ValueError(
                        f"A process set with ranks {ps.ranks} already exists "
                        f"(id={existing.process_set_id})")
            ps.process_set_id = self._next_id
            self._next_id += 1
            self._materialize(ps, runtime)
            self._by_id[ps.process_set_id] = ps
            return ps

    def _materialize(self, ps, runtime):
        from . import basics
        world = runtime.size
        for r in ps.ranks:
            if not 0 <= r < world:
                raise ValueError(
                    f"Rank {r} in process set out of range [0, {world})")
        if runtime.mode == basics.MODE_SINGLE:
            sub_devices = [runtime.devices[r] for r in ps.ranks]
            import jax
            ps.mesh = jax.sharding.Mesh(np.array(sub_devices), ("hvd",))
        else:
            ps.mesh = runtime.mesh
        runtime.backend.register_process_set(ps)

    def remove(self, ps, runtime):
        with self._lock:
            if ps.process_set_id is None:
                return
            if ps.process_set_id == 0:
                raise ValueError("Cannot remove the global process set")
            self._by_id.pop(ps.process_set_id, None)
            runtime.backend.remove_process_set(ps)
            ps._invalidate()

    def get(self, set_id):
        with self._lock:
            return self._by_id.get(set_id)

    def all(self):
        with self._lock:
            return list(self._by_id.values())


global_process_set = ProcessSet([])


def _setup(runtime, extra_sets):
    """Materialize the global set and any user sets (called from init;
    reference: horovod/common/process_sets.py:99 _init_process_sets)."""
    table = runtime.process_set_table
    if table is None:
        table = _ProcessSetTable()
        runtime.process_set_table = table
    if global_process_set.process_set_id is None:
        global_process_set.ranks = list(range(runtime.size))
        table.register(global_process_set, runtime)
    for ps in extra_sets:
        if ps.process_set_id is None:
            table.register(ps, runtime)


def add_process_set(process_set):
    """Add a new process set after init (reference:
    horovod/common/process_sets.py:123)."""
    from . import basics
    rt = basics.runtime()
    if not isinstance(process_set, ProcessSet):
        process_set = ProcessSet(process_set)
    return rt.process_set_table.register(process_set, rt)


def remove_process_set(process_set):
    """Remove a process set (reference: horovod/common/process_sets.py:145)."""
    from . import basics
    rt = basics.runtime()
    rt.process_set_table.remove(process_set, rt)
    return True


def process_set_by_id(set_id):
    from . import basics
    ps = basics.runtime().process_set_table.get(set_id)
    if ps is None:
        raise ValueError(f"No process set with id {set_id}")
    return ps


def _teardown(runtime=None):
    """Invalidate every registered set so a later init() re-registers them
    against the fresh runtime (shutdown+init is the elastic reset path,
    reference: horovod/torch/elastic/__init__.py:46-48)."""
    if runtime is not None and runtime.process_set_table is not None:
        for ps in runtime.process_set_table.all():
            ps._invalidate()
    global_process_set._invalidate()
