"""Data-plane guardian: cross-rank consistency checks + stall forensics.

The reference framework refuses to compute garbage or hang silently on
rank divergence: the controller's message table rejects cross-rank
shape/op mismatches at negotiation time (reference:
horovod/common/controller.cc ComputeResponseList error responses) and
the stall inspector names the ranks that never submitted a stuck tensor
(reference: horovod/common/stall_inspector.cc). Our coordinator used to
dispatch whatever the local rank submitted and log a purely local stall
line. This module closes both gaps:

- **ConsistencyGuard** (``HVDTPU_CONSISTENCY_CHECK``): at submit time
  each rank publishes a compact metadata digest — kind, reduce op,
  dtype, flattened shapes, process set, pre/postscale — for every named
  collective to a shared *board*; before dispatch the digests are
  compared and a divergence fails the handle with
  ``CollectiveMismatchError`` naming the divergent ranks and fields,
  instead of hanging in negotiation or silently reducing mismatched
  bytes. ``1`` checks every named collective, ``N>1`` samples every Nth
  submission (same slot on every rank — sequence numbers advance with
  the name stream, which must agree for the program to be correct at
  all).
- **Watchdog** (``HVDTPU_COLLECTIVE_TIMEOUT``): the coordinator's stall
  scan feeds it the in-flight set; it publishes this rank's view,
  fetches the peers', and reports which ranks never submitted each
  stalled op. Past the timeout it drives a coordinated abort — every
  in-flight handle fails with ``CollectiveAbortError`` carrying the
  diagnostic, and an abort notice on the board makes peers abort too.
  Under elastic the abort is a ``HorovodInternalError``, so training
  restores the last commit and resets instead of dying or hanging
  forever.

The board is the launcher's KV store in multi-process runs and a
process-global in-memory table otherwise (threaded multi-rank tests,
the local native transport). Both knobs unset costs nothing: the
coordinator holds ``None`` and the submit path pays one attribute
check (the telemetry/chaos disabled-guard contract).
"""

import json
import time

from .analysis import sanitizer
from .exceptions import CollectiveMismatchError
from .ops import reduce_ops
from .telemetry import core as telemetry
from .utils import envparse
from .utils.logging_util import get_logger

DEFAULT_CONSISTENCY_TIMEOUT_S = 10.0
# Board key prefixes: digests are one key per (name, rank) — overwritten
# each occurrence, so storage stays bounded by the program's name set.
_DIGEST_PREFIX = "dg"
_INFLIGHT_PREFIX = "if"
_ABORT_KEY = "abort"


def _m_mismatches():
    # Resolved at call time (mismatches are terminal events): NULL no-op
    # when HOROVOD_TPU_METRICS is off.
    return telemetry.counter(
        "hvd_collective_mismatch_total",
        "Cross-rank collective metadata mismatches detected")


# ---------------------------------------------------------------------------
# Boards: where digests / in-flight sets / abort notices live
# ---------------------------------------------------------------------------

_INPROC_TABLE = {}
_INPROC_LOCK = sanitizer.make_lock("guardian.inproc")


def _reset_inproc():
    """Test hook: drop the process-global table."""
    with _INPROC_LOCK:
        _INPROC_TABLE.clear()


class InProcBoard:
    """Process-global coordination table for runs where every rank lives
    in this process (threaded tests, the native local transport)."""

    def __init__(self, scope):
        self._scope = scope

    def put(self, key, value):
        with _INPROC_LOCK:
            _INPROC_TABLE[(self._scope, key)] = value

    def get(self, key):
        with _INPROC_LOCK:
            return _INPROC_TABLE.get((self._scope, key))


class KVBoard:
    """Launcher KV store board. Every verb uses a SHORT retry budget:
    the guard is advisory infrastructure — a flaky store must degrade it
    to a warning, never block a dispatch for the full KV deadline or
    kill the job with a transport error."""

    RETRIES = 2
    DEADLINE_S = 3.0

    def __init__(self, addr, port, token, scope):
        self._addr = addr
        self._port = port
        self._token = token
        self._scope = scope
        self._log = get_logger()

    def put(self, key, value):
        from .runner import http_client
        try:
            # Deliberately bounded I/O on the cycle thread (short retry
            # budget above): exempt from the sanitize tripwire.
            with sanitizer.allowed("guardian board put (bounded)"):
                http_client.put_kv(self._addr, self._port, self._scope,
                                   key, value, token=self._token,
                                   retries=self.RETRIES,
                                   deadline=self.DEADLINE_S)
        except Exception as exc:  # noqa: BLE001 — advisory plane
            self._log.warning("guardian: board put %s failed: %s", key,
                              exc)

    def get(self, key):
        from .runner import http_client
        try:
            with sanitizer.allowed("guardian board get (bounded)"):
                raw = http_client.get_kv(self._addr, self._port,
                                         self._scope, key,
                                         token=self._token,
                                         retries=self.RETRIES,
                                         deadline=self.DEADLINE_S)
        except Exception as exc:  # noqa: BLE001 — advisory plane
            self._log.warning("guardian: board get %s failed: %s", key,
                              exc)
            return None
        return raw.decode() if isinstance(raw, bytes) else raw


def _board_scope():
    """One board scope per elastic membership version, so a fresh cohort
    never reads the previous cohort's digests or abort notice."""
    ver = envparse.get_str(envparse.ELASTIC_VERSION, "0")
    return f"guardian.{ver}"


def make_board():
    """KV board when the launcher's rendezvous is configured, the
    in-process table otherwise. Callers coordinating across real
    processes must use ``make_cross_process_board`` — the in-process
    table only reaches ranks living in THIS process (threaded tests,
    the local native transport)."""
    from .runner import rendezvous as rdv
    cfg = rdv.rendezvous_config()
    scope = _board_scope()
    if cfg is None:
        return InProcBoard(scope)
    addr, port, token = cfg
    return KVBoard(addr, port, token, scope)


def make_cross_process_board():
    """KV board, or None when no launcher rendezvous exists (a digest
    published to the in-process table would never reach a peer
    process — worse than no check: every verify would wait out its
    deadline)."""
    from .runner import rendezvous as rdv
    cfg = rdv.rendezvous_config()
    if cfg is None:
        return None
    addr, port, token = cfg
    return KVBoard(addr, port, token, _board_scope())


# ---------------------------------------------------------------------------
# Digests
# ---------------------------------------------------------------------------

# Fields compared across ranks, in reporting order. "codec" is the
# compression plane's selection (codec name + block size): ranks
# disagreeing on it would run DIFFERENT wire pipelines for the same
# named collective — int8 payloads reduced against raw floats — so a
# mismatch must fail fast naming the field, not corrupt numerics.
# "shard_index"/"shard_shape" cover the scatter/gather collective kinds
# (reducescatter, allgather, and the ZeRO plane's zero_reduce_scatter/
# zero_allgather): every rank must hold the shard its index owns, and
# even-split shard shapes must agree — a rank holding the wrong slice
# reassembles a permuted buffer with no arithmetic error to catch it.
# "index_dtype"/"dense_shape" cover the sparse gather plane
# (ops/sparse.py): ranks must agree on the index width and the table
# geometry they scatter-add into — but nnz is per-rank-varying BY
# CONSTRUCTION (each rank touches its own rows), so sparse entries
# publish shapes=None; a naive shape digest would false-abort every
# healthy sparse step.
_DIGEST_FIELDS = ("kind", "op", "dtype", "shapes", "process_set",
                  "prescale", "postscale", "root_rank", "codec",
                  "shard_index", "shard_shape", "index_dtype",
                  "dense_shape")


def _codec_digest(entry):
    codec = getattr(entry, "codec", None)
    if codec is None:
        sparse = getattr(entry, "sparse", None)
        if sparse is not None and sparse.codec:
            # Row-quantized values on the sparse gather path: a rank
            # disagreeing would gather raw floats against int8 payloads.
            return f"{sparse.codec}@rows"
        return None
    if isinstance(codec, tuple):
        name, block = codec
        return f"{name}@b{block}" if block else name
    return str(codec)


def _shard_fields(entry, shapes):
    """(shard_index, shard_shape) for the scatter/gather kinds; (None,
    None) otherwise. shard_index is this rank's slot in the process
    set (verified against the publisher's rank — see compare_digests);
    shard_shape is the per-rank shard for EVEN splits only (uneven
    splits legitimately differ per rank, and entries whose process set
    cannot answer rank() yet are skipped rather than guessed)."""
    if entry.kind not in ("reducescatter", "allgather"):
        return None, None
    if entry.process_set.process_set_id not in (None, 0):
        # Sub-cohort sets: process_set.rank() is the rank WITHIN the
        # set, but verify() keys peers by GLOBAL rank — publishing the
        # set-relative index would false-abort healthy jobs. The shard
        # fields cover the global cohort (and the ZeRO plane, which is
        # global-only by construction).
        return None, None
    try:
        rank = entry.process_set.rank()
    except Exception:  # noqa: BLE001 — pre-init / test stub process set
        return None, None
    if getattr(entry, "uneven", False) or not shapes:
        return rank, None
    if entry.kind == "reducescatter":
        # Stacked (n, s0, ...) input → the reduction's dim 0 is split
        # across ranks; even only when every s0 divides by n.
        n = shapes[0][0] if shapes[0] else 0
        if n <= 0 or any(len(s) < 2 or s[1] % n for s in shapes):
            return rank, None
        return rank, [[s[1] // n] + s[2:] for s in shapes]
    # allgather: each rank contributes its local shard as-is; shapes
    # must agree across ranks for the even (non-`uneven`) form.
    return rank, [list(s) for s in shapes]


def entry_digest(entry):
    """Compact metadata digest of a TensorEntry — everything that must
    agree across ranks for the collective to be well-formed (the analog
    of the reference message table's per-rank request record)."""
    dtype = None
    shapes = []
    index_dtype = dense_shape = None
    sparse = getattr(entry, "sparse", None)
    if sparse is not None:
        # Sparse gather entries: per-rank nnz legitimately differs, so
        # the array shapes are excluded from the digest; what MUST
        # agree is the value dtype, the index dtype, and the dense
        # table shape every rank scatter-adds into.
        dtype = sparse.values_dtype
        shapes = None
        index_dtype = sparse.index_dtype
        dense_shape = [int(s) for s in sparse.dense_shape]
    else:
        for a in entry.arrays:
            if dtype is None and hasattr(a, "dtype"):
                dtype = str(a.dtype)
            shapes.append([int(s) for s in getattr(a, "shape", ())])
    shard_index, shard_shape = _shard_fields(entry, shapes or [])
    return {
        "kind": entry.kind,
        "op": reduce_ops.op_name(entry.op) if entry.op is not None
        else None,
        "dtype": dtype,
        "shapes": shapes,
        "process_set": entry.process_set.process_set_id,
        "prescale": None if entry.prescale is None
        else float(entry.prescale),
        "postscale": None if entry.postscale is None
        else float(entry.postscale),
        "root_rank": entry.root_rank,
        "codec": _codec_digest(entry),
        "shard_index": shard_index,
        "shard_shape": shard_shape,
        "index_dtype": index_dtype,
        "dense_shape": dense_shape,
    }


def render_digest(digest):
    return json.dumps(digest, sort_keys=True, separators=(",", ":"))


def compare_digests(mine, theirs_by_rank):
    """Diff the local digest against each rank's published one. Returns
    ``[(rank, field, theirs, mine), ...]`` — empty when consistent.

    ``shard_index`` is the one per-rank-varying field: a peer's value
    must equal its OWN rank (rank r claiming shard q would reassemble a
    permuted buffer), so it is checked against the publishing rank, not
    against the local value."""
    divergences = []
    for rank in sorted(theirs_by_rank):
        theirs = theirs_by_rank[rank]
        for field in _DIGEST_FIELDS:
            if field == "shard_index":
                peer_index = theirs.get(field)
                if peer_index is not None and peer_index != rank:
                    divergences.append((rank, field, peer_index, rank))
                continue
            if theirs.get(field) != mine.get(field):
                divergences.append((rank, field, theirs.get(field),
                                    mine.get(field)))
    return divergences


class ConsistencyGuard:
    """Publishes digests at submit time, verifies them before dispatch.

    ``every``: 1 checks each named collective; N>1 checks every Nth
    named submission (the sequence counter advances identically on every
    rank of a correct program, so the sampled slots line up)."""

    def __init__(self, rank, size, board, every=1, timeout_s=None,
                 poll_s=0.01):
        self.rank = rank
        self.size = size
        self.board = board
        self.every = max(1, int(every))
        self.timeout_s = (envparse.get_float(
            envparse.CONSISTENCY_TIMEOUT, DEFAULT_CONSISTENCY_TIMEOUT_S)
            if timeout_s is None else timeout_s)
        self._poll_s = poll_s
        self._seq = 0
        self._occ = {}
        self._lock = sanitizer.make_lock("guardian.consistency")
        self._log = get_logger()

    # -- submit side (framework threads) -----------------------------------
    def on_submit(self, entry):
        """Publish this entry's digest; arm ``entry.guard_token`` when
        this submission slot is one the pre-dispatch verify samples."""
        if not entry.name:
            return
        with self._lock:
            self._seq += 1
            seq = self._seq
            occ = self._occ.get(entry.name, 0) + 1
            self._occ[entry.name] = occ
        digest = entry_digest(entry)
        published = (self._perturb(digest) if entry.chaos_mismatch
                     else digest)
        self.board.put(f"{_DIGEST_PREFIX}.{entry.name}.{self.rank}",
                       f"{occ}|{render_digest(published)}")
        if seq % self.every == 0:
            # `digest` is the pre-perturb truth (_perturb copies), so a
            # chaos-corrupted rank still flags ITSELF at verify time.
            entry.guard_token = (entry.name, occ, digest)

    @staticmethod
    def _perturb(digest):
        """Chaos ``collective:mismatch``: publish a digest whose shapes
        (or dtype, for shapeless ops) disagree with what this rank
        actually submitted — peers AND this rank's own verify flag it."""
        digest = dict(digest)
        if digest["shapes"]:
            digest["shapes"] = [[s + 1 for s in shape] or [1]
                                for shape in digest["shapes"]]
        else:
            digest["dtype"] = "chaos-corrupted"
        return digest

    # -- dispatch side (coordinator cycle thread) --------------------------
    def verify(self, entry):
        """Compare every rank's published digest for this entry against
        the local truth. Raises ``CollectiveMismatchError`` on
        divergence; unreported peers within the deadline degrade to a
        warning (the stall watchdog owns missing-submission detection)."""
        name, occ, mine = entry.guard_token
        deadline = time.monotonic() + self.timeout_s
        waiting = set(range(self.size))
        theirs_by_rank = {}
        ahead = set()
        while waiting:
            for rank in sorted(waiting):
                raw = self.board.get(f"{_DIGEST_PREFIX}.{name}.{rank}")
                if raw is None:
                    continue
                peer_occ, _, blob = raw.partition("|")
                try:
                    peer_occ = int(peer_occ)
                except ValueError:
                    theirs_by_rank[rank] = {"malformed": blob}
                    waiting.discard(rank)
                    continue
                if peer_occ < occ:
                    continue  # peer still on an earlier occurrence
                if peer_occ > occ:
                    # The per-(name, rank) key was already overwritten
                    # by a later occurrence; comparing would flag a
                    # healthy program whose shapes legitimately vary
                    # per step. Occurrence k is gone — skip this peer.
                    ahead.add(rank)
                    waiting.discard(rank)
                    continue
                try:
                    theirs_by_rank[rank] = json.loads(blob)
                except ValueError:
                    theirs_by_rank[rank] = {"malformed": blob}
                waiting.discard(rank)
            if not waiting or time.monotonic() > deadline:
                break
            time.sleep(self._poll_s)
        if waiting or ahead:
            reasons = []
            if waiting:
                reasons.append(f"rank(s) {sorted(waiting)} published no "
                               f"digest within {self.timeout_s:.1f}s")
            if ahead:
                reasons.append(f"rank(s) {sorted(ahead)} already "
                               "overwrote this occurrence")
            self._log.warning(
                "guardian: consistency check for %r (occurrence %d) "
                "skipped some peers: %s (if a rank never submits, the "
                "stall watchdog will name it)",
                name, occ, "; ".join(reasons))
        divergences = compare_digests(mine, theirs_by_rank)
        if not divergences:
            return
        _m_mismatches().inc()
        lines = [
            f"  rank {rank}: {field} = {theirs!r} (rank {self.rank} "
            f"submitted {ours!r})"
            for rank, field, theirs, ours in divergences]
        ranks = sorted({d[0] for d in divergences})
        fields = sorted({d[1] for d in divergences})
        raise CollectiveMismatchError(
            f"collective {name!r} (occurrence {occ}) was submitted with "
            f"divergent metadata by rank(s) {ranks} "
            f"(fields: {', '.join(fields)}):\n" + "\n".join(lines) +
            "\nEvery rank must submit the same op/dtype/shapes for a "
            "named collective (reference: message-table mismatch, "
            "horovod/common/controller.cc). Run `hvd-lint` on the "
            "training script (docs/lint.md).", divergences=divergences)


# ---------------------------------------------------------------------------
# Stuck-collective watchdog
# ---------------------------------------------------------------------------

class Watchdog:
    """Cluster view + abort policy for stalled collectives. The
    coordinator's stall scan calls ``observe`` with its in-flight set;
    this publishes the local view, reads the peers', and answers (a)
    which ranks never submitted each stalled op and (b) whether the
    abort threshold is crossed (locally, or because a peer already
    aborted)."""

    def __init__(self, rank, size, timeout_s, board=None):
        self.rank = rank
        self.size = size
        self.timeout_s = timeout_s
        self.board = board
        self.last_missing = {}
        self._log = get_logger()

    def observe(self, inflight_names, stalled, now):
        """``stalled``: [(name, age_s)]. Returns (missing, peer_abort):
        ``missing`` maps each stalled name to the ranks whose published
        in-flight view lacks it; ``peer_abort`` is a peer's abort
        diagnostic when one already fired.

        The local view is published on EVERY call — including scans
        with nothing stalled — so peers never diagnose against a stale
        snapshot from before this rank's latest submissions; the peer
        fetch only happens when something is actually stalled here."""
        if self.board is None:
            return {}, None
        self.board.put(f"{_INFLIGHT_PREFIX}.{self.rank}",
                       ";".join(sorted(inflight_names)))
        if not stalled:
            return {}, None
        peer_view = {}
        unreported = []
        for rank in range(self.size):
            if rank == self.rank:
                peer_view[rank] = set(inflight_names)
                continue
            raw = self.board.get(f"{_INFLIGHT_PREFIX}.{rank}")
            if raw is None:
                unreported.append(rank)
                peer_view[rank] = None
            else:
                peer_view[rank] = {n for n in raw.split(";") if n}
        missing = {}
        for name, _age in stalled:
            absent = [r for r, names in peer_view.items()
                      if names is not None and name not in names]
            if absent or unreported:
                missing[name] = sorted(absent) + [f"{r}?" for r in
                                                  unreported]
        self.last_missing = missing
        if missing:
            # Flight-recorder breadcrumb: the postmortem bundle shows
            # what this rank believed about its peers BEFORE the abort.
            from . import tracing
            for name, ranks in sorted(missing.items()):
                tracing.trace_event("guardian", "stall_observe",
                                    coll=name,
                                    missing=[str(r) for r in ranks])
        return missing, self.board.get(_ABORT_KEY)

    def should_abort(self, oldest_age):
        return self.timeout_s > 0 and oldest_age > self.timeout_s

    def post_abort(self, diagnostic):
        from . import tracing
        tracing.trace_event("guardian", "post_abort",
                            detail=str(diagnostic)[:200])
        if self.board is not None:
            self.board.put(_ABORT_KEY, diagnostic)

    def describe_missing(self, name):
        """Human-readable missing-rank note for ``name`` from the last
        observation (feeds stall logs and Handle.wait timeouts)."""
        ranks = self.last_missing.get(name)
        if not ranks:
            return ""
        note = " — never submitted by rank(s) " + ", ".join(
            str(r) for r in ranks)
        if any(str(r).endswith("?") for r in ranks):
            note += " ('?' = no report yet)"
        return note


# ---------------------------------------------------------------------------
# Factories (called by the coordinator; None = feature off, zero cost)
# ---------------------------------------------------------------------------

def make_guard(runtime):
    """ConsistencyGuard when HVDTPU_CONSISTENCY_CHECK is set and there
    is more than one process-rank to compare; otherwise None."""
    every = envparse.get_int(envparse.CONSISTENCY_CHECK, 0)
    if every <= 0:
        return None
    if (getattr(runtime, "mode", None) != "spmd"
            or runtime.topology.size < 2):
        # Single-controller mode: one submitter owns every virtual rank,
        # so there is no cross-rank metadata to disagree about.
        return None
    board = make_cross_process_board()
    if board is None:
        get_logger().warning(
            "HVDTPU_CONSISTENCY_CHECK is set but no launcher rendezvous "
            "is configured (HVDTPU_RENDEZVOUS_ADDR/PORT) — the digests "
            "have nowhere to meet; the consistency check stays off")
        return None
    return ConsistencyGuard(runtime.topology.rank, runtime.topology.size,
                            board, every=every)


def make_watchdog(runtime):
    """Watchdog when HVDTPU_COLLECTIVE_TIMEOUT > 0; the cluster board
    rides along only in multi-process mode."""
    timeout_s = envparse.get_float(envparse.COLLECTIVE_TIMEOUT, 0.0)
    if timeout_s <= 0:
        return None
    board = None
    if (getattr(runtime, "mode", None) == "spmd"
            and runtime.topology.size > 1):
        # None without a rendezvous: the watchdog still aborts locally,
        # it just cannot gather the peers' in-flight views.
        board = make_cross_process_board()
    return Watchdog(runtime.topology.rank, runtime.topology.size,
                    timeout_s, board=board)
