"""The fleet arbiter: the control loop over ledger, policy, and
actuators.

One ``tick`` = read serving stats, step the in-flight lease (at most
one), else ask the policy for a new transfer. Every transition is
**ledger-before-actuation**: the new state is written durably
(journal + term fence via the backend) first, then the chaos
``transfer`` point fires, then the idempotent actuation runs — so a
crash anywhere in that sandwich is recoverable from the ledger alone.
``resume`` is the recovery half: a freshly-promoted standby's arbiter
finds the in-flight lease, rolls a ``proposed`` lease back (nothing
was actuated) and rolls anything later forward by re-issuing the
current state's actuation verbatim.

Transfer state machines (docs/fault_tolerance.md "Fleet arbitration"):

- ``train_to_serve``: proposed -> preempting (shrink the training
  target; the training driver delivers graceful SIGTERM preemption at
  the next commit boundary, victims exit 83) -> resharding (the
  shrunk cohort resumes via the planner-emitted reshard program — no
  lost steps, moments bit-exact) -> activating (grow the serving
  target; freed slots join through router/rendezvous) -> complete.
- ``serve_to_train``: proposed -> draining (per-worker drain flags;
  accepted requests finish) -> returning (shrink serving, grow
  training back through the same planner leg) -> complete.
"""

import threading
import time

from . import ledger as ledger_mod
from . import metrics as _m
from .policy import FleetPolicy, fleet_knobs
from ..chaos import inject as _chaos_inject
from ..serving.autoscale import scale_knobs
from ..utils.logging_util import get_logger


class FleetArbiter:
    """Composes a LeaseLedger, actuators, probes, and a FleetPolicy
    into the chip-budget control loop."""

    def __init__(self, ledger, actuators, probes, *, policy=None,
                 train_slots=None, serve_slots=None, stats_fn=None,
                 train_idle_fn=None, drain_timeout=None, tick_s=None):
        self.ledger = ledger
        self.act = actuators
        self.probes = probes
        self.policy = policy if policy is not None else FleetPolicy()
        self.stats_fn = stats_fn or probes.cohort_stats
        self.train_idle_fn = train_idle_fn
        self.drain_timeout = (drain_timeout
                              if drain_timeout is not None
                              else scale_knobs()["drain_timeout"])
        self.tick_s = (tick_s if tick_s is not None
                       else fleet_knobs()["tick_s"])
        self.log = get_logger()
        self._stop = threading.Event()
        self._thread = None
        split = self.ledger.split()
        if split is None:
            if train_slots is None or serve_slots is None:
                raise ValueError(
                    "no recorded split and no initial "
                    "train_slots/serve_slots given")
            split = {"train": int(train_slots),
                     "serve": int(serve_slots), "leased": 0}
            self.ledger.set_split(**split)
        self.split = split
        self._gauge_split()

    # -- recovery ----------------------------------------------------------
    def resume(self):
        """Adopt an in-flight lease left by a previous arbiter (e.g.
        before a standby promotion). Returns the action taken:
        None / 'rollback' / 'roll_forward'."""
        lease = self.ledger.active()
        if lease is None:
            return None
        action = ledger_mod.resume_action(lease)
        if action == "rollback":
            self._finish(lease, "rolled_back")
            self.log.warning(
                "fleet arbiter: lease %s recovered at 'proposed' — "
                "nothing was actuated; rolled back", lease["id"])
        elif action == "roll_forward":
            self.log.warning(
                "fleet arbiter: lease %s recovered at %r — re-issuing "
                "its actuation and rolling forward", lease["id"],
                lease["state"])
            self._reissue(lease)
        return action

    def _reissue(self, lease):
        """Re-run the current state's entry actuation. Safe because
        every actuation is an idempotent desired-state write."""
        state = lease["state"]
        if state == "preempting":
            for wid in lease["wids"]:
                self.ledger.mark_transfer(wid, lease["id"])
            self.act.set_train_slots(lease["train_slots"])
        elif state == "resharding":
            self.act.set_train_slots(lease["train_slots"])
        elif state == "activating":
            self.act.set_serve_slots(lease["serve_slots"])
        elif state == "draining":
            for wid in lease["wids"]:
                self.act.drain(wid)
        elif state == "returning":
            self.act.set_serve_slots(lease["serve_slots"])
            self.act.set_train_slots(lease["train_slots"])

    # -- the control loop --------------------------------------------------
    def tick(self, now=None):
        """One arbiter step. Returns the in-flight lease (possibly
        just finished) or None when idle."""
        now = time.time() if now is None else now
        lease = self.ledger.active()
        if lease is not None:
            _m.lease_age_seconds().set(
                max(0.0, now - lease["created"]))
            return self._step(lease, now)
        _m.lease_age_seconds().set(0.0)
        cohorts = self.stats_fn()
        train_idle = bool(self.train_idle_fn()) \
            if self.train_idle_fn else False
        decision = self.policy.decide(
            self.split, cohorts, self.split.get("leased", 0), now,
            train_idle=train_idle)
        if decision is None:
            return None
        return self._begin(decision, now)

    def _begin(self, decision, now):
        self.log.warning("fleet arbiter: proposing %s of %d slot(s) "
                         "(%s)", decision.direction, decision.slots,
                         decision.reason)
        lease = self.ledger.open(decision.direction, decision.slots,
                                 now=now)
        self.policy.note_transfer(now)
        _chaos_inject("transfer", name="proposed",
                      kind=lease["direction"])
        return self._step(lease, now)

    def _advance(self, lease, state, now, **fields):
        """Ledger write, then chaos point, then the caller actuates —
        the one ordering everything else here relies on."""
        lease = self.ledger.advance(lease, state, now=now, **fields)
        _chaos_inject("transfer", name=state,
                      kind=lease["direction"])
        return lease

    def _step(self, lease, now):
        if lease["direction"] == ledger_mod.TRAIN_TO_SERVE:
            return self._step_surge(lease, now)
        return self._step_ebb(lease, now)

    def _step_surge(self, lease, now):
        state = lease["state"]
        if state == "proposed":
            t, m, s = (self.split["train"], self.split["serve"],
                       lease["slots"])
            victims = self.act.pick_train_victims(t, t - s)
            for wid in victims:
                self.ledger.mark_transfer(wid, lease["id"])
            lease = self._advance(lease, "preempting", now,
                                  wids=victims, train_slots=t - s,
                                  serve_slots=m + s)
            self.act.set_train_slots(t - s)
        elif state == "preempting":
            if self.probes.train_victims_gone(lease["wids"]):
                lease = self._advance(lease, "resharding", now)
        elif state == "resharding":
            if self.probes.train_size() == lease["train_slots"]:
                lease = self._advance(lease, "activating", now)
                self.act.set_serve_slots(lease["serve_slots"])
        elif state == "activating":
            if self.probes.serve_size() >= lease["serve_slots"]:
                lease = self._finish(lease, "complete", now)
        return lease

    def _step_ebb(self, lease, now):
        state = lease["state"]
        if state == "proposed":
            t, m, s = (self.split["train"], self.split["serve"],
                       lease["slots"])
            victims = self.act.pick_serve_victims(m, m - s)
            lease = self._advance(lease, "draining", now,
                                  wids=victims, train_slots=t + s,
                                  serve_slots=m - s)
            for wid in victims:
                self.act.drain(wid)
        elif state == "draining":
            drained = self.probes.serve_drained(lease["wids"])
            timed_out = now - lease["updated"] > self.drain_timeout
            if drained or timed_out:
                if timed_out and not drained:
                    self.log.warning(
                        "fleet arbiter: lease %s drain timed out "
                        "after %.0fs; returning slots anyway",
                        lease["id"], self.drain_timeout)
                lease = self._advance(lease, "returning", now)
                self.act.set_serve_slots(lease["serve_slots"])
                self.act.set_train_slots(lease["train_slots"])
        elif state == "returning":
            if self.probes.train_size() == lease["train_slots"]:
                lease = self._finish(lease, "complete", now)
        return lease

    def _finish(self, lease, outcome, now=None):
        lease = self.ledger.advance(lease, outcome, now=now)
        if outcome == "complete":
            delta = lease["slots"]
            if lease["direction"] == ledger_mod.TRAIN_TO_SERVE:
                leased = self.split.get("leased", 0) + delta
            else:
                leased = max(0, self.split.get("leased", 0) - delta)
            self.split = {"train": lease["train_slots"],
                          "serve": lease["serve_slots"],
                          "leased": leased}
            self.ledger.set_split(**self.split)
            self._gauge_split()
        for wid in lease.get("wids", ()):
            self.ledger.clear_transfer(wid)
        _m.transfers_total(lease["direction"], outcome).inc()
        self.log.warning("fleet arbiter: lease %s %s (split now "
                         "train=%d serve=%d leased=%d)", lease["id"],
                         outcome, self.split["train"],
                         self.split["serve"],
                         self.split.get("leased", 0))
        return lease

    def _gauge_split(self):
        _m.train_slots().set(self.split["train"])
        _m.serve_slots().set(self.split["serve"])

    # -- threaded mode ------------------------------------------------------
    def start(self):
        self._thread = threading.Thread(
            target=self._loop, name="fleet-arbiter", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)

    def _loop(self):
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — the loop must not die silently
                self.log.exception(
                    "fleet arbiter: tick failed; arbiter stopped "
                    "(the ledger holds the in-flight lease for "
                    "resume)")
                return
            self._stop.wait(self.tick_s)
