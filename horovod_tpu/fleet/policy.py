"""The arbiter's decision core: pressure signals in, Decision out.

Pure policy — no KV, no processes, no clocks of its own (the caller
passes ``now``), so the whole surge/ebb behaviour is unit-testable
with synthetic stats. Two signals feed one window-smoothed breach
counter, mirroring the serving autoscaler (serving/autoscale.py):

- **queue pressure**: total queued + running at or above
  ``HVDTPU_SERVING_SCALE_UP_DEPTH``;
- **p99 SLO breach**: worst per-cohort p99 end-to-end latency at or
  above ``HVDTPU_SERVING_SLO_P99`` (the slow-but-not-queued overload
  a depth trigger misses).

``window`` consecutive breached observations (one, when training
reports idle — an idle donor makes lending cheap) propose a
train->serve lease of one slot; ``HVDTPU_FLEET_EBB_IDLE_S`` of calm
with leased slots outstanding proposes the serve->train ebb.
``HVDTPU_FLEET_COOLDOWN`` spaces transfers in either direction so an
oscillating load cannot thrash the reshard machinery, and the
``HVDTPU_FLEET_MIN_*_SLOTS`` floors are never crossed.
"""

import collections

from ..serving.autoscale import scale_knobs
from ..utils import envparse

Decision = collections.namedtuple("Decision",
                                  ["direction", "slots", "reason"])


def fleet_knobs():
    return {
        "min_train_slots": envparse.get_int(
            envparse.FLEET_MIN_TRAIN_SLOTS, 1),
        "min_serve_slots": envparse.get_int(
            envparse.FLEET_MIN_SERVE_SLOTS, 1),
        "window": envparse.get_int(envparse.FLEET_WINDOW, 3),
        "cooldown_s": envparse.get_float(envparse.FLEET_COOLDOWN,
                                         30.0),
        "ebb_idle_s": envparse.get_float(envparse.FLEET_EBB_IDLE_S,
                                         60.0),
        "tick_s": envparse.get_float(envparse.FLEET_TICK_S, 1.0),
    }


class FleetPolicy:
    """Stateful smoothing around a stateless decision rule."""

    def __init__(self, *, min_train_slots=None, min_serve_slots=None,
                 window=None, cooldown_s=None, ebb_idle_s=None,
                 scale_up_depth=None, slo_p99=None):
        knobs = fleet_knobs()
        serving = scale_knobs()

        def pick(value, default):
            return default if value is None else value

        self.min_train_slots = pick(min_train_slots,
                                    knobs["min_train_slots"])
        self.min_serve_slots = pick(min_serve_slots,
                                    knobs["min_serve_slots"])
        self.window = int(pick(window, knobs["window"]))
        self.cooldown_s = float(pick(cooldown_s, knobs["cooldown_s"]))
        self.ebb_idle_s = float(pick(ebb_idle_s, knobs["ebb_idle_s"]))
        self.scale_up_depth = pick(scale_up_depth,
                                   serving["scale_up_depth"])
        self.slo_p99 = pick(slo_p99, serving["slo_p99"])
        self._streak = 0
        self._calm_since = None
        self._last_transfer = float("-inf")

    @staticmethod
    def pressure(cohorts):
        return sum(int(s.get("queue_depth", 0)) + int(s.get("running",
                                                            0))
                   for s in cohorts.values())

    @staticmethod
    def worst_p99(cohorts):
        return max((float(s.get("p99_latency") or 0.0)
                    for s in cohorts.values()), default=0.0)

    def note_transfer(self, now):
        """The arbiter opened a lease — start the cooldown."""
        self._last_transfer = now
        self._streak = 0
        self._calm_since = None

    def decide(self, split, cohorts, leased_out, now, *,
               train_idle=False):
        """One observation. ``split`` is ``{"train": n, "serve": n}``;
        ``cohorts`` the serving stats map; ``leased_out`` how many
        slots train->serve leases currently hold. Returns a Decision
        or None."""
        total = self.pressure(cohorts)
        p99 = self.worst_p99(cohorts)
        slo_breach = self.slo_p99 > 0 and p99 >= self.slo_p99
        pressured = total >= self.scale_up_depth or slo_breach
        if pressured:
            self._streak += 1
            self._calm_since = None
        else:
            self._streak = 0
            if self._calm_since is None:
                self._calm_since = now
        if now - self._last_transfer < self.cooldown_s:
            return None
        # -- surge: take a slot from training -----------------------------
        need = self.window if not train_idle else 1
        if (self._streak >= need
                and split["train"] - 1 >= self.min_train_slots):
            reason = (f"p99 {p99:.3f}s >= SLO {self.slo_p99:.3f}s"
                      if slo_breach and total < self.scale_up_depth
                      else f"pressure {total} >= {self.scale_up_depth}")
            if train_idle:
                reason += " (training idle)"
            return Decision(direction="train_to_serve", slots=1,
                            reason=reason)
        # -- ebb: return a leased slot to training ------------------------
        if (leased_out > 0 and self._calm_since is not None
                and now - self._calm_since >= self.ebb_idle_s
                and split["serve"] - 1 >= self.min_serve_slots):
            return Decision(
                direction="serve_to_train", slots=1,
                reason=(f"serving calm {now - self._calm_since:.0f}s "
                        f"with {leased_out} leased slot(s) out"))
        return None
