"""horovod_tpu.fleet: one chip budget, two planes.

The chip-budget arbiter owns a fixed slot budget split between the
training cohort and the serving cohort and rebalances it from live
pressure signals: serving queue depth + p99 SLO breaches pull chips
*out of* training (graceful preemption at the next commit boundary,
planner-driven reshard, zero lost steps), and a calm serving plane
ebbs leased chips back (drain-first, zero dropped accepted requests).

Every rebalance is a journaled **lease transfer**: the lease record
lands in the driver journal's durable ``fleet`` KV scope *before* any
actuation it authorises, term-fenced like every other control-plane
mutation, so a standby promotion mid-transfer resumes or rolls the
transfer back deterministically (docs/fault_tolerance.md "Fleet
arbitration").

Modules:

- ``ledger``    — the lease ledger: records, state machine, backends
- ``policy``    — the pure decision core (pressure in, Decision out)
- ``actuators`` — the only module outside the drivers allowed to
  mutate cohorts (HVD212 enforces this)
- ``arbiter``   — the control loop composing the three
- ``metrics``   — telemetry families (``hvd_fleet_*``)
- ``cli``       — the ``hvd-fleet`` operator tool
"""

__all__ = ["actuators", "arbiter", "cli", "ledger", "metrics",
           "policy"]
