"""The fleet lease ledger: durable, term-fenced transfer records.

A **lease** is the unit of chip movement between the training and
serving planes: ``{id, direction, slots, state, wids, ...}`` stored in
the KV plane's durable ``fleet`` scope (journal.DURABLE_SCOPES), which
means every write is journaled *before* it is acknowledged and
replicated to warm standbys. The arbiter's contract is
**ledger-before-actuation**: a state transition is written here first
and only then actuated, so the journal always bounds what can have
happened — a promoted standby reading ``proposed`` knows nothing was
actuated yet (roll back); any later state means actuation may have
started, and because every actuation is an idempotent desired-state
write (target files, drain flags, transfer markers) it can simply be
re-issued (roll forward). ``resume_action`` encodes exactly that rule.

Alongside leases the scope carries:

- ``active``          — the id of the (single) in-flight lease
- ``split``           — the current train/serve slot split
- ``transfer.<wid>``  — per-victim markers the training driver reads
  to account a graceful preemption to ``cause=arbiter_transfer``
  instead of a cloud notice (runner/elastic_driver.py).
"""

import json
import time

# The lease state machine lives in the protocol spec
# (spec-is-implementation — analysis/protocol/lease_spec.py is the
# module the hvd-model checker explores, and this module executes the
# exact same chain/validation/resume functions;
# tests/test_protocol_model.py asserts the delegation by identity).
# This file owns everything impure: backends, journaled writes, terms.
from ..analysis.protocol.lease_spec import (
    CHAINS,
    DIRECTIONS,
    SERVE_TO_TRAIN,
    TERMINAL_STATES,
    TRAIN_TO_SERVE,
    LeaseStateError,
    check_transition,
    next_state,
    resume_action,
)

#: The durable KV scope (runner/journal.py DURABLE_SCOPES).
SCOPE = "fleet"
ACTIVE_KEY = "active"
SPLIT_KEY = "split"
LEASE_PREFIX = "lease."
TRANSFER_PREFIX = "transfer."

# Compatibility alias: the validator predates the spec split and was
# module-private here.
_check_transition = check_transition


# --------------------------------------------------------------------------
# Backends: where the durable writes go
# --------------------------------------------------------------------------

class MemoryBackend:
    """Dict-backed ledger storage for unit tests and the CPU bench
    stand-in — same interface, no durability."""

    def __init__(self):
        self.data = {}

    def put(self, key, value):
        self.data[key] = value

    def get(self, key):
        return self.data.get(key)

    def delete(self, key):
        self.data.pop(key, None)


class DriverBackend:
    """Ledger storage colocated with the (primary) elastic driver:
    journal-record first (fsync'd), then apply to the live KV store
    stamped with the driver's term. This is the same
    journal-before-apply discipline the driver uses for membership
    (elastic_driver._jrec) — an in-process write must journal
    explicitly because only *HTTP* mutations are journaled by the
    handler."""

    def __init__(self, server, journal=None, term_fn=None):
        self.server = server
        self.journal = journal
        self.term_fn = term_fn or (lambda: None)

    def put(self, key, value):
        if self.journal is not None:
            self.journal.record("kv_put", scope=SCOPE, key=key,
                                value=value)
        self.server.put(SCOPE, key, value, term=self.term_fn())

    def get(self, key):
        value = self.server.get(SCOPE, key)
        if value is None:
            return None
        return value if isinstance(value, str) else value.decode()

    def delete(self, key):
        if self.journal is not None:
            self.journal.record("kv_delete", scope=SCOPE, key=key)
        self.server.delete(SCOPE, key, term=self.term_fn())


class HttpBackend:
    """Ledger storage over the runner KV HTTP routes — for an arbiter
    running outside the driver process. Durability is free: the HTTP
    handler journals every ``fleet``-scope mutation
    (journal.durable_key) and fences stale terms server-side."""

    def __init__(self, addr, port, token=""):
        self.addr, self.port, self.token = addr, int(port), token

    def put(self, key, value):
        from ..runner import http_client
        http_client.put_kv(self.addr, self.port, SCOPE, key, value,
                           token=self.token)

    def get(self, key):
        from ..runner import http_client
        value = http_client.get_kv(self.addr, self.port, SCOPE, key,
                                   token=self.token)
        if value is None:
            return None
        return value if isinstance(value, str) else value.decode()

    def delete(self, key):
        from ..runner import http_client
        http_client.delete_kv(self.addr, self.port, SCOPE, key,
                              token=self.token)


# --------------------------------------------------------------------------
# The ledger
# --------------------------------------------------------------------------

class LeaseLedger:
    """Typed access to the ``fleet`` scope over any backend. All
    mutations go through here so the write ordering (lease before
    marker before actuation) lives in one place."""

    def __init__(self, backend):
        self.backend = backend
        self._seq = 0

    # -- leases ------------------------------------------------------------
    def open(self, direction, slots, now=None):
        """Create a new lease in ``proposed`` and mark it active.
        Exactly one lease may be in flight."""
        if direction not in DIRECTIONS:
            raise LeaseStateError(f"unknown direction {direction!r}")
        if self.active() is not None:
            raise LeaseStateError(
                "a lease is already in flight; the arbiter moves one "
                "lease at a time")
        now = time.time() if now is None else now
        self._seq += 1
        lease = {
            "id": f"{direction}-{int(now)}-{self._seq}",
            "direction": direction,
            "slots": int(slots),
            "state": "proposed",
            "wids": [],
            "created": now,
            "updated": now,
        }
        self._write(lease)
        self.backend.put(ACTIVE_KEY, lease["id"])
        return lease

    def advance(self, lease, state, now=None, **fields):
        """Validated transition, written durably BEFORE the caller
        actuates it. Returns the updated lease dict."""
        _check_transition(lease, state)
        lease = dict(lease)
        lease.update(fields)
        lease["state"] = state
        lease["updated"] = time.time() if now is None else now
        self._write(lease)
        if state in TERMINAL_STATES:
            self.backend.delete(ACTIVE_KEY)
        return lease

    def get(self, lease_id):
        raw = self.backend.get(LEASE_PREFIX + lease_id)
        return json.loads(raw) if raw else None

    def active(self):
        lease_id = self.backend.get(ACTIVE_KEY)
        if not lease_id:
            return None
        return self.get(lease_id.strip())

    def _write(self, lease):
        self.backend.put(LEASE_PREFIX + lease["id"],
                         json.dumps(lease, sort_keys=True))

    # -- the split ---------------------------------------------------------
    def split(self):
        """``{"train": n, "serve": m, "leased": k}`` — the current
        slot split plus how many serving slots are held under
        train->serve leases (the ebb ceiling)."""
        raw = self.backend.get(SPLIT_KEY)
        if not raw:
            return None
        split = json.loads(raw)
        split.setdefault("leased", 0)
        return split

    def set_split(self, train, serve, leased=0):
        self.backend.put(SPLIT_KEY, json.dumps(
            {"train": int(train), "serve": int(serve),
             "leased": int(leased)}, sort_keys=True))

    # -- per-victim transfer markers ----------------------------------------
    def mark_transfer(self, wid, lease_id):
        """Claim ``wid`` for a lease BEFORE the shrink that preempts
        it — the training driver reads this marker at exit-sweep time
        to account the hand-off as ``cause=arbiter_transfer``."""
        self.backend.put(TRANSFER_PREFIX + wid, lease_id)

    def transfer_of(self, wid):
        value = self.backend.get(TRANSFER_PREFIX + wid)
        return value.strip() if value else None

    def clear_transfer(self, wid):
        self.backend.delete(TRANSFER_PREFIX + wid)
